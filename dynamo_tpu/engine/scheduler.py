"""Continuous-batching scheduler: admission, chunked prefill, decode slots.

The reference's scheduling lives inside vLLM; this is the native equivalent,
shaped for XLA's compilation model: each device step is either one *prefill*
batch (a few sequences' next prompt chunks, padded to a token bucket) or one
*decode* batch (every running sequence advances one token, padded to a batch
bucket).  Keeping the two phases separate keeps shapes regular → a handful of
compiled programs total.

Admission is blocks-aware: a sequence is only admitted when the KV manager
can allocate its prompt blocks (minus prefix-cache hits).  Decode growth
allocates one block at a time; if the pool is exhausted a victim sequence is
preempted back to the waiting queue (its blocks freed — recomputed later,
matching the reference engines' recompute-style preemption).  Victims are
chosen QoS-aware: ``batch``-priority rows first (they signed up to be the
degradation buffer — llm/qos.py), youngest first within a class, so one
tenant's burst can never preempt another tenant's interactive rows while
batch rows are available.

The waiting queue is a weighted-fair queue (``WfqQueue``) keyed on tenant
identity, not a FIFO: under overload one flooding tenant's backlog cannot
crowd admission away from others — each backlogged tenant drains in
proportion to its configured weight (EngineConfig ``qos.tenant_weights``),
with a provable starvation bound (see WfqQueue).  Single-tenant traffic
degenerates to exact FIFO, so the pre-QoS behaviour is unchanged.
"""

from __future__ import annotations

import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..llm.protocols import PreprocessedRequest
from ..llm.qos import BATCH, INTERACTIVE, normalize_priority
from ..tokens import TokenBlockSequence
from .config import EngineConfig
from .kv_manager import KvBlockManager


@dataclass
class SequenceState:
    """Everything the engine tracks per in-flight request."""

    request_id: str
    prompt: List[int]
    block_seq: TokenBlockSequence  # hashes prompt+output as blocks complete
    sampling_temperature: float = 0.0
    sampling_top_k: int = 0
    sampling_top_p: float = 1.0
    sampling_seed: int = 0  # per-request rng stream (engine fills default)
    freq_penalty: float = 0.0
    pres_penalty: float = 0.0
    # None = no logprobs; 0 = chosen-token only; N = chosen + top-N
    logprobs: Optional[int] = None
    max_new_tokens: Optional[int] = None
    min_new_tokens: Optional[int] = None
    stop_token_ids: frozenset = frozenset()
    ignore_eos: bool = False

    output: List[int] = field(default_factory=list)
    # Reference-held prefix blocks (sp-prefill / host-restore sealed them
    # just before admission): keeps the reuse-pool LRU from evicting the
    # work between sealing and allocate_sequence.  Released by the
    # scheduler once admission lands (or the request leaves the queue).
    pin_ids: Optional[List[int]] = None
    # A sampled token for this row is in flight device→host (the engine's
    # deferred first-token fetch): the scheduler must not plan the row
    # until the engine harvests it (engine.py _harvest_pending).
    awaiting_fetch: bool = False
    # Live-migration freeze (engine/migrate.py): the sequence keeps its KV
    # blocks and queue but is never planned, never a preemption victim, and
    # blocks no one — the brief final-delta window of a migration, ended by
    # cutover (finish_migrated) or rollback (unfreeze_sequence).
    frozen: bool = False
    # Original request prompt length.  Preemption folds generated tokens into
    # ``prompt`` for recompute, so stop checks and usage must count output as
    # total_tokens - orig_prompt_len, never len(output).
    orig_prompt_len: int = 0
    block_ids: List[int] = field(default_factory=list)
    num_computed: int = 0  # tokens whose KV is resident
    num_cached_prompt: int = 0  # prefix-cache hit length (metrics)
    finished: bool = False
    # blocks sealed (hash-published) so far — index into block_seq.blocks
    num_sealed_blocks: int = 0
    # Queue-entry timestamp (time.perf_counter): admission latency =
    # admit time - this.  The dominant TTFT-tail term at saturation is a
    # newcomer waiting out a fused pure-decode session (r5 stall
    # diagnosis); admission_waits records it per request.
    enqueue_t: float = 0.0
    # --- speculative decoding (engine/spec.py) ---
    # Per-request opt-out (sampling_options.spec_decode=false via nvext).
    spec_enabled: bool = True
    # Adaptive draft length: -1 = unresolved (controller seeds it from
    # SpecDecodeConfig.k on first use).  Survives preemption — acceptance
    # history is a property of the traffic, not of the KV residency.
    spec_k: int = -1
    # EWMA of per-dispatch acceptance (accepted/drafted).
    spec_ewma: float = 1.0
    # Proposer bench: no drafts until num_output_tokens reaches this
    # (-1 = not benched).
    spec_bench_until: int = -1
    # Miss backoff: matching is skipped until total_tokens reaches this
    # (exponential in consecutive misses, capped) so non-repetitive
    # traffic stops paying the n-gram scan almost immediately.
    spec_next_try: int = 0
    spec_miss: int = 0
    # --- multi-tenancy (llm/tenancy) ---
    # Tenant salt mixed into the chained block hashes (tokens.py): equal
    # token streams from different adapters never share KV — engine
    # sealing, host offload, transfer plane and kv_router all key on the
    # salted hashes, so one field isolates every tier.
    kv_salt: Optional[str] = None
    # LoRA adapter (None = base model) + its resident device-bank slot.
    adapter: Optional[str] = None
    adapter_slot: int = -1
    # Registry ref dropped (engine _finish is reachable from several paths;
    # the flag makes the release idempotent).
    adapter_released: bool = False
    # Grammar constraint: TokenMaskAutomaton + the sequence's current
    # state, advanced host-side per ACCEPTED token.  Constrained rows are
    # excluded from the fused multi-step decode programs (the mask must be
    # rebuilt between tokens, and fused steps feed tokens forward on
    # device) — they advance through single unified steps instead.
    grammar: Any = None
    grammar_state: int = 0
    # --- QoS (llm/qos.py) ---
    # Fairness identity for the WFQ waiting queue: explicit annotation, the
    # LoRA adapter, or the served model name — "" means the shared default
    # tenant (single-tenant traffic collapses to FIFO).
    tenant: str = ""
    # interactive (default, protected) | batch (first preemption victim,
    # shed first under brownout).  Threaded from nvext.priority via
    # PreprocessedRequest.priority.
    priority: str = INTERACTIVE
    # --- distributed tracing (runtime/tracing.py) ---
    # SeqTrace (context + timing anchors + first-token latch) for sampled
    # requests, parsed from ``annotations.trace`` at engine admission; None
    # = untraced (the zero-cost path — every engine instrumentation point
    # is behind this check).  The CONTEXT travels in the migration snapshot
    # (SequenceSnapshot.trace) so a migrated stream stays one trace.
    trace: Any = None

    def __post_init__(self) -> None:
        if self.orig_prompt_len == 0:
            self.orig_prompt_len = len(self.prompt)

    @property
    def total_tokens(self) -> int:
        return len(self.prompt) + len(self.output)

    @property
    def num_output_tokens(self) -> int:
        """Generated tokens across preemptions (see orig_prompt_len)."""
        return self.total_tokens - self.orig_prompt_len

    @property
    def in_prefill(self) -> bool:
        # The final prompt token's forward pass produces the first output
        # token, so prefill is done once num_computed == len(prompt).
        return self.num_computed < len(self.prompt)

    @classmethod
    def from_request(
        cls, request_id: str, pre: PreprocessedRequest, cfg: EngineConfig
    ) -> "SequenceState":
        samp, stop = pre.sampling_options, pre.stop_conditions
        # Live-migration resume (llm/migration): the prompt is the original
        # prompt PLUS every token already emitted elsewhere; orig_prompt_len
        # restores the rng-stream position (sampler steps count from it) and
        # the stop/usage accounting, so the continued stream is
        # token-identical to the never-migrated run.
        resume = pre.annotations.get("resume") or {}
        orig_len = 0
        if isinstance(resume, dict):
            try:
                v = int(resume.get("orig_prompt_len", 0))
            except (TypeError, ValueError):
                v = 0
            if 0 < v <= len(pre.token_ids):
                orig_len = v
        # Tenant identity (llm/tenancy): the salt roots the block-hash
        # chain, so it must be fixed before the first block seals.
        kv_salt = pre.annotations.get("kv_salt") or None
        if kv_salt is not None and not isinstance(kv_salt, str):
            kv_salt = str(kv_salt)
        seq = cls(
            request_id=request_id,
            prompt=list(pre.token_ids),
            block_seq=TokenBlockSequence(block_size=cfg.block_size, salt=kv_salt),
            kv_salt=kv_salt,
            sampling_temperature=samp.temperature or 0.0,
            sampling_top_k=samp.top_k or 0,
            sampling_top_p=samp.top_p if samp.top_p is not None else 1.0,
            sampling_seed=(
                # Masked to uint32 either way: a user seed of -1 or 2**64
                # must not blow up the numpy cast in _sampling_arrays.
                samp.seed & 0xFFFFFFFF
                if samp.seed is not None
                # Engine-assigned deterministic default: stable per request
                # id (crc32 — not Python's salted hash), so replays
                # reproduce without a global stream.
                else (zlib.crc32(request_id.encode()) ^ cfg.seed) & 0xFFFFFFFF
            ),
            freq_penalty=samp.frequency_penalty or 0.0,
            pres_penalty=samp.presence_penalty or 0.0,
            logprobs=getattr(samp, "logprobs", None),
            max_new_tokens=stop.max_tokens,
            min_new_tokens=stop.min_tokens,
            stop_token_ids=frozenset(stop.stop_token_ids or ()),
            ignore_eos=bool(stop.ignore_eos),
            spec_enabled=getattr(samp, "spec_decode", None) is not False,
            orig_prompt_len=orig_len,
            # QoS identity (llm/qos.py): tenant keys the WFQ waiting queue,
            # priority picks the class band.  Both default benign — absent
            # fields reproduce the pre-QoS scheduler exactly.
            tenant=str(
                pre.annotations.get("tenant")
                or pre.annotations.get("adapter")
                or pre.model
                or ""
            ),
            priority=normalize_priority(
                pre.priority
                if pre.priority is not None
                else pre.annotations.get("priority")
            ),
        )
        spec = resume.get("spec") if isinstance(resume, dict) else None
        if isinstance(spec, dict):
            # Speculation controller state travels with the sequence — the
            # acceptance history is a property of the traffic, not of which
            # worker holds the KV (same rationale as surviving preemption).
            seq.spec_k = int(spec.get("k", seq.spec_k))
            seq.spec_ewma = float(spec.get("ewma", seq.spec_ewma))
            seq.spec_bench_until = int(spec.get("bench_until", seq.spec_bench_until))
            seq.spec_next_try = int(spec.get("next_try", seq.spec_next_try))
            seq.spec_miss = int(spec.get("miss", seq.spec_miss))
        return seq


class WfqQueue:
    """Weighted fair queue over (priority class, tenant) with FIFO per flow.

    Classic virtual-finish-time WFQ: each arriving sequence is stamped
    ``vft = max(V, last_vft[flow]) + cost / weight`` where ``V`` is the
    queue's virtual time (advanced to the departing head's vft on every
    pop), ``cost`` is the request's worst-case token work (prompt +
    generation budget) and ``weight`` the tenant's configured share.  The
    head is always the minimum-vft entry, so each backlogged tenant drains
    work in proportion to its weight regardless of arrival order or burst
    size.

    **Starvation bound** (the fairness contract tests assert): a backlogged
    tenant of weight ``w`` with head cost ``c`` is admitted after at most
    ``(W/w)·c`` token-work units of other tenants' admissions, where ``W``
    is the total weight of backlogged tenants — its head's vft is at most
    ``V + c/w``, and every competing admission advances ``V`` by at least
    ``cost/W``.  No request waits forever while the queue drains.

    **Priority classes**: interactive flows are served before batch flows,
    EXCEPT that after ``batch_every`` consecutive interactive admissions
    with batch backlogged, one batch admission is forced — so batch is
    starved by at most ``batch_every`` admissions, never indefinitely.

    **Urgent lane**: ``appendleft`` (preemption requeue) bypasses WFQ —
    a preempted sequence already earned its admission and re-enters first,
    preserving the pre-QoS recompute semantics.

    Single tenant + single class degenerates to exact FIFO (vft is
    monotone per flow), so existing single-tenant behaviour is unchanged.
    Duck-types the deque surface the scheduler/engine/migration layers use:
    ``[0]``, ``popleft``, ``append``, ``appendleft``, ``remove``, ``in``,
    ``len``, truthiness, iteration, ``clear``.
    """

    def __init__(
        self,
        tenant_weights: Optional[Dict[str, float]] = None,
        default_weight: float = 1.0,
        batch_every: int = 4,
    ):
        self.tenant_weights = dict(tenant_weights or {})
        self.default_weight = max(default_weight, 1e-9)
        self.batch_every = max(1, int(batch_every))
        self._urgent: Deque[SequenceState] = deque()
        # flow = (priority, tenant) → FIFO of seqs; vft rides on the seq.
        self._flows: Dict[Tuple[str, str], Deque[SequenceState]] = {}
        self._last_vft: Dict[Tuple[str, str], float] = {}
        self._vt = 0.0
        self._since_batch = 0

    # -- helpers -----------------------------------------------------------

    def _weight(self, tenant: str) -> float:
        return max(float(self.tenant_weights.get(tenant, self.default_weight)), 1e-9)

    @staticmethod
    def _cost(seq: SequenceState) -> float:
        # Worst-case token work: prompt prefill + generation budget.  add()
        # trims max_new_tokens before enqueue, so the budget is always set.
        return float(max(1, len(seq.prompt) + (seq.max_new_tokens or 0)))

    def _flow_head(self, priority: str) -> Optional[SequenceState]:
        """Min-vft head among ``priority``-class flows (tenant name breaks
        ties deterministically)."""
        best: Optional[SequenceState] = None
        best_key: Optional[Tuple[float, str]] = None
        for (prio, tenant), q in self._flows.items():
            if prio != priority or not q:
                continue
            key = (q[0]._wfq_vft, tenant)
            if best_key is None or key < best_key:
                best, best_key = q[0], key
        return best

    def _select(self) -> Optional[SequenceState]:
        """The next sequence WFQ would admit (pure — no counter updates)."""
        if self._urgent:
            return self._urgent[0]
        interactive = self._flow_head(INTERACTIVE)
        batch = self._flow_head(BATCH)
        if interactive is None:
            return batch
        if batch is not None and self._since_batch >= self.batch_every:
            return batch  # anti-starvation: batch head jumps the class gap
        return interactive

    # -- deque surface -----------------------------------------------------

    def append(self, seq: SequenceState) -> None:
        flow = (seq.priority, seq.tenant)
        vft = max(self._vt, self._last_vft.get(flow, 0.0)) + self._cost(
            seq
        ) / self._weight(seq.tenant)
        seq._wfq_vft = vft
        self._last_vft[flow] = vft
        self._flows.setdefault(flow, deque()).append(seq)

    def appendleft(self, seq: SequenceState) -> None:
        self._urgent.appendleft(seq)

    def popleft(self) -> SequenceState:
        seq = self._select()
        if seq is None:
            raise IndexError("pop from an empty WfqQueue")
        self._remove_entry(seq)
        # Virtual time advances to the ADMITTED head's finish time — the
        # WFQ invariant that keeps newly arriving flows from replaying
        # history.  Only real admissions advance it: a cancellation deep
        # in a backlogged flow (remove()) must not jump V to that flow's
        # far-future finish time, or every later arrival from OTHER
        # tenants would be stamped behind the whole backlog — exactly the
        # starvation WFQ exists to prevent.  Same for the batch counter:
        # only admissions count toward the anti-starvation window.
        self._vt = max(self._vt, getattr(seq, "_wfq_vft", self._vt))
        if seq.priority == BATCH:
            self._since_batch = 0
        elif self._flow_head(BATCH) is not None:
            self._since_batch += 1
        return seq

    def _remove_entry(self, seq: SequenceState) -> None:
        if seq in self._urgent:
            self._urgent.remove(seq)
            return
        flow = (seq.priority, seq.tenant)
        q = self._flows.get(flow)
        if q is None or seq not in q:
            raise ValueError("sequence not in WfqQueue")
        q.remove(seq)
        if not q:
            # Prune the flow's virtual-time memory with its queue: tenant
            # ids are wire-controlled, so _last_vft must not grow without
            # bound as tenants churn — and a flow whose whole backlog was
            # CANCELLED must not keep the cancelled tail's far-future
            # finish time as a penalty on its next genuine request.  (An
            # admission-drained flow's last_vft is <= the advanced V, so
            # deletion is a no-op semantically.)
            del self._flows[flow]
            self._last_vft.pop(flow, None)
        elif getattr(seq, "_wfq_vft", None) == self._last_vft.get(flow):
            # Cancelled the flow's TAIL: roll last_vft back to the new
            # tail (per-flow vfts are FIFO-monotone) so later arrivals
            # are not stamped behind cancelled, never-served work.
            self._last_vft[flow] = q[-1]._wfq_vft

    def remove(self, seq: SequenceState) -> None:
        """Drop a cancelled/aborted entry WITHOUT advancing virtual time
        or the batch admission counter (see popleft)."""
        self._remove_entry(seq)

    def clear(self) -> None:
        self._urgent.clear()
        self._flows.clear()
        self._last_vft.clear()
        self._since_batch = 0

    def __getitem__(self, index: int) -> SequenceState:
        if index != 0:
            raise IndexError("WfqQueue only exposes its head ([0])")
        seq = self._select()
        if seq is None:
            raise IndexError("WfqQueue is empty")
        return seq

    def __contains__(self, seq: SequenceState) -> bool:
        return seq in self._urgent or any(
            seq in q for q in self._flows.values()
        )

    def __len__(self) -> int:
        return len(self._urgent) + sum(len(q) for q in self._flows.values())

    def __bool__(self) -> bool:
        return len(self._urgent) > 0 or any(self._flows.values())

    def __iter__(self):
        yield from self._urgent
        for q in self._flows.values():
            yield from q


class RowSlots:
    """Row-slot free list for the continuous fused decode pipeline
    (engine/pipeline.py _decode_pipeline).

    The fused multi-step decode program is dispatched over ``max_batch``
    device rows; under static membership row ``i`` simply was ``members[i]``
    and any change drained the whole pipeline.  Continuous batching instead
    keeps a persistent slot map: retiring a finished row frees its slot
    (after the in-flight-write barrier — the retired sequence's KV blocks
    are released only once every dispatched chunk that could write them has
    been harvested), and a newly admitted sequence takes a free slot at the
    next chain-break merge.  The per-row ``pos0``/``tables``/``limits``/
    sampling arrays are all indexed by these slots.

    Retired slots pass through a PENDING state (``retire`` → barrier →
    ``free``) so a slot is never handed to a newcomer while an in-flight
    chunk could still write the old row's blocks; ``capacity_left`` counts
    pending slots as available because admission decisions happen strictly
    before the merge that would reuse them (by which point every barrier
    has passed).
    """

    def __init__(self, size: int):
        self.size = size
        self.rows: List[Optional[SequenceState]] = [None] * size
        # Pop from the end → lowest index first (matches the legacy
        # members-list row order, keeping device row assignment stable for
        # trace comparisons).
        self._free: List[int] = list(range(size - 1, -1, -1))
        self._pending: set = set()  # retired, awaiting the write barrier

    def assign(self, seq: SequenceState) -> int:
        i = self._free.pop()
        self.rows[i] = seq
        return i

    def retire(self, i: int) -> None:
        """Row finished/cancelled: excluded from future dispatches now,
        reusable only after ``free(i)`` (the caller's write barrier)."""
        self.rows[i] = None
        self._pending.add(i)

    def free(self, i: int) -> None:
        self._pending.discard(i)
        self._free.append(i)

    def active(self) -> List[Tuple[int, SequenceState]]:
        return [(i, s) for i, s in enumerate(self.rows) if s is not None]

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.rows if s is not None)

    @property
    def capacity_left(self) -> int:
        return len(self._free) + len(self._pending)


@dataclass
class StepPlan:
    """One unified device step: per-row (state, start, n_tokens).

    Decode rows have n_tokens == 1; prefill rows carry their next prompt
    chunk.  ``pure_decode`` marks a steady state (every running sequence is
    decoding, nothing waiting) where the engine can switch to the fused
    multi-step decode pipeline instead of single unified steps.
    """

    items: List[Tuple[SequenceState, int, int]]
    pure_decode: bool = False


class Scheduler:
    def __init__(self, cfg: EngineConfig, kv: KvBlockManager):
        self.cfg = cfg
        self.kv = kv
        self.waiting: WfqQueue = WfqQueue(
            tenant_weights=cfg.qos.tenant_weights,
            default_weight=cfg.qos.default_weight,
            batch_every=cfg.qos.batch_every,
        )
        self.running: List[SequenceState] = []
        self.rejected: List[SequenceState] = []  # can never fit; engine fails them
        self.preempted = 0  # cumulative, for metrics
        # Cumulative mid-prefill requeues (preemption of a sequence whose
        # prompt was only partially computed).  The engine compares this
        # against its last-seen value each scheduling pass and resets the
        # mixed-phase chunk cadence (_chunks_since_burst): the requeued
        # sequence restarts chunking from zero, so a stale count would
        # skew the first decode burst after re-admission.
        self.prefill_requeues = 0
        # Queue->admission latencies (s), bounded; loadgen reads per level.
        self.admission_waits: Deque[float] = deque(maxlen=16384)

    # ------------------------------------------------------------------ entry
    def add(self, seq: SequenceState) -> None:
        # Trim the generation budget to the context limit rather than reject;
        # over-long prompts are rejected by the engine before reaching us.
        # The budget counts from the ORIGINAL prompt (orig_prompt_len ==
        # len(prompt) for fresh requests): a migrated resume folds emitted
        # tokens into the prompt, and trimming against the folded length
        # would silently shrink the remaining budget by the emitted count.
        room = self.cfg.max_model_len - seq.orig_prompt_len
        if seq.max_new_tokens is None or seq.max_new_tokens > room:
            seq.max_new_tokens = room
        seq.enqueue_t = time.perf_counter()
        self.waiting.append(seq)

    def _record_admission(self, seq: SequenceState) -> None:
        """Shared admission bookkeeping: the queue→admission latency sample
        plus, for traced requests, the ``engine.queue_wait`` span — the
        dominant TTFT-tail term at saturation (a newcomer waiting out a
        fused pure-decode session) finally attributable per request."""
        now = time.perf_counter()
        if seq.enqueue_t:
            self.admission_waits.append(now - seq.enqueue_t)
        st = seq.trace
        if st is not None:
            from ..runtime.tracing import collector as trace_collector

            st.t_admit = now
            trace_collector.record(
                st.ctx, "engine.queue_wait", "engine",
                seq.enqueue_t or now, now,
                attrs={"request_id": seq.request_id},
            )

    def remove(self, seq: SequenceState) -> None:
        """Drop a sequence (finished or cancelled) and release its blocks."""
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.waiting:
            self.waiting.remove(seq)
        if seq.block_ids:
            self.kv.free_sequence(seq.block_ids)
            seq.block_ids = []
        self._release_pin(seq)

    def _release_pin(self, seq: SequenceState) -> None:
        if seq.pin_ids:
            self.kv.free_sequence(seq.pin_ids)
            seq.pin_ids = None

    # --------------------------------------------------------------- planning
    def schedule(self) -> Optional[StepPlan]:
        """Plan the next unified device step: decode tokens FIRST (every
        decoding sequence advances — no ITL starvation behind prefills), then
        prompt chunks fill the remaining token budget (chunked prefill mixed
        into the same step, vLLM-chunked-prefill style).  Returns None when
        nothing is runnable."""
        budget = self.cfg.prefill_chunk
        items: List[Tuple[SequenceState, int, int]] = []

        # Decode rows: one token per running decoded sequence.  On block
        # exhaustion preempt the YOUNGEST BATCH-class sequence if any (QoS:
        # batch rows are the degradation buffer, llm/qos.py), else the
        # youngest overall (vLLM recompute policy: protect older requests'
        # progress) and retry.  Victims must come from sequences NOT yet
        # scheduled this step: preempting one already in ``items`` would
        # leave a stale row whose blocks were freed (block_ids=[]) and
        # crash _build_ragged downstream.
        scheduled: set = set()
        for seq in [
            s
            for s in self.running
            if not s.in_prefill
            and not s.finished
            and not s.awaiting_fetch
            and not s.frozen
        ]:
            if seq not in self.running:
                continue  # preempted as a victim below
            ok = self._ensure_slot(seq)
            while not ok:
                # Rows parked on an in-flight token fetch are not victims:
                # preempting one would fold/rewind state the engine's
                # harvest is about to append a token to.  Frozen rows are
                # not victims either: preemption frees exactly the KV
                # blocks a migration is transferring.
                victims = [
                    s
                    for s in self.running
                    if s is not seq
                    and id(s) not in scheduled
                    and not s.awaiting_fetch
                    and not s.frozen
                ]
                if not victims:
                    break
                batch_victims = [s for s in victims if s.priority == BATCH]
                self._preempt((batch_victims or victims)[-1])
                ok = self._ensure_slot(seq)
            if not ok:
                # No unscheduled victim left: self-preempt and recompute later.
                self._preempt(seq)
                continue
            items.append((seq, seq.num_computed, 1))
            scheduled.add(id(seq))
            # Decode rows do NOT consume the prefill budget: the unified
            # step is sized for prefill_chunk + max_batch tokens
            # (config.max_step_tokens), so a full decode batch must never
            # starve prompt chunks — with max_batch > prefill_chunk it
            # would permanently block admission at saturation.

        # Prefill continuations (chunked prefill of already-running prompts).
        for seq in self.running:
            if budget <= 0 or len(items) >= self.cfg.max_batch:
                break
            if seq.in_prefill and not seq.finished and not seq.frozen:
                chunk = min(budget, len(seq.prompt) - seq.num_computed)
                items.append((seq, seq.num_computed, chunk))
                budget -= chunk

        # Admit newcomers while slots + blocks + budget allow.  Track
        # whether the waiting head is BLOCKED (slots/blocks full): waiting
        # requests that cannot land must not hold the fused decode pipeline
        # off — that inverts throughput exactly at saturation (conc 32 below
        # conc 16 in round 3), when the queue is never empty.
        admission_blocked = (
            bool(self.waiting) and len(self.running) >= self.cfg.max_batch
        )
        while budget > 0 and self.waiting and len(items) < self.cfg.max_batch:
            if len(self.running) >= self.cfg.max_batch:
                admission_blocked = True
                break
            seq = self.waiting[0]
            if seq.frozen:
                # A preempted sequence frozen mid-migration must not be
                # admitted and recomputed — a sampled token the snapshot
                # lacks would reach the client twice after the splice.
                # Freezes are sub-second; treat the head as blocked.
                admission_blocked = True
                break
            if not self._try_admit(seq):
                own_pins = len(seq.pin_ids or [])
                if (
                    not self.running
                    and self.kv.active_blocks <= own_pins
                    and not self._pressure_reserve()
                ):
                    # Pool entirely free (apart from this request's OWN
                    # pre-admission pin) and it still doesn't fit: this
                    # request can never run — reject instead of deadlocking.
                    # (Not under an armed kv_pressure squeeze: that pool is
                    # SYNTHETICALLY small and the right behaviour is to
                    # stall until the fault clears, exactly like waiting
                    # out a real tenant's HBM.)
                    self.waiting.popleft()
                    self._release_pin(seq)
                    self.rejected.append(seq)
                    continue
                admission_blocked = True
                break
            self.waiting.popleft()
            self.running.append(seq)
            self._record_admission(seq)
            # Admission always leaves >= 1 prompt token to compute (a fully
            # cached prompt still recomputes its last token for logits).
            chunk = min(budget, len(seq.prompt) - seq.num_computed)
            items.append((seq, seq.num_computed, chunk))
            budget -= chunk

        if not items:
            return None
        pure = (
            (not self.waiting or admission_blocked)
            and all(n == 1 for _, _, n in items)
            and not any(
                s.in_prefill and not s.frozen for s in self.running
            )
            # Grammar-constrained rows bar the fused multi-step programs:
            # their token mask advances host-side per accepted token, and a
            # fused chunk feeds sampled tokens forward ON DEVICE.  The
            # engine's mixed-phase path still bursts the unconstrained rows
            # (engine.py _run_loop).
            and not any(
                s.grammar is not None and not s.finished and not s.frozen
                for s in self.running
            )
        )
        return StepPlan(items, pure_decode=pure)

    def admission_ready(self) -> bool:
        """Non-destructive check: would the waiting head admit right now?
        The fused decode pipeline polls this between chunks — it keeps
        fusing while admission is impossible (slots/blocks full) and drains
        for a rebuild the moment a newcomer could actually land."""
        if not self.waiting:
            return False
        if len(self.running) >= self.cfg.max_batch:
            return False
        seq = self.waiting[0]
        if seq.frozen:
            return False  # mid-migration: schedule() will not admit it
        prompt_blocks = (len(seq.prompt) + self.cfg.block_size) // self.cfg.block_size
        reserve = self._pressure_reserve()
        if reserve and prompt_blocks + reserve > self.kv.free_blocks:
            return False  # squeezed pool: the head cannot land right now
        if prompt_blocks <= self.kv.free_blocks:
            return True  # fits even with zero prefix hits: skip the hashing
        # The fused pipeline polls this twice per chunk at saturation; the
        # prompt is immutable while waiting, so hash it once per sequence
        # (invalidate on preemption, which folds output into the prompt).
        cached = getattr(seq, "_admit_hash_cache", None)
        if cached is None or cached[0] != len(seq.prompt):
            from ..tokens import hash_token_blocks

            cached = (
                len(seq.prompt),
                hash_token_blocks(seq.prompt, self.cfg.block_size, seq.kv_salt),
            )
            seq._admit_hash_cache = cached
        return self.kv.would_fit(cached[1], prompt_blocks)

    def waiting_head_compatible(self) -> bool:
        """Can the waiting head join a running fused decode session via
        in-loop admission (engine/pipeline.py)?  Grammar-constrained rows
        cannot — their logit mask advances host-side per accepted token
        while fused chunks feed tokens forward on device — and frozen
        (mid-migration) heads must not be admitted at all.  An
        incompatible-but-admissible head is the one remaining reason the
        continuous pipeline drains for a full scheduler rebuild."""
        if not self.waiting:
            return False
        seq = self.waiting[0]
        return not seq.frozen and seq.grammar is None

    def admit_continuous(self, limit: int) -> List[SequenceState]:
        """In-loop admission for the continuous fused decode pipeline: pop
        and admit up to ``limit`` compatible waiting heads (same WFQ order,
        same ``_try_admit`` block accounting and admission-wait metrics as
        ``schedule()``'s admission loop — only the call site differs).
        Stops at the first head that is incompatible (the pipeline drains
        for it), frozen, or doesn't fit; never rejects (the never-fits
        reject path needs an EMPTY engine to be provable, and mid-pipeline
        the batch is running)."""
        admitted: List[SequenceState] = []
        while (
            limit > 0
            and self.waiting
            and len(self.running) < self.cfg.max_batch
        ):
            seq = self.waiting[0]
            if seq.frozen or seq.grammar is not None:
                break
            if not self._try_admit(seq):
                break
            self.waiting.popleft()
            self.running.append(seq)
            self._record_admission(seq)
            admitted.append(seq)
            limit -= 1
        return admitted

    def _pressure_reserve(self) -> int:
        """Blocks withheld from ADMISSION by the ``kv_pressure`` fault point
        (chaos ladder): a squeezed pool stalls newcomers — queue depth and
        TTFT rise exactly as they would when real tenants hold the HBM —
        without destabilizing already-running sequences."""
        from ..runtime.faultinject import faults

        if not faults.enabled:
            return 0
        level = faults.level_for("kv_pressure")
        if level <= 0:
            return 0
        return int(self.kv.num_blocks * min(level, 1.0))

    def _try_admit(self, seq: SequenceState) -> bool:
        """Allocate prompt blocks (sharing any cached prefix)."""
        prompt_blocks = (len(seq.prompt) + self.cfg.block_size) // self.cfg.block_size
        # ^ +1 slack block so the first decode token always has a slot.
        reserve = self._pressure_reserve()
        if reserve and prompt_blocks + reserve > self.kv.free_blocks:
            return False  # kv_pressure fault: pool squeezed, head waits
        seq.block_seq.extend(seq.prompt)
        alloc = self.kv.allocate_sequence(seq.block_seq.blocks, prompt_blocks)
        if alloc is None:
            seq.block_seq = TokenBlockSequence(
                block_size=self.cfg.block_size, salt=seq.kv_salt
            )
            return False
        seq.block_ids, cached_tokens = alloc
        # Admission holds its own references now; the pre-admission pin
        # (sp-prefill / host-restore) has done its job.
        self._release_pin(seq)
        # A fully-cached prompt must still recompute its last token to get
        # logits for sampling the first output token.
        if cached_tokens >= len(seq.prompt):
            cached_tokens = len(seq.prompt) - 1
        seq.num_computed = cached_tokens
        seq.num_cached_prompt = cached_tokens
        seq.num_sealed_blocks = cached_tokens // self.cfg.block_size
        return True

    def _ensure_slot(self, seq: SequenceState, lookahead: int = 1) -> bool:
        """Allocate KV blocks so ``lookahead`` tokens past num_computed have
        slots (the decode pipeline asks for its whole in-flight window; the
        device-side `limits` guard keeps steps past the allocation from
        writing)."""
        needed_blocks = min(
            (seq.num_computed + lookahead + self.cfg.block_size - 1)
            // self.cfg.block_size,
            self.cfg.max_blocks_per_seq,
        )
        while len(seq.block_ids) < needed_blocks:
            bid = self.kv.allocate_block()
            if bid is None:
                return False
            seq.block_ids.append(bid)
        return True

    def _preempt(self, seq: SequenceState) -> None:
        """Recompute-style preemption: free blocks, rewind to waiting."""
        self.running.remove(seq)
        self.kv.free_sequence(seq.block_ids)
        seq.block_ids = []
        # Mid-prefill must be detected BEFORE the fold below: folding sets
        # num_computed = 0, after which EVERY preempted sequence looks
        # mid-prefill.
        if seq.in_prefill:
            self.prefill_requeues += 1
        # Fold generated tokens into the prompt so recompute resumes exactly.
        seq.prompt = seq.prompt + seq.output
        seq.output = []
        seq.num_computed = 0
        seq.num_sealed_blocks = 0
        seq.block_seq = TokenBlockSequence(
            block_size=self.cfg.block_size, salt=seq.kv_salt
        )
        # Wait-since-preemption: without this reset, re-admission would
        # record the span since the ORIGINAL enqueue — including time the
        # request spent RUNNING — inflating admission_waits exactly in the
        # KV-pressure regime the metric exists to attribute.
        seq.enqueue_t = time.perf_counter()
        self.waiting.appendleft(seq)
        self.preempted += 1

    def take_rejected(self) -> List[SequenceState]:
        out, self.rejected = self.rejected, []
        return out

    # ---------------------------------------------------------------- metrics
    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)
