"""Object-store KV tier: the fourth, fleet-durable rung of the hierarchy
(HBM → host → disk → object store).

Reference direction: Mooncake's disaggregated KVCache pool and the
CacheGen durable-prefix argument (PAPERS.md) — the first three tiers die
with the worker process, so every scale-from-zero replica pays full
prefill for prefixes the fleet computed thousands of times.  This tier
decouples prefix lifetime from worker lifetime: hot chains are persisted
into a shared object layout that a brand-new worker re-indexes at boot and
restores from (object → host → HBM), turning cold-start prefill into a
prefix-cache hit.

Local-FS-backed object layout (an S3/GCS client would slot behind the
same interface): objects live under two-level fan-out directories
(``{hash>>56:02x}/{hash:016x}.obj``) so a fleet's worth of prefixes never
piles a million files into one directory.  Writes are multipart-style and
atomic: the payload streams into a ``*.tmp`` staging file in bounded
parts (``part_bytes`` per write syscall — the shape an object store's
multipart upload API takes), then one ``os.replace`` publishes the
object; readers never observe a torn object and a crash mid-upload leaves
only a staging file that re-index deletes.

Integrity: the envelope carries the SAME CRC-32 stamp minted at host
offload (engine/integrity.py) — demotion parses and RE-VERIFIES the disk
envelope before re-wrapping it, so disk rot is refused at ingest instead
of laundered into a durable object the whole fleet would trust; reads
verify again before any promotion, and a corrupt object is deleted +
quarantined (recompute, never a wrong scatter) per the PR 13 contract.

GC is byte-budgeted and batched, not per-put: puts may transiently
overshoot ``capacity_bytes``; ``gc()`` then evicts coldest-first down to
the low watermark.  Batching matters here because this tier is SHARED
ACROSS WORKER LIFETIMES — an eviction is fleet-visible, so the store
prefers a few large GC sweeps (observable, countable) over a constant
trickle interleaved with every demotion.

Thread-safety mirrors DiskKvStore: one internal lock around mutation
(callers run under ``asyncio.to_thread``), a tiny separate lock for the
transition records the engine drains on the event loop, and lock-free
GIL-atomic membership reads for hot-path callers.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from .disk_cache import _np_dtype

logger = logging.getLogger(__name__)

_MAGIC = b"DOBJ1\n"
_HLEN = struct.Struct("<I")


def parse_object_blob(
    blob: bytes,
    expected_shape=None,
    expected_dtype=None,
    magic: bytes = _MAGIC,
) -> Optional[Tuple[np.ndarray, Optional[int]]]:
    """Validate one self-describing KV envelope (magic + JSON header
    {dtype, shape, checksum} + payload) byte-for-byte; None on ANY
    structural or checksum failure — the inject_blocks contract: a bad
    object is a miss, never a crash or a wrong scatter.  ``magic`` lets
    the demotion path parse the disk tier's ``.kvblk`` envelope with the
    same validator before re-wrapping it."""
    from .integrity import bytes_checksum

    if not blob.startswith(magic) or len(blob) < len(magic) + _HLEN.size:
        return None
    off = len(magic)
    (hlen,) = _HLEN.unpack_from(blob, off)
    off += _HLEN.size
    if len(blob) < off + hlen:
        return None
    try:
        header = json.loads(blob[off : off + hlen])
        dt = _np_dtype(header["dtype"])
        shape = tuple(int(s) for s in header["shape"])
        checksum = header.get("checksum")
        checksum = None if checksum is None else int(checksum)
    except (ValueError, KeyError, TypeError):
        return None
    off += hlen
    if len(blob) - off != int(np.prod(shape)) * dt.itemsize:
        return None  # truncated/padded payload
    if expected_shape is not None and shape != tuple(expected_shape):
        return None
    if expected_dtype is not None and dt != np.dtype(expected_dtype):
        return None
    if checksum is not None and bytes_checksum(blob[off:]) != checksum:
        return None  # payload bit-rot: structural checks passed, CRC not
    return np.frombuffer(blob, dtype=dt, offset=off).reshape(shape), checksum


class ObjectKvStore:
    """hash → one durable block object [L, page_size, 2*kv_heads, head_dim].

    Duck-types ``DiskKvStore`` (contains/block_nbytes/put/get/read/drop/
    drain_transitions/used_bytes) so the promotion and quarantine paths
    treat it as one more rung; single-process writers, any-process readers
    (the scale-from-zero consumer re-indexes the directory at boot)."""

    def __init__(
        self,
        capacity_bytes: int,
        directory: str,
        fsync: bool = False,
        part_bytes: int = 1 << 20,
        gc_watermark: float = 0.9,
    ):
        self.capacity_bytes = capacity_bytes
        self.directory = directory
        self.fsync = fsync
        self.part_bytes = max(1, part_bytes)
        # GC target as a fraction of capacity: a sweep stops once
        # used_bytes <= capacity * gc_watermark, leaving headroom so the
        # next few puts don't immediately re-trigger it.
        self.gc_watermark = gc_watermark
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._tlock = threading.Lock()
        # hash → object bytes, access-ordered (coldest first).
        self._index: "OrderedDict[int, int]" = OrderedDict()
        self._bytes = 0
        # counters (metrics / tests)
        self.stored_blocks = 0
        self.fetched_blocks = 0
        self.evicted_blocks = 0
        self.rejected_blocks = 0
        self.corrupt_blocks = 0
        self.gc_runs = 0
        self._transitions: List[Tuple[str, int]] = []
        # Re-index an existing object root (the scale-from-zero boot path:
        # a fresh worker pointed at the fleet's object dir finds every
        # persisted prefix).  Coldest = oldest mtime; orphaned staging
        # files from a crashed multipart upload are deleted — they hold no
        # indexable content but consume bytes outside the budget forever.
        entries = []
        for sub in sorted(os.listdir(directory)):
            subdir = os.path.join(directory, sub)
            if not os.path.isdir(subdir):
                continue
            for name in os.listdir(subdir):
                if name.endswith(".obj.tmp"):
                    try:
                        os.remove(os.path.join(subdir, name))
                    except OSError:
                        pass
                    continue
                if not name.endswith(".obj"):
                    continue
                try:
                    h = int(name[: -len(".obj")], 16)
                except ValueError:
                    continue
                try:
                    st = os.stat(os.path.join(subdir, name))
                except OSError:
                    continue
                entries.append((st.st_mtime, h, st.st_size))
        for _, h, size in sorted(entries):
            self._index[h] = size
            self._bytes += size

    # ------------------------------------------------------------------ state
    def _path(self, seq_hash: int) -> str:
        return os.path.join(
            self.directory, f"{(seq_hash >> 56) & 0xFF:02x}",
            f"{seq_hash:016x}.obj",
        )

    def _tmp_path(self, final: str) -> str:
        """Staging path for the multipart write protocol: parts land in
        ``<final>.tmp`` and are ``os.replace``d into place on completion
        or ``os.remove``d on failure (dynalint DYN501 tracks the pair)."""
        return final + ".tmp"

    # Membership reads are lock-free like the other tiers: the event loop
    # consults them on hot paths and a stale answer degrades to one
    # validated miss + recompute, never corruption.
    def __len__(self) -> int:
        return len(self._index)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._index

    def block_nbytes(self, seq_hash: int) -> Optional[int]:
        return self._index.get(seq_hash)

    def drain_transitions(self) -> List[Tuple[str, int]]:
        with self._tlock:
            out, self._transitions = self._transitions, []
            return out

    # -------------------------------------------------------------------- put
    def put(self, seq_hash: int, block, checksum: Optional[int] = None) -> bool:
        """Persist one block as a durable object.  ``checksum`` is the
        offload-time stamp; when provided it is VERIFIED against the
        payload before anything touches the store — a mismatch means the
        bytes rotted upstream, and persisting them would hand the poison
        to every future scale-from-zero worker."""
        from .integrity import bytes_checksum

        from ..llm.metrics import objstore_metrics

        if not isinstance(block, np.ndarray):
            self.rejected_blocks += 1
            return False
        payload = np.ascontiguousarray(block).tobytes()
        payload_crc = bytes_checksum(payload)
        if checksum is not None and int(checksum) != payload_crc:
            from ..llm.metrics import kv_integrity_metrics

            kv_integrity_metrics.corrupt_total["host"] += 1
            self.corrupt_blocks += 1
            self.rejected_blocks += 1
            logger.warning(
                "refusing to persist block %#x: payload fails its offload "
                "checksum (upstream corruption)", seq_hash,
            )
            return False
        header = json.dumps(
            {
                "dtype": str(block.dtype),
                "shape": list(block.shape),
                "checksum": payload_crc,
            }
        ).encode()
        return self._store_blob(
            seq_hash, _MAGIC + _HLEN.pack(len(header)) + header + payload,
            objstore_metrics,
        )

    def ingest_kvblk(self, seq_hash: int, path: str) -> bool:
        """Demotion entry point (``DiskKvStore.on_evict``): parse + verify
        the evicted ``.kvblk`` envelope and re-wrap it as a durable object.
        Runs inside the disk store's eviction loop (under ITS lock, off the
        event loop) — so this must never call back into the disk tier.  A
        file that fails validation is refused (the disk tier's own read
        path owns quarantining it); the carried CRC rides into the object
        header unchanged."""
        from .disk_cache import _MAGIC as _DISK_MAGIC

        from ..llm.metrics import objstore_metrics

        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self.rejected_blocks += 1
            return False
        parsed = parse_object_blob(blob, magic=_DISK_MAGIC)
        if parsed is None:
            self.corrupt_blocks += 1
            self.rejected_blocks += 1
            logger.warning(
                "refusing to persist demoted block %#x: disk envelope "
                "fails validation", seq_hash,
            )
            return False
        arr, checksum = parsed
        # Same header, object magic: the payload bytes (and their CRC)
        # are carried, not recomputed.
        header = json.dumps(
            {
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "checksum": checksum,
            }
        ).encode()
        (hlen,) = _HLEN.unpack_from(blob, len(_DISK_MAGIC))
        payload = blob[len(_DISK_MAGIC) + _HLEN.size + hlen:]
        return self._store_blob(
            seq_hash, _MAGIC + _HLEN.pack(len(header)) + header + payload,
            objstore_metrics,
        )

    def _store_blob(self, seq_hash: int, blob: bytes, metrics) -> bool:
        nbytes = len(blob)
        with self._lock:
            if nbytes > self.capacity_bytes:
                self.rejected_blocks += 1
                return False
            if seq_hash in self._index:
                self._index.move_to_end(seq_hash)
                return True
            path = self._path(seq_hash)
            tmp = self._tmp_path(path)
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(tmp, "wb") as f:
                    # Multipart-style upload: bounded parts, one final
                    # atomic publish.  A crash between parts leaves only
                    # the staging file (re-index deletes it).
                    for off in range(0, nbytes, self.part_bytes):
                        f.write(blob[off : off + self.part_bytes])
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
                os.replace(tmp, path)  # atomic: readers never see parts
            except OSError:
                logger.exception("object KV tier write failed for %#x", seq_hash)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                self.rejected_blocks += 1
                return False
            self._index[seq_hash] = nbytes
            self._bytes += nbytes
            self.stored_blocks += 1
            metrics.puts_total += 1
            metrics.put_bytes_total += nbytes
            if self._bytes > self.capacity_bytes:
                self._gc_locked()
            return True

    # --------------------------------------------------------------------- gc
    def _gc_locked(self) -> None:
        """Byte-budgeted sweep: evict coldest objects until used bytes sit
        at/below the low watermark.  Caller holds the main lock."""
        from ..llm.metrics import objstore_metrics

        target = int(self.capacity_bytes * self.gc_watermark)
        swept = 0
        while self._bytes > target and self._index:
            old, old_bytes = self._index.popitem(last=False)  # coldest
            self._bytes -= old_bytes
            self.evicted_blocks += 1
            swept += 1
            objstore_metrics.gc_evictions_total += 1
            with self._tlock:
                self._transitions.append(("drop", old))
            try:
                os.remove(self._path(old))
            except OSError:
                pass
        if swept:
            self.gc_runs += 1
            logger.info(
                "object KV GC: evicted %d objects, %d bytes in use", swept,
                self._bytes,
            )

    def gc(self) -> int:
        """Run one sweep now (operator/test hook); returns evicted count."""
        with self._lock:
            before = self.evicted_blocks
            self._gc_locked()
            return self.evicted_blocks - before

    # -------------------------------------------------------------------- get
    def get(
        self,
        seq_hash: int,
        expected_shape: Optional[Tuple[int, ...]] = None,
        expected_dtype=None,
    ) -> Optional[np.ndarray]:
        return self.read(seq_hash, expected_shape, expected_dtype)[0]

    def read(
        self,
        seq_hash: int,
        expected_shape: Optional[Tuple[int, ...]] = None,
        expected_dtype=None,
    ) -> Tuple[Optional[np.ndarray], Optional[int], bool]:
        """Read + VALIDATE one object; ``(array, carried_checksum,
        corrupt)`` exactly like ``DiskKvStore.read`` — a corrupt object is
        deleted (it cannot miss forever) and the loss RECORDED so the
        router stops advertising the prefix."""
        from ..llm.metrics import objstore_metrics
        from ..runtime.faultinject import faults

        with self._lock:
            if seq_hash not in self._index:
                return None, None, False
            path = self._path(seq_hash)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                self._drop_locked(seq_hash)
                with self._tlock:
                    self._transitions.append(("drop", seq_hash))
                return None, None, False
            if (
                faults.enabled
                and len(blob) > len(_MAGIC) + _HLEN.size
                and faults.should("kv_corrupt", "objstore")
            ):
                # Chaos hook: flip one payload byte AFTER the read —
                # durable media rots too (the L10 rung's fault).
                from .integrity import flip_blob_byte

                (hlen,) = _HLEN.unpack_from(blob, len(_MAGIC))
                blob = flip_blob_byte(blob, len(_MAGIC) + _HLEN.size + hlen)
            parsed = parse_object_blob(blob, expected_shape, expected_dtype)
            if parsed is None:
                self.corrupt_blocks += 1
                self._drop_locked(seq_hash)
                with self._tlock:
                    self._transitions.append(("drop", seq_hash))
                try:
                    os.remove(path)
                except OSError:
                    pass
                return None, None, True
            arr, checksum = parsed
            self._index.move_to_end(seq_hash)  # touch
            objstore_metrics.gets_total += 1
            objstore_metrics.get_bytes_total += len(blob)
            self.fetched_blocks += 1
            return arr, checksum, False

    def drop(self, seq_hash: int) -> bool:
        """Remove one object (corruption quarantine of chained
        descendants); records the loss for the engine's event flush."""
        with self._lock:
            if seq_hash not in self._index:
                return False
            self._drop_locked(seq_hash)
            try:
                os.remove(self._path(seq_hash))
            except OSError:
                pass
        with self._tlock:
            self._transitions.append(("drop", seq_hash))
        return True

    def _drop_locked(self, seq_hash: int) -> None:
        nbytes = self._index.pop(seq_hash, None)
        if nbytes is not None:
            self._bytes -= nbytes
