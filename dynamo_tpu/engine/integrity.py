"""KV integrity plane: content checksums for every tier and wire boundary.

PRs 5/11 made KV blocks a fleet-wide, multi-tier currency — HBM → host →
disk demotion, cross-worker prefix pull, live migration — but the
validation on those paths was *structural* (magic/header/shape/dtype/
byte-length): a single payload bit-flip on disk, in host RAM, or on the
wire scattered cleanly and silently poisoned every stream reusing that
prefix, and the pull/migration planes then propagated the poison
fleet-wide (Llumnix's point: once live state migrates between workers,
state fidelity is a correctness invariant, not an optimization).

This module is the shared core; the verification points live at each
media/process boundary:

=========  ==============================================  ==============
plane      stamped by                                      verified by
=========  ==============================================  ==============
``host``   ``HostKvStore.put`` (offload commit)            ``_restore_pass``
                                                           before scatter
``disk``   carried from the host stamp into the ``.kvblk`` ``DiskKvStore.read``
           envelope header (``_demote_to_disk``)           before promote
``wire``   ``export_prompt_blocks`` (per-block, from HBM)  ``inject_blocks``
                                                           before seal —
                                                           covers pull,
                                                           migration push
                                                           and disagg
=========  ==============================================  ==============

The checksum is CRC-32 (zlib) — stdlib, byte-identical in every process
of a fleet (an algorithm that varies by installed modules would read as
fleet-wide corruption).  Host and disk share ONE stamp per block (CRC
over the combined block's ``tobytes()``), computed once at offload and
carried down and back up the tier chain, so host-RAM rot between offload
and demotion is caught at the disk write, not laundered into a "valid"
file.  The wire stamp is computed per exported block from the split K/V
arrays at export time (a fresh HBM gather — HBM is the source of truth).

A verification failure is never a crash or a wrong token: the block and
its chained descendants are dropped from the tiers (``Removed`` events
stop the router advertising the prefix), the hash is negative-cached
(``CorruptionCache``, TTL) so restore/pull loops cannot thrash on it,
the stream falls back to recompute (byte-identical by construction), and
repeated corruption from one donor feeds the health watchdog's
quarantine path (``runtime/health.py kv_corruption``).

Wire compat is omit-when-absent: payloads without ``checksums`` (older
peers) stay servable — verification simply has nothing to check.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def block_checksum(block) -> int:
    """CRC-32 of one combined KV block's bytes ([L, ps, 2KV, hd]) — the
    identity stamped at offload and carried host → disk → host."""
    return zlib.crc32(np.ascontiguousarray(block).tobytes()) & 0xFFFFFFFF


def bytes_checksum(payload: bytes) -> int:
    """CRC-32 of raw payload bytes (the ``.kvblk`` envelope check).  For
    an array this equals ``block_checksum`` of the same values because
    the envelope payload IS ``tobytes()`` of the contiguous array."""
    return zlib.crc32(payload) & 0xFFFFFFFF


def payload_block_checksums(k, v) -> List[int]:
    """Per-block wire checksums over a transfer payload's split K/V
    arrays ([L, n, ps, KV, hd] each): block i's CRC chains K then V.

    Per-block (not per-payload) so the importer can seal the verified
    prefix and drop only the corrupt block + its chained descendants —
    one flipped byte costs one block's recompute, not the whole
    transfer."""
    out: List[int] = []
    for i in range(k.shape[1]):
        c = zlib.crc32(np.ascontiguousarray(k[:, i]).tobytes())
        c = zlib.crc32(np.ascontiguousarray(v[:, i]).tobytes(), c)
        out.append(c & 0xFFFFFFFF)
    return out


def flip_array_byte(arr) -> np.ndarray:
    """Fault-injection helper (``kv_corrupt``): copy ``arr`` and flip one
    byte in the middle — a deterministic stand-in for media/DMA rot.  The
    copy matters: the source buffer (a host-tier entry, a wire view) must
    stay pristine so the fault models corruption *in flight*."""
    a = np.ascontiguousarray(arr).copy()
    flat = a.reshape(-1).view(np.uint8)
    flat[flat.size // 2] ^= 0xFF
    return a


def flip_blob_byte(blob: bytes, offset: int) -> bytes:
    """Flip one payload byte of a serialized envelope at/after ``offset``
    (keeps the header intact so structural validation still passes — the
    checksum is what must catch it)."""
    b = bytearray(blob)
    i = offset + max(0, (len(b) - offset) // 2)
    i = min(i, len(b) - 1)
    b[i] ^= 0xFF
    return bytes(b)


class CorruptionCache:
    """TTL negative cache of checksum-failed block hashes.

    Restore, promotion and cross-worker pull consult it before touching a
    hash: without it, a corrupt block on a donor (which the donor still
    holds — we can only drop OUR copies) would be re-pulled and re-fail
    on every admission of the prefix, and a flaky medium could thrash
    promote→corrupt→drop loops.  Entries expire after ``ttl_s`` so a
    healthy copy (new donor, rewritten tier) becomes reachable again —
    the ban is a thrash guard, not a permanent verdict.

    Bounded (the entry expiring soonest is evicted first) and
    clock-injectable for deterministic tests.  Mutations take a lock:
    callers mix the event loop with ``asyncio.to_thread`` contexts
    (promotion, offload staging), and the bounded-eviction ``min()`` scan
    iterating a dict another thread mutates would crash the very
    corruption-handling path that must degrade gracefully.
    """

    def __init__(
        self,
        ttl_s: float = 30.0,
        max_entries: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._clock = clock
        self._lock = threading.Lock()
        self._banned: Dict[int, float] = {}  # hash → ban deadline
        self.bans_total = 0

    def __len__(self) -> int:
        return len(self._banned)

    def ban(self, seq_hash: int) -> None:
        with self._lock:
            if (
                len(self._banned) >= self.max_entries
                and seq_hash not in self._banned
            ):
                # Evict the entry expiring soonest; the newest ban is the
                # one actively guarding a live thrash loop.
                oldest = min(self._banned, key=self._banned.__getitem__)
                self._banned.pop(oldest, None)
            self._banned[seq_hash] = self._clock() + self.ttl_s
            self.bans_total += 1

    def banned(self, seq_hash: int) -> bool:
        deadline = self._banned.get(seq_hash)  # GIL-atomic read
        if deadline is None:
            return False
        if self._clock() >= deadline:
            with self._lock:
                # Re-check under the lock: a concurrent ban() may have
                # refreshed the deadline since the read above.
                if (d := self._banned.get(seq_hash)) is not None and (
                    self._clock() >= d
                ):
                    self._banned.pop(seq_hash, None)
                return False if d is None else self._clock() < d
        return True

    def any_banned(self, seq_hashes: Sequence[int]) -> Optional[int]:
        """First banned hash in ``seq_hashes``, or None."""
        for h in seq_hashes:
            if self.banned(h):
                return h
        return None

    def clear(self) -> None:
        with self._lock:
            self._banned.clear()
