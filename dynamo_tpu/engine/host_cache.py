"""Host (CPU RAM) KV offload tier: evicted HBM blocks keep their contents.

Reference counterpart: the pinned host block pool + device↔host block copies
(lib/llm/src/kv/storage.rs:48-316, kernels/block_copy.cu, layer.rs:100-772)
behind the published ~40% TTFT win for multi-turn workloads
(docs/architecture.md:91-95).  The TPU translation: sealed blocks are
write-behind copied to host as soon as they are published (one batched
device gather + async D2H per pump cycle — no per-block copy kernel), so
HBM eviction never loses reusable contents; a prompt whose prefix fell out
of HBM restores it with one scatter (the same donated in-place path KV
transfer uses) instead of recomputing prefill.

Keyed by chained sequence hash (tokens.py), LRU-bounded by bytes.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np


class HostKvStore:
    """hash → one block's pages [L, page_size, 2*kv_heads, head_dim].

    Multi-host deployments store a PER-HOST SHARD instead: a dict mapping
    the combined-head-axis offset of each locally-held shard to its slice
    (engine._offload_store) — each process's tier holds only what its own
    devices held, and restores reassemble the global array from every
    process's local contribution."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = capacity_bytes
        self._data: "OrderedDict[int, object]" = OrderedDict()
        self._bytes = 0
        # counters (metrics / tests)
        self.stored_blocks = 0
        self.restored_blocks = 0
        self.evicted_blocks = 0

    @staticmethod
    def _nbytes(block) -> int:
        if isinstance(block, dict):
            return sum(a.nbytes for a in block.values())
        return block.nbytes

    def __len__(self) -> int:
        return len(self._data)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._data

    def put(self, seq_hash: int, block) -> None:
        if seq_hash in self._data:
            self._data.move_to_end(seq_hash)
            return
        nbytes = self._nbytes(block)
        if nbytes > self.capacity_bytes:
            return
        while self._bytes + nbytes > self.capacity_bytes and self._data:
            _, old = self._data.popitem(last=False)  # LRU
            self._bytes -= self._nbytes(old)
            self.evicted_blocks += 1
        self._data[seq_hash] = block
        self._bytes += nbytes
        self.stored_blocks += 1

    def get(self, seq_hash: int) -> Optional[np.ndarray]:
        blk = self._data.get(seq_hash)
        if blk is not None:
            self._data.move_to_end(seq_hash)  # touch
        return blk

    def peek(self, seq_hash: int):
        """Read WITHOUT the LRU touch.  Multi-host tiers must mutate in
        broadcast order only — a leader-local speculative read (candidate
        selection that may be truncated before the restore is broadcast)
        must not reorder the leader's LRU relative to the followers'."""
        return self._data.get(seq_hash)
