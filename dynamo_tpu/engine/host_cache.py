"""Host (CPU RAM) KV offload tier: evicted HBM blocks keep their contents.

Reference counterpart: the pinned host block pool + device↔host block copies
(lib/llm/src/kv/storage.rs:48-316, kernels/block_copy.cu, layer.rs:100-772)
behind the published ~40% TTFT win for multi-turn workloads
(docs/architecture.md:91-95).  The TPU translation: sealed blocks are
write-behind copied to host as soon as they are published (one batched
device gather + async D2H per pump cycle — no per-block copy kernel), so
HBM eviction never loses reusable contents; a prompt whose prefix fell out
of HBM restores it with one scatter (the same donated in-place path KV
transfer uses) instead of recomputing prefill.

Keyed by chained sequence hash (tokens.py), LRU-bounded by bytes.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class HostKvStore:
    """hash → one block's pages [L, page_size, 2*kv_heads, head_dim].

    Multi-host deployments store a PER-HOST SHARD instead: a dict mapping
    the combined-head-axis offset of each locally-held shard to its slice
    (engine._offload_store) — each process's tier holds only what its own
    devices held, and restores reassemble the global array from every
    process's local contribution.

    With a disk tier configured (engine/disk_cache.py) LRU eviction DEMOTES
    instead of dropping: ``on_evict(hash, block) -> bool`` is the engine's
    demotion hook; a True return means the next tier took the block.  Every
    eviction is recorded in ``_transitions`` — ("demote", h) or ("drop", h)
    — for the engine's event flush (tier-tagged KvCacheEvents must be
    published from the event loop, and eviction often happens inside
    ``asyncio.to_thread``)."""

    def __init__(
        self,
        capacity_bytes: int,
        on_evict: Optional[Callable[[int, object], bool]] = None,
    ):
        self.capacity_bytes = capacity_bytes
        self.on_evict = on_evict
        self._data: "OrderedDict[int, object]" = OrderedDict()
        self._bytes = 0
        # Mutations come from asyncio.to_thread workers (offload commit,
        # disk→host promotion) that can overlap — OrderedDict reordering
        # is not atomic, so serialize every access.  Reads (contains/peek/
        # len) stay lock-free (GIL-atomic dict ops; stale answers degrade
        # to a recompute, never corruption) because the EVENT LOOP calls
        # them on hot paths and the main lock is held across on_evict disk
        # writes.  Transitions use their own tiny lock for the same
        # reason (drain_transitions runs on the loop).
        self._lock = threading.Lock()
        self._tlock = threading.Lock()
        # Integrity stamps (engine/integrity.py): hash → CRC-32 of the
        # block's bytes, computed ONCE at offload (put) and carried to the
        # disk envelope on demotion and back on promotion.  Multi-host
        # shard dicts carry None (per-shard stamps would not survive the
        # broadcast-ordered reassembly; documented restriction).
        self._sums: Dict[int, Optional[int]] = {}
        # counters (metrics / tests)
        self.stored_blocks = 0
        self.restored_blocks = 0
        self.evicted_blocks = 0
        self.demoted_blocks = 0
        self.corrupt_blocks = 0
        self._transitions: List[Tuple[str, int]] = []

    @staticmethod
    def _nbytes(block) -> int:
        if isinstance(block, dict):
            return sum(a.nbytes for a in block.values())
        return block.nbytes

    def __len__(self) -> int:
        return len(self._data)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._data

    def admit_bytes(self, nbytes: int) -> bool:
        """Could ``nbytes`` EVER fit this tier's budget?  The reject-early
        gate restore/promotion paths consult BEFORE copying anything: an
        oversized batch must fail before it stages a single byte, not blow
        the budget transiently and evict the working set for nothing."""
        return nbytes <= self.capacity_bytes

    def drain_transitions(self) -> List[Tuple[str, int]]:
        with self._tlock:
            out, self._transitions = self._transitions, []
            return out

    def _evict_one(self) -> None:
        # caller holds self._lock
        h, old = self._data.popitem(last=False)  # LRU
        self._bytes -= self._nbytes(old)
        self.evicted_blocks += 1
        demoted = False
        if self.on_evict is not None:
            try:
                # _sums still holds h here: the demotion hook reads
                # checksum(h) to carry the offload stamp into the disk
                # envelope; popped only after the hook returns.
                demoted = bool(self.on_evict(h, old))
            except Exception:
                # Demotion is an optimization; a failing disk tier must
                # never break the host tier's eviction path.
                logger.exception("host-tier demotion failed for %#x", h)
        self._sums.pop(h, None)
        if demoted:
            self.demoted_blocks += 1
        with self._tlock:
            self._transitions.append(("demote" if demoted else "drop", h))

    def put(self, seq_hash: int, block, checksum: Optional[int] = None) -> None:
        from .integrity import block_checksum

        if checksum is None and isinstance(block, np.ndarray):
            # THE integrity stamp: computed once here (offload commit /
            # disk promotion passes the carried one instead) and verified
            # at every later media boundary.  Shard dicts stay unstamped.
            checksum = block_checksum(block)
        with self._lock:
            if seq_hash in self._data:
                self._data.move_to_end(seq_hash)
                return
            nbytes = self._nbytes(block)
            if nbytes > self.capacity_bytes:
                return
            while self._bytes + nbytes > self.capacity_bytes and self._data:
                self._evict_one()
            self._data[seq_hash] = block
            self._sums[seq_hash] = checksum
            self._bytes += nbytes
            self.stored_blocks += 1

    def get(self, seq_hash: int) -> Optional[np.ndarray]:
        with self._lock:
            blk = self._data.get(seq_hash)
            if blk is not None:
                self._data.move_to_end(seq_hash)  # touch
            return blk

    def touch(self, seq_hash: int) -> None:
        """Best-effort recency touch that NEVER blocks: the event loop
        refreshes LRU order after a restore, and the main lock can be held
        by a thread through an on_evict disk write — skipping a touch
        under contention costs at most one suboptimal future eviction."""
        if self._lock.acquire(blocking=False):
            try:
                if seq_hash in self._data:
                    self._data.move_to_end(seq_hash)
            finally:
                self._lock.release()

    def peek(self, seq_hash: int):
        """Read WITHOUT the LRU touch.  Multi-host tiers must mutate in
        broadcast order only — a leader-local speculative read (candidate
        selection that may be truncated before the restore is broadcast)
        must not reorder the leader's LRU relative to the followers'."""
        return self._data.get(seq_hash)

    def checksum(self, seq_hash: int) -> Optional[int]:
        """The block's offload-time integrity stamp (None: absent or an
        unstamped multi-host shard dict).  Lock-free like the other reads
        — a stale answer degrades to one spurious recompute, never a
        wrong scatter."""
        return self._sums.get(seq_hash)

    def drop(self, seq_hash: int) -> bool:
        """Remove one block WITHOUT demotion (corruption quarantine: the
        contents failed verification, pushing them down a tier would just
        relocate the poison).  Records the loss for the engine's event
        flush so the router stops advertising the prefix."""
        with self._lock:
            blk = self._data.pop(seq_hash, None)
            self._sums.pop(seq_hash, None)
            if blk is None:
                return False
            self._bytes -= self._nbytes(blk)
            self.corrupt_blocks += 1
        with self._tlock:
            self._transitions.append(("drop", seq_hash))
        return True
