"""Host-KV offload tier pump, sealed-block restore, and the sp (ring
attention) whole-prompt prefill path.

Split out of engine.py as a pure move (r5; VERDICT r4 weak #7) — these are
TpuEngine methods, combined via mixin inheritance.
"""

from __future__ import annotations

import asyncio
import time
import logging
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)



class HostOffloadMixin:
    async def _offload_pump(self) -> None:
        """Write-behind: batch-gather queued sealed blocks to the host tier
        (one device gather + one D2H per cycle, not per block)."""
        while not self._closed:
            await asyncio.sleep(self.cfg.host_offload_interval)
            if self._offload_queue:
                try:
                    await self.drain_offload()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # Offload is an optimization; never let it kill serving.
                    logger.exception("host KV offload cycle failed")

    async def drain_offload(self, max_blocks: int = 64) -> int:
        """Copy up to ``max_blocks`` queued sealed blocks to host RAM.
        Returns how many were stored (public so tests can force a cycle).

        The device lock is held only for the GATHER DISPATCH: the gather's
        output is a fresh buffer independent of the (donated) cache, and
        the device executes queued programs in order, so once it is
        enqueued the D2H force + host-tier copy can run outside the lock —
        decode dispatch never waits on an offload's host copy (the r5
        drain held the lock across the whole batched D2H).  Multi-process
        runs keep the combined under-lock path: the leader's store must
        complete before the mirror publish so a leader-side failure leaves
        every process's tier unchanged (no tier skew)."""
        if self.host_kv is None or not self._offload_queue:
            return 0
        batch, self._offload_queue = (
            self._offload_queue[:max_blocks],
            self._offload_queue[max_blocks:],
        )
        async with self._device_lock:
            # A block may have been recycled since sealing; only blocks
            # still holding their hash are snapshotted.
            live = [
                (bid, tb)
                for bid, tb in batch
                if self.kv._blocks[bid].sequence_hash == tb.sequence_hash
            ]
            if not live:
                return 0
            pad = 1 << max(0, (len(live) - 1).bit_length())
            ids = np.zeros((pad,), np.int32)
            ids[: len(live)] = [bid for bid, _ in live]
            hashes = [tb.sequence_hash for _, tb in live]
            if jax.process_count() > 1:
                # Leader stores FIRST, publish only on success — still
                # under the device lock, so no other dispatch can
                # interleave and the followers' execution position matches
                # the leader's.  A leader-side failure then leaves every
                # tier unchanged instead of followers holding blocks the
                # leader lacks (tier skew would surface later as a fatal
                # restore divergence).
                await asyncio.to_thread(self._offload_store, ids, hashes)
                if self._publisher is not None:
                    await self._publisher.publish("offload", (ids, hashes))
                # Host-tier drops still record transitions here (no disk
                # tier multi-process) — flush them or the list grows
                # unboundedly and the router keeps advertising prefixes
                # this worker can no longer restore.
                self._flush_tier_events()
                return len(live)
            # Single-process: enqueue the gather under the lock (ordering
            # vs later donating steps), copy/store outside it.
            pages_g = await asyncio.to_thread(
                self._gather_fn, self.cache, self._prep(ids)
            )
        await asyncio.to_thread(self._offload_commit, pages_g, hashes)
        self._flush_tier_events()
        return len(live)

    def _offload_commit(self, pages_g, hashes: List[int]) -> None:
        """Force the gathered pages to host and store them in the host tier
        (single-process half of _offload_store, runs OUTSIDE the device
        lock)."""
        pages = np.asarray(pages_g)
        for i, h in enumerate(hashes):
            self.host_kv.put(h, np.ascontiguousarray(pages[:, i]))

    def _offload_store(self, ids: np.ndarray, hashes: List[int]) -> None:
        """Gather ``ids``'s pages and store THIS PROCESS's portion in the
        host tier.  Single-process: the whole block (contiguous, one
        array).  Multi-process: one slice per locally-held shard, keyed by
        the shard's heads-axis offset (combined-head axis 3)."""
        # _prep: in multi-process runs the gather's index operand must be a
        # replicated GLOBAL array like every other mirrored dispatch.
        pages_g = self._gather_fn(self.cache, self._prep(ids))
        if jax.process_count() == 1:
            pages = np.asarray(pages_g)
            for i, h in enumerate(hashes):
                self.host_kv.put(h, np.ascontiguousarray(pages[:, i]))
            return
        shards: Dict[int, np.ndarray] = {}
        for s in pages_g.addressable_shards:
            start = s.index[3].start or 0
            if start not in shards:
                shards[start] = np.asarray(s.data)
        for i, h in enumerate(hashes):
            self.host_kv.put(
                h,
                {
                    start: np.ascontiguousarray(arr[:, i])
                    for start, arr in shards.items()
                },
            )

    async def _sp_prefill(
        self, token_ids: List[int], salt: Optional[str] = None
    ) -> int:
        """Whole-prompt sequence-parallel prefill: compute the prompt's KV in
        one ring-attention pass over the "sp" mesh axis and seal its complete
        blocks into the paged cache (released to the reuse pool), so
        admission sees a full prefix hit.  The trailing partial block plus
        the last token recompute through the normal unified step (which also
        produces the first sampled token's logits).  Returns sealed tokens.
        """
        from ..tokens import hash_token_blocks

        cfg = self.cfg
        bs = cfg.block_size
        n_complete = len(token_ids) // bs
        blocks = hash_token_blocks(token_ids, bs, salt)
        resident = len(self.kv.match_prefix(blocks))
        if resident >= n_complete or n_complete == 0:
            return 0
        # Token bucket: power of two, multiple of sp (bounds recompiles).
        Tg = max(cfg.sp, 1 << (len(token_ids) - 1).bit_length())
        Tg += (-Tg) % cfg.sp
        toks = np.zeros((Tg,), np.int32)
        toks[: len(token_ids)] = token_ids
        valid = np.asarray(len(token_ids), np.int32)
        # No _device_lock here: the forward is a pure function of
        # params+tokens (touches no donated cache), so decode dispatches
        # interleave in the device queue instead of stalling behind the
        # whole-prompt pass.  (Dedicated disagg prefill workers remain the
        # intended fit for sp — config.py.)
        _, kv_rows = await asyncio.to_thread(
            self._sp_fn, self.params, toks, valid
        )
        # [L, Tg, 2KV, hd] → complete-block pages [L, n, bs, 2KV, hd]
        L = kv_rows.shape[0]
        if self.kv_scale is not None:
            # Quantized cache stores value/scale (write_kv_ragged contract);
            # per-layer calibration vectors broadcast over [L, Tg, 2KV, hd].
            sc = np.asarray(self.kv_scale, np.float32).reshape(-1, 1, 1, 1)
            kv_rows = kv_rows.astype(jnp.float32) / sc
        pages = kv_rows[:, : n_complete * bs].reshape(
            L, n_complete, bs, kv_rows.shape[2], kv_rows.shape[3]
        )[:, resident:]
        n_new = n_complete - resident
        pad = 1 << max(0, (n_new - 1).bit_length())
        if pad != n_new:
            pages = jnp.pad(pages, ((0, 0), (0, pad - n_new), (0, 0), (0, 0), (0, 0)))
        covered = await self.inject_blocks_from_device(
            token_ids, pages, n_new, start_block=resident, salt=salt
        )
        if covered:
            logger.info(
                "sp prefill sealed %d tokens of %d (sp=%d, bucket %d)",
                covered, len(token_ids), cfg.sp, Tg,
            )
        return covered

    def _promote_blocks(
        self, seq_hashes: List[int], stop_on_miss: bool
    ) -> List[int]:
        """Disk→host promotion (thread context): read + validate each
        block's file and insert it into the host tier.  A hash the disk
        tier no longer holds falls through to the object-store tier
        (object → host directly — the scale-from-zero restore path, where
        the disk tier starts empty).  Byte budget is counted against the
        DESTINATION tier before any file is read — an oversized batch
        rejects early instead of transiently blowing the host budget (and
        evicting the working set for nothing).  ``stop_on_miss`` stops at
        the first unavailable hash (prefix restores need a contiguous
        leading run); prefetch skips instead.

        Integrity: the envelope checksum verifies inside ``read`` (a
        corrupt file is a quarantine event — the chain's deeper tier
        blocks drop with it and the hash is negative-cached), and the
        carried stamp rides into the host entry so the later host→HBM
        scatter re-verifies the same identity."""
        from ..llm.metrics import kv_integrity_metrics

        L, _, ps, KV2, hd = self.cache.pages.shape
        shape, dtype = (L, ps, KV2, hd), self.cache.pages.dtype
        staged = 0
        promoted: List[int] = []
        for h in seq_hashes:
            if self.integrity.banned(h):
                # Recently corrupt: treat as a miss for the TTL so a
                # promote→corrupt→drop loop cannot thrash on the hash.
                kv_integrity_metrics.negative_cache_hits_total += 1
                if stop_on_miss:
                    break
                continue
            if self.host_kv.contains(h):
                continue
            source, plane = self.disk_kv, "disk"
            nbytes = self.disk_kv.block_nbytes(h)
            if nbytes is None and self.object_kv is not None:
                source, plane = self.object_kv, "objstore"
                nbytes = self.object_kv.block_nbytes(h)
            if nbytes is None:
                if stop_on_miss:
                    break
                continue
            if not self.host_kv.admit_bytes(staged + nbytes):
                break  # destination budget exhausted: reject BEFORE copying
            arr, checksum, corrupt = source.read(
                h, expected_shape=shape, expected_dtype=dtype
            )
            if corrupt:
                # The file was already dropped by read(); quarantine the
                # chain (descendants + negative cache) and recompute.
                self._record_corruption(plane, h, chain=seq_hashes)
                kv_integrity_metrics.recomputed_total += 1
                if stop_on_miss:
                    break
                continue
            if arr is None:
                if stop_on_miss:
                    break
                continue
            if checksum is not None:
                kv_integrity_metrics.verified_total[plane] += 1
            self.host_kv.put(h, arr, checksum=checksum)
            staged += nbytes
            promoted.append(h)
        if promoted:
            from ..llm.metrics import kv_tier_metrics

            self.disk_kv.promoted_blocks += len(promoted)
            kv_tier_metrics.promoted_blocks_total += len(promoted)
        return promoted

    def _emit_promotions(self, promoted: List[int]) -> None:
        """Tier-tag promoted blocks back to 'host' (unless HBM still holds
        them, in which case the router's view never left 'hbm'), then flush
        any demotions the promotion's own evictions caused."""
        self.kv.emit_tiered(
            "host", [h for h in promoted if h not in self.kv._by_hash]
        )
        self._flush_tier_events()

    async def prefetch_hashes(self, seq_hashes: List[int]) -> int:
        """Warm predicted prefixes disk→host ahead of arrivals (the
        planner/prefetch plane's engine hook — llm/kv_router/pull.py
        KvPrefetchConsumer).  Returns blocks promoted; skips hashes already
        resident in a faster tier."""
        if self.disk_kv is None or self.host_kv is None or not seq_hashes:
            return 0
        want = [h for h in seq_hashes if h not in self.kv._by_hash]
        if not want:
            return 0
        promoted = await asyncio.to_thread(self._promote_blocks, want, False)
        if promoted:
            from ..llm.metrics import kv_tier_metrics

            kv_tier_metrics.prefetched_blocks_total += len(promoted)
        self._emit_promotions(promoted)
        return len(promoted)

    async def persist_hashes(self, seq_hashes: List[int]) -> int:
        """Persist predicted-hot chains into the durable object tier (the
        autopilot warming policy's durability half — llm/kv_router/pull.py
        KvPrefetchConsumer ``persist`` flag): a chain persisted here
        survives this worker's death and warm-starts its scale-from-zero
        replacement.  Sources the host tier first (carried offload stamp),
        then the disk tier (validated read); HBM-only blocks are skipped —
        the write-behind offload pump lands them in host within a cycle.
        Returns objects stored."""
        if self.object_kv is None or not seq_hashes:
            return 0
        stored = await asyncio.to_thread(self._persist_blocks, seq_hashes)
        self._flush_tier_events()
        return stored

    def _persist_blocks(self, seq_hashes: List[int]) -> int:
        stored = 0
        for h in seq_hashes:
            if self.integrity.banned(h) or self.object_kv.contains(h):
                continue
            blk = self.host_kv.peek(h) if self.host_kv is not None else None
            if isinstance(blk, np.ndarray):
                if self.object_kv.put(
                    h, blk, checksum=self.host_kv.checksum(h)
                ):
                    stored += 1
                continue
            if self.disk_kv is None or not self.disk_kv.contains(h):
                continue
            arr, checksum, corrupt = self.disk_kv.read(h)
            if corrupt:
                self._record_corruption("disk", h, chain=list(seq_hashes))
                continue
            if arr is not None and self.object_kv.put(
                h, arr, checksum=checksum
            ):
                stored += 1
        return stored

    async def restore_prefix(
        self, token_ids: List[int], salt: Optional[str] = None
    ) -> int:
        """Public tier restore: bring ``token_ids``'s leading blocks back
        into HBM from the host/disk tiers if any are resident there.
        Used by admission (generate) and by the donor side of a
        cross-worker pull — export_prompt_blocks reads HBM only, so a
        donor whose blocks were demoted restores them before exporting
        (the pull's primary scenario IS tier-demoted donors)."""
        if self.host_kv is None or not (
            len(self.host_kv)
            or (self.disk_kv is not None and len(self.disk_kv))
            or (self.object_kv is not None and len(self.object_kv))
        ):
            return 0
        return await self._restore_from_host(token_ids, salt)

    async def _restore_from_host(
        self, token_ids: List[int], salt: Optional[str] = None
    ) -> int:
        """Scatter host/disk-tier blocks beyond the HBM-resident prefix
        back into the device cache (sealed + released to the reuse pool),
        so admission sees them as ordinary prefix-cache hits.  Returns
        restored blocks.  Iterates promote→restore rounds until no
        progress: a prefix deeper than the host tier's byte budget still
        restores fully, one host-budget's worth per round (disk → host →
        HBM).  ``salt`` (llm/tenancy): the tiers index blocks by the
        SALTED sequence hashes they sealed under, so tenant restores look
        up with the tenant's salt — and can never resurrect another
        tenant's KV."""
        total = 0
        while True:
            n = await self._restore_pass(token_ids, salt)
            if n <= 0:
                return total
            total += n
            if self.disk_kv is None:
                return total  # one pass covers the whole host-resident run

    async def _restore_pass(
        self, token_ids: List[int], salt: Optional[str] = None
    ) -> int:
        """One promote→restore round of ``_restore_from_host``."""
        if self.host_kv is None:
            return 0
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)
        resident = len(self.kv.match_prefix(blocks))
        if self.disk_kv is not None and (
            len(self.disk_kv)
            or (self.object_kv is not None and len(self.object_kv))
        ):
            # Promote the leading disk/object-resident run into the host
            # tier first, so the host→HBM scatter below sees one
            # contiguous restorable prefix (objstore → host → HBM is the
            # scale-from-zero boot path: disk starts empty).
            promoted = await asyncio.to_thread(
                self._promote_blocks,
                [tb.sequence_hash for tb in blocks[resident:]],
                True,
            )
            self._emit_promotions(promoted)
        from ..llm.metrics import kv_integrity_metrics
        from ..runtime.faultinject import faults
        from .integrity import block_checksum, flip_array_byte

        chain = [tb.sequence_hash for tb in blocks]
        run: List[Tuple[Any, np.ndarray]] = []
        for tb in blocks[resident:]:
            if self.integrity.banned(tb.sequence_hash):
                kv_integrity_metrics.negative_cache_hits_total += 1
                break  # recently corrupt: a miss; the tail recomputes
            # peek, not get: this is candidate selection (possibly
            # truncated below); touching the LRU here would diverge the
            # leader's eviction order from the followers'.
            host = self.host_kv.peek(tb.sequence_hash)
            if host is None:
                break
            if isinstance(host, np.ndarray):
                # The host→HBM media boundary: verify the offload stamp
                # BEFORE the scatter (host RAM rots too — ECC is not a
                # guarantee, and this array may have round-tripped disk).
                stamp = self.host_kv.checksum(tb.sequence_hash)
                if (
                    stamp is not None
                    and faults.enabled
                    and faults.should("kv_corrupt", "host")
                ):
                    # Chaos hook gated on a present stamp: flipping an
                    # unstamped (legacy) entry would SCATTER the flip —
                    # the fault tests detection, not legacy exposure.
                    host = flip_array_byte(host)
                if stamp is not None:
                    if block_checksum(host) != stamp:
                        self._record_corruption(
                            "host", tb.sequence_hash, chain=chain
                        )
                        self._flush_tier_events()
                        kv_integrity_metrics.recomputed_total += 1
                        break  # verified prefix still restores below
                    kv_integrity_metrics.verified_total["host"] += 1
            run.append((tb, host))
        run = run[: max(0, self.kv.free_blocks - 1)]
        if not run:
            return 0
        # PIN the resident prefix (take references) while allocating the
        # tail: the prefix blocks sit in the reuse pool and are otherwise
        # legitimate LRU eviction victims for our own allocations — which
        # would replace recompute-the-tail with recompute-everything.
        prefix_ids: List[int] = (
            self.kv.acquire_prefix(blocks[:resident]) or [] if resident else []
        )
        try:
            ids: List[int] = []
            for _ in run:
                bid = self.kv.allocate_block()
                if bid is None:
                    break
                ids.append(bid)
            run = run[: len(ids)]
            if not run:
                self.kv.free_sequence(ids)
                return 0
            n = len(run)
            pad = 1 << max(0, (n - 1).bit_length())
            page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
            page_ids[:n] = ids
            if jax.process_count() > 1:
                # Per-host sharded tier: every process reassembles ITS
                # devices' slice of each block from its own store — the
                # broadcast carries only ids + hashes, never page data.
                hashes = [tb.sequence_hash for tb, _ in run]
                async with self._device_lock:
                    # Revalidate UNDER the lock: the offload pump may have
                    # LRU-evicted a candidate while we awaited it.  Tiers
                    # mutate only under this lock and in broadcast order,
                    # so leader-present-here implies follower-present-there;
                    # a miss now means recompute-prefill, not a crash.
                    if any(
                        not isinstance(self.host_kv.peek(h), dict)
                        for h in hashes
                    ):
                        self.kv.free_sequence(ids)
                        return 0
                    # Inject locally first; publish only on success (same
                    # ordering argument as drain_offload).
                    await asyncio.to_thread(
                        self._restore_inject, page_ids, hashes
                    )
                    if self._publisher is not None:
                        await self._publisher.publish(
                            "restore_host", (page_ids, hashes)
                        )
            else:
                comb = np.stack([h for _, h in run], axis=1)  # [L,n,ps,2KV,hd]
                comb_p = np.zeros(
                    comb.shape[:1] + (pad,) + comb.shape[2:], comb.dtype
                )
                comb_p[:, :n] = comb
                async with self._device_lock:
                    if self._publisher is not None:
                        await self._publisher.publish(
                            "inject", (page_ids, comb_p)
                        )
                    self.cache = await asyncio.to_thread(
                        self._inject_fn,
                        self.cache,
                        *self._prep((page_ids, comb_p)),
                    )
                # Candidate selection peeked; refresh recency for the
                # blocks actually restored (single-process has no
                # cross-process lockstep to preserve).  touch(), not
                # get(): this runs ON THE EVENT LOOP and must never wait
                # behind a thread holding the lock through a disk write.
                for tb, _ in run:
                    self.host_kv.touch(tb.sequence_hash)
            for bid, (tb, _) in zip(ids, run):
                self.kv.seal_block(bid, tb)
            self.kv.free_sequence(ids)
            self.host_kv.restored_blocks += n
            from ..llm.metrics import kv_tier_metrics

            kv_tier_metrics.restored_blocks_total += n
            return n
        finally:
            if prefix_ids:
                self.kv.free_sequence(prefix_ids)

    def _restore_inject(self, page_ids: np.ndarray, hashes: List[int]) -> None:
        """Multi-process host restore: build this process's devices' slices
        of the [L, pad, ps, 2KV, hd] block stack from the per-host sharded
        tier and scatter them into the cache (every process runs this — the
        leader inline, followers via the 'restore_host' mirror step)."""
        from jax.sharding import NamedSharding

        from ..parallel.mesh import pages_pspec

        L, _, ps, KV2, hd = self.cache.pages.shape
        pad = int(page_ids.shape[0])
        shape = (L, pad, ps, KV2, hd)
        sharding = NamedSharding(self.mesh, pages_pspec())
        # Touch each hash exactly once (same broadcast order on every
        # process → identical LRU order), then build ONE local stack per
        # distinct head-shard offset — local devices sharing an offset
        # (dp/ep replicas) reuse the same array.
        fetched = []
        for h in hashes:
            blk = self.host_kv.get(h)
            if not isinstance(blk, dict):
                # Tiers mutate only in broadcast order, so after the
                # leader's under-lock revalidation this cannot happen on a
                # healthy deployment — fail LOUDLY rather than inject
                # zeros under a valid hash.
                raise RuntimeError(f"host tier missing block {h:#x}")
            fetched.append(blk)
        idx_map = sharding.addressable_devices_indices_map(shape)
        locals_by_start: Dict[int, np.ndarray] = {}
        for index in idx_map.values():
            start = index[3].start or 0
            if start in locals_by_start:
                continue
            parts = []
            for h, blk in zip(hashes, fetched):
                if start not in blk:
                    raise RuntimeError(
                        f"host tier missing shard {start} of block {h:#x}"
                    )
                parts.append(blk[start])  # [L, ps, local_heads, hd]
            local = np.stack(parts, axis=1)  # [L, n, ps, lh, hd]
            if pad != len(hashes):
                z = np.zeros(
                    local.shape[:1] + (pad,) + local.shape[2:], local.dtype
                )
                z[:, : len(hashes)] = local
                local = z
            locals_by_start[start] = local
        arrays = [
            jax.device_put(locals_by_start[index[3].start or 0], dev)
            for dev, index in idx_map.items()
        ]
        comb = jax.make_array_from_single_device_arrays(
            shape, sharding, arrays
        )
        self.cache = self._inject_fn(
            self.cache, self._prep(page_ids), comb
        )
