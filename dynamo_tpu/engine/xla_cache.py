"""Persistent XLA compilation cache.

Round-3 measurement: warming every reachable program costs ~140s of XLA
compiles on every engine start, so each worker restart / elastic scale-up
served nothing for ~2.3 minutes.  The reference's engines inherit vLLM's
torch.compile/CUDA-graph caches; the JAX equivalent is the persistent
compilation cache keyed by (HLO, compile options, backend version) — with it
wired, a restarted worker's warmup replays from disk in seconds.

Enabled by default at ``~/.cache/dynamo_tpu/xla`` (override with
DYN_XLA_CACHE_DIR; set it empty to disable).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_configured: Optional[str] = None


def setup_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (idempotent).

    ``None`` resolves DYN_XLA_CACHE_DIR, falling back to the default cache
    dir; an empty string disables.  Returns the active cache dir or None.
    """
    global _configured
    # An explicit path — argument or env var — is an opt-in that overrides
    # the CPU-backend default-off below.
    explicit = path is not None or bool(os.environ.get("DYN_XLA_CACHE_DIR"))
    if path is None:
        path = os.environ.get(
            "DYN_XLA_CACHE_DIR",
            os.path.join(
                os.path.expanduser("~"), ".cache", "dynamo_tpu", "xla"
            ),
        )
    if not path:
        return None
    import jax

    backend = jax.default_backend()
    if backend == "cpu" and not explicit:
        # XLA:CPU AOT cache entries embed the compile machine's CPU feature
        # set and can fail (or SIGILL) when loaded under a different feature
        # detection — observed between the serving process and hermetic
        # child processes on the SAME host.  CPU compiles are cheap; the
        # restart-warmup win this cache exists for is the accelerator path.
        # Explicitly setting DYN_XLA_CACHE_DIR opts CPU back in.
        return None
    path = os.path.join(path, backend)  # one cache per backend
    if _configured == path:
        return path
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything: the whole point is restart-time warmup, and the
        # warmup set is dozens of programs of wildly varying compile cost.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _configured = path
        logger.info("persistent XLA compilation cache at %s", path)
        return path
    except Exception:  # cache is an optimization; never block serving
        logger.exception("failed to enable XLA compilation cache")
        return None
