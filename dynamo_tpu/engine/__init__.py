"""The native TPU engine: paged KV block manager, continuous-batching
scheduler, and the jitted device step loop (SURVEY.md §7 stage 4 — the piece
the reference outsources to vLLM/sglang)."""

from .config import (  # noqa: F401
    EngineConfig,
    LoraConfig,
    QosSchedConfig,
    SpecDecodeConfig,
)
from .kv_manager import KvBlockManager  # noqa: F401
from .scheduler import Scheduler, SequenceState  # noqa: F401


def build_tpu_engine(args):
    """CLI factory (``run out=tpu`` — reference: launch/dynamo-run engine
    selection, lib.rs:198-453).  Imports jax lazily."""
    from .engine import TpuEngine

    arch = getattr(args, "arch", None)
    checkpoint = getattr(args, "checkpoint", None)
    model_config_path = getattr(args, "model_config", None)
    if checkpoint:
        # Resolve BEFORE anything else, like the reference's dynamo-run
        # (launch/dynamo-run/src/lib.rs:125-130): local dirs pass through,
        # names/repo-ids acquire via models/hub.py (HF snapshot or the
        # pre-staged offline cache).
        from ..models.hub import resolve_model

        args.checkpoint_source = checkpoint  # pre-resolution spec (registry)
        checkpoint = resolve_model(checkpoint)
        args.checkpoint = checkpoint  # tokenizer discovery reads it too
    if (
        checkpoint
        and not arch
        and not checkpoint.endswith(".gguf")
        and not model_config_path
    ):
        # The checkpoint's own config.json is the architecture source of
        # truth (reference: MDC from checkpoint metadata).
        from ..models.config import ModelConfig, register_config

        arch = register_config(ModelConfig.from_local_path(checkpoint)).name
    if checkpoint and checkpoint.endswith(".gguf") and not arch:
        # GGUF carries its own architecture metadata (reference: the
        # ModelDeploymentCard's gguf path, lib/llm/src/gguf/*).
        from ..models.config import register_config
        from ..models.gguf import GGUFFile

        arch = register_config(GGUFFile(checkpoint).to_model_config()).name
    if model_config_path:
        import json

        from ..models.config import ModelConfig, register_config

        with open(model_config_path) as f:
            cfg_json = json.load(f)
        arch = register_config(
            ModelConfig.from_hf_config(cfg_json, name=cfg_json.get("_name", "custom"))
        ).name

    lora_section, lora_adapters = _lora_section(args)
    cfg = EngineConfig(
        model=arch or "debug-tiny",
        block_size=getattr(args, "block_size", 16),
        num_blocks=getattr(args, "num_blocks", 256),
        max_batch=getattr(args, "max_batch", 8),
        max_model_len=getattr(args, "max_model_len", 1024),
        prefill_chunk=getattr(args, "prefill_chunk", 512),
        tp=getattr(args, "tp", 1),
        dp=getattr(args, "dp", 1),
        ep=getattr(args, "ep", 1),
        sp=getattr(args, "sp", 1),
        sp_prefill_min=getattr(args, "sp_prefill_min", 1024),
        dtype=getattr(args, "dtype", "bfloat16"),
        decode_steps=getattr(args, "decode_steps", 4),
        pipeline_depth=getattr(args, "pipeline_depth", 2),
        cache_dtype=getattr(args, "cache_dtype", None),
        kv_scale=getattr(args, "kv_scale", 1.0),
        checkpoint_path=getattr(args, "checkpoint", None),
        attn_impl=getattr(args, "attn_impl", "auto"),
        decode_kernel=getattr(args, "decode_kernel", "auto"),
        host_cache_bytes=(getattr(args, "host_cache_mb", 0) or 0) << 20,
        disk_cache_bytes=(getattr(args, "disk_cache_mb", 0) or 0) << 20,
        disk_cache_dir=getattr(args, "disk_cache_dir", None),
        object_store_bytes=(getattr(args, "object_store_mb", 0) or 0) << 20,
        object_store_dir=getattr(args, "object_store_dir", None),
        spec_decode=_spec_decode_section(args),
        lora=lora_section,
        qos=_qos_sched_section(),
    )
    if getattr(args, "kv_pull_mb", None) is not None:
        cfg.kv_pull_max_bytes = int(args.kv_pull_mb) << 20
    engine = TpuEngine(cfg)
    _load_adapters(engine, lora_adapters, getattr(args, "model", None))
    return engine


def _spec_decode_section(args) -> dict:
    """Layered spec_decode section: RuntimeConfig (file/DYN_SPEC_DECODE__*
    env) under explicit --spec-* CLI flags."""
    from ..runtime.config import RuntimeConfig

    section = dict(RuntimeConfig.from_layers().spec_decode)
    if getattr(args, "spec_decode", None) is not None:
        section["enable"] = bool(args.spec_decode)
    if getattr(args, "spec_k", None) is not None:
        section["k"] = int(args.spec_k)
    if getattr(args, "spec_ngram_max", None) is not None:
        section["ngram_max"] = int(args.spec_ngram_max)
    if getattr(args, "spec_ngram_min", None) is not None:
        section["ngram_min"] = int(args.spec_ngram_min)
    return section


def _qos_sched_section() -> dict:
    """Scheduler half of the layered ``qos`` config section (file /
    DYN_QOS__* env): WFQ tenant weights + the batch starvation bound.  The
    edge half (quotas, brownout) is consumed by the CLI's HttpService
    wiring instead."""
    from ..runtime.config import RuntimeConfig

    section = RuntimeConfig.from_layers().qos or {}
    known = ("tenant_weights", "default_weight", "batch_every")
    return {k: section[k] for k in known if k in section}


def _lora_section(args):
    """Layered multi-LoRA section (llm/tenancy): RuntimeConfig ``lora``
    (file / DYN_LORA__* env) under explicit --lora* CLI flags.  Returns
    ``(LoraConfig-kwargs, {name: spec})`` — the adapters map merges the
    config section's ``adapters`` with every repeatable ``--lora NAME=SPEC``
    flag, and any adapter at all implies ``enable``."""
    from ..runtime.config import RuntimeConfig

    section = dict(RuntimeConfig.from_layers().lora)
    adapters = dict(section.pop("adapters", None) or {})
    for spec in getattr(args, "lora", None) or []:
        name, sep, path = spec.partition("=")
        if not sep or not name or not path:
            raise SystemExit(f"--lora expects NAME=PATH, got {spec!r}")
        adapters[name] = path
    if getattr(args, "lora_max_adapters", None) is not None:
        section["max_adapters"] = int(args.lora_max_adapters)
    if getattr(args, "lora_rank", None) is not None:
        section["rank"] = int(args.lora_rank)
    if adapters:
        section["enable"] = True
    return section, adapters


def _load_adapters(engine, adapters: dict, base_model) -> None:
    """Host-register the configured adapters (no restart needed later —
    this is just the boot-time convenience path).  ``random[:seed]`` specs
    build synthetic adapters (tests / loadgen multi-tenant replay); other
    specs resolve like checkpoints (local dir or HF repo —
    models/hub.resolve_adapter).  On any LoRA-enabled engine the
    served-model allowlist is pinned to base+adapters so unknown names 404
    (llm/tenancy satellite) instead of silently running the base model —
    also when NO boot adapters exist (register_adapter adds to the pinned
    set later): without the allowlist the engine's only fallback identity
    is cfg.model, the ARCHITECTURE name, and a served name that differs
    from it would 404 all base traffic."""
    if adapters:
        from ..llm.tenancy.lora import LoraAdapter, load_lora_adapter
        from ..models.hub import resolve_adapter

        for name, spec in sorted(adapters.items()):
            if isinstance(spec, str) and spec.startswith("random"):
                _, _, seed = spec.partition(":")
                adapter = LoraAdapter.random(
                    engine.model_config,
                    name,
                    rank=min(4, engine.cfg.lora.rank),
                    seed=int(seed or 0),
                )
            else:
                adapter = load_lora_adapter(
                    resolve_adapter(spec), engine.model_config, name=name
                )
            engine.register_adapter(adapter)
    if base_model and (adapters or engine.cfg.lora.enable):
        engine.set_served_models([base_model, *adapters])
