"""TpuEngine: the native JAX engine behind the AsyncEngine interface.

This is the component the reference delegates to vLLM/sglang subprocesses
(lib/engines/* — SURVEY.md §2.8); here it is in-process and TPU-native.
Round-2 architecture, shaped by measurement on real hardware:

- ONE unified step program per token-count bucket: a flat ragged run of
  tokens mixing prompt chunks and decode tokens (models/llama.py
  forward_ragged over ops/ragged_attention.py).  Decode rows ride along in
  every prefill step, so prefills never starve ITL, and the compile count
  stays tiny (the round-1 separate prefill/decode bucket grid still hit
  cold shapes in production mixes — a single cold XLA compile costs ~15s).
- a fused multi-step decode program (``decode_steps`` iterations per
  dispatch, sampled tokens fed forward ON DEVICE) for the steady state;
- an asynchronous decode PIPELINE: up to ``pipeline_depth`` fused dispatches
  in flight, with the token carry staying on device between dispatches and
  host readback overlapped.  Measured on the tunneled v5e chip: a
  device→host fetch costs ~100ms while a batch-16 decode step costs ~5ms —
  without the pipeline the fetch dominates 20:1.  Stop conditions are
  applied with bounded lag; over-decoded tokens are discarded host-side and
  never land in sealed KV blocks (block sealing happens host-side only for
  accepted tokens).
- KV cache lives in HBM as donated jit operands — scatters update in place;
- KV events (stored/removed, chained hashes) and ForwardPassMetrics are
  emitted exactly as the reference's C-API hooks do
  (lib/bindings/c/src/lib.rs:51-296), feeding the KV-aware router.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.kv_router.protocols import ForwardPassMetrics, KvCacheEvent
from ..llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from ..models.config import ModelConfig, get_config
from ..models.llama import PagedKVCache, RaggedBatch, forward_ragged, init_params
from ..ops.sampling import SamplingParams, sample_tokens
from ..parallel.mesh import (
    MeshConfig,
    make_mesh,
    pages_pspec,
    param_pspecs,
    shard_tree,
    sharding_tree,
)
from ..runtime.engine import AsyncEngine, Context, ResponseStream
from .config import EngineConfig
from .kv_manager import KvBlockManager
from .scheduler import Scheduler, SequenceState, StepPlan

logger = logging.getLogger(__name__)

_FINISHED = object()  # queue sentinel


def _scales_close(a, b, rtol: float = 1e-3) -> bool:
    """Stored-representation scale compatibility for KV transfers.

    Exact equality would silently disable disagg transfers between two
    workers that each ran kv_scale='auto' (independent calibration drifts
    at the ULP level across device generations / compiler versions).  The
    tolerance covers exactly that ULP/compiler drift and NO more: beyond it
    the quantized rows genuinely encode different values, and importing
    them raw would carry a systematic dequantization error — such imports
    are rejected and the caller prefills locally (r4 review: the earlier 5%
    tolerance silently accepted up to ~5% of real scale error)."""
    if a is None or b is None:
        return a is None and b is None
    av = np.asarray(a, np.float32).reshape(-1)
    bv = np.asarray(b, np.float32).reshape(-1)
    if av.shape != bv.shape and av.size != 1 and bv.size != 1:
        return False
    return bool(np.allclose(av, bv, rtol=rtol))


class TpuEngine(AsyncEngine):
    """Token-in/token-out engine (ExecutionContext equivalent)."""

    def __init__(
        self,
        cfg: EngineConfig,
        event_callback: Optional[Callable[[KvCacheEvent], None]] = None,
        params: Any = None,
    ):
        self.cfg = cfg
        from .xla_cache import setup_compilation_cache

        setup_compilation_cache(cfg.compilation_cache_dir)
        self.model_config: ModelConfig = get_config(cfg.model).with_overrides(
            dtype=cfg.dtype
        )
        if cfg.tp > 1 and self.model_config.num_kv_heads % cfg.tp != 0:
            # pages_pspec shards the combined 2*kv_heads axis over tp; a tp
            # that doesn't divide num_kv_heads would split a K/V pair of one
            # head across shards (XLA's divisibility check alone would let
            # e.g. tp == 2*num_kv_heads through).
            raise ValueError(
                f"tp={cfg.tp} must divide num_kv_heads="
                f"{self.model_config.num_kv_heads} (KV pages shard by head)"
            )
        self.kv = KvBlockManager(
            cfg.num_blocks,
            cfg.block_size,
            event_callback=event_callback,
            enable_prefix_caching=cfg.enable_prefix_caching,
        )
        self.scheduler = Scheduler(cfg, self.kv)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Any] = {}
        self._wake = asyncio.Event()
        self._closed = False
        self._loop_task: Optional[asyncio.Task] = None
        # Serialises device-state access: step functions donate the cache
        # buffers, so export/import must never observe a mid-step cache.
        self._device_lock = asyncio.Lock()
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._steps = 0
        # Multi-host: leader broadcasts every dispatch over this plane so
        # followers keep their device queues in SPMD lockstep (multihost.py).
        self._publisher = None
        self._mirror_carry: Any = None
        # Host KV offload tier (engine/host_cache.py).
        self.host_kv = None
        self._offload_queue: List[Tuple[int, Any]] = []
        self._offload_task: Optional[asyncio.Task] = None
        if cfg.host_cache_bytes > 0:
            # Multi-process: every host keeps a PER-HOST SHARDED tier — it
            # stores only the shards its own devices hold (gathers and
            # restores ride the leader→follower mirror plane, so all
            # processes run the same device programs in the same order).
            from .host_cache import HostKvStore

            self.host_kv = HostKvStore(cfg.host_cache_bytes)
        # Per-dispatch trace: (kind, wall_s, rows, device_tokens); the
        # pipeline records dispatch and fetch separately since they
        # overlap.  Bounded: a long-lived server must not grow it forever.
        self.step_trace: deque = deque(maxlen=65536)
        # Mixed-phase cadence: prefill chunks run since the last decode
        # burst (see _run_loop).
        self._chunks_since_burst = 0
        # Deferred token fetches (FIFO).  Prompt-completing unified steps
        # AND mixed-phase decode bursts start their token D2H
        # asynchronously, park their rows (awaiting_fetch), and keep the
        # loop dispatching; accepts happen at harvest points once the
        # round trip has overlapped with real work.  r4 measured one
        # blocking ~230ms fetch per request plus ~230ms of queue+RTT per
        # burst on the tunneled chip — together over half of
        # mid-concurrency wall time.
        self._pending_fetches: List[Tuple] = []

        # --- device state -------------------------------------------------
        mesh_cfg = MeshConfig(dp=cfg.dp, tp=cfg.tp, ep=cfg.ep, sp=cfg.sp)
        self.mesh = make_mesh(mesh_cfg) if mesh_cfg.num_devices > 1 else None
        # In a multi-process (multi-host) run, host-side step inputs must be
        # assembled into replicated GLOBAL arrays before they can feed a jit
        # over the global mesh.
        self._rep_sharding = None
        if jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            if self.mesh is None:
                raise ValueError(
                    "multi-process run needs a device mesh (dp*tp*ep == "
                    f"global devices, got {mesh_cfg.num_devices})"
                )
            self._rep_sharding = NamedSharding(self.mesh, PartitionSpec())
        if params is None:
            if cfg.checkpoint_path:
                from ..models.loader import load_params

                params = load_params(
                    self.model_config, cfg.checkpoint_path, quant=cfg.weight_quant
                )
            elif cfg.weight_quant:
                from ..models.quant import init_params_quantized

                # Direct int8 init — full-depth random bf16 would OOM the
                # chip before it could be quantized.
                params = init_params_quantized(
                    self.model_config, jax.random.PRNGKey(cfg.seed)
                )
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(cfg.seed))
        elif cfg.weight_quant:
            from ..models.quant import quantize_params

            params = quantize_params(params)  # no-op if already quantized
        cache = PagedKVCache.create(
            self.model_config,
            cfg.num_blocks,
            cfg.block_size,
            dtype=jnp.dtype(cfg.cache_dtype),
        )
        if self.mesh is not None:
            params = shard_tree(params, param_pspecs(self.model_config), self.mesh)
            cache = shard_tree(cache, PagedKVCache(pages_pspec()), self.mesh)
        self.params = params
        self.cache = cache
        # Quantized-scale resolution AFTER sharding: the calibration probe
        # runs over the (possibly tp/dp-sharded) params on the engine's own
        # mesh — a single-device probe would materialize the whole model on
        # one chip, OOMing exactly the tp>1 configurations quantized KV
        # exists for.
        if jnp.dtype(cfg.cache_dtype).itemsize == 1:
            if isinstance(cfg.kv_scale, str):
                if cfg.kv_scale != "auto":
                    raise ValueError(f"unknown kv_scale {cfg.kv_scale!r}")
                self.kv_scale = self._calibrate_kv_scales(params)
            elif isinstance(cfg.kv_scale, (list, tuple, np.ndarray)):
                self.kv_scale = np.asarray(cfg.kv_scale, np.float32)
            else:
                self.kv_scale = float(cfg.kv_scale)
        else:
            self.kv_scale = None

        model_config, bs = self.model_config, cfg.block_size
        attn_impl = cfg.attn_impl
        if attn_impl == "auto":
            from ..ops.ragged_attention import on_tpu

            attn_impl = "tpu" if on_tpu() else "xla"
        self.attn_impl = attn_impl
        S = cfg.max_batch
        mesh = self.mesh
        # Quantized (1-byte) KV pages: a static scale, or per-layer scales
        # calibrated at init (kv_scale == "auto"; resolved above, before
        # sharding).  Arrays fold into the forward algebraically
        # (models/llama.py), so they stay fully traced.
        kv_scale = self.kv_scale

        def _step(params, cache, rb, samp):
            logits, cache = forward_ragged(
                params, model_config, rb, cache, attn_impl=attn_impl,
                mesh=mesh, kv_scale=kv_scale,
            )
            out = sample_tokens(
                logits,
                samp.seeds,
                samp.steps,
                samp.temperature,
                samp.top_k,
                samp.top_p,
                samp.freq_penalty,
                samp.pres_penalty,
                samp.counts,
                samp.need_logprobs,
            )
            return out, cache

        T_steps = cfg.decode_steps

        def _multi(params, cache, tok0, steps0, counts0, pos0, tables, limits, samp):
            """``decode_steps`` fused decode iterations: one dispatch, the
            sampled token feeds the next step ON DEVICE, and the final token
            carry is returned un-fetched so the next dispatch can chain to it
            without a host round trip.

            ``pos0[s]`` is -1 for padding rows; ``limits[s]`` is the
            allocated KV capacity — steps whose position reaches it skip the
            cache write (their tokens are discarded host-side).  Output-token
            counts (penalties) and per-row rng stream positions advance ON
            DEVICE across the fused steps.
            """
            cu = jnp.arange(S + 1, dtype=jnp.int32)
            num = jnp.full((1,), S, jnp.int32)
            active = pos0 >= 0

            def body(carry, _):
                cache, tok, pos, steps, counts = carry
                posc = jnp.maximum(pos, 0)
                slot = (
                    tables[jnp.arange(S), posc // bs] * bs + posc % bs
                )
                writable = active & (posc < limits)
                slot = jnp.where(writable, slot, -1)
                rb = RaggedBatch(
                    token_ids=tok,
                    positions=posc,
                    slot_mapping=slot,
                    # Padding rows attend over 1 garbage token (never 0 —
                    # keeps the kernel's per-row loop well-defined).
                    kv_lens=jnp.where(active, jnp.minimum(pos + 1, limits), 1),
                    page_indices=tables,
                    cu_q_lens=cu,
                    num_seqs=num,
                )
                logits, cache = forward_ragged(
                    params, model_config, rb, cache, attn_impl=attn_impl,
                    mesh=mesh, kv_scale=kv_scale, decode=True,
                )
                out = sample_tokens(
                    logits,
                    samp.seeds,
                    steps,
                    samp.temperature,
                    samp.top_k,
                    samp.top_p,
                    samp.freq_penalty,
                    samp.pres_penalty,
                    counts,
                    samp.need_logprobs,
                )
                nxt = out.tokens
                counts = counts.at[jnp.arange(S), nxt].add(
                    active.astype(counts.dtype)
                )
                carry = (
                    cache,
                    nxt,
                    jnp.where(active, pos + 1, pos),
                    jnp.where(active, steps + 1, steps),
                    counts,
                )
                return carry, out

            (cache, last, _, steps_f, counts_f), outs = jax.lax.scan(
                body,
                (cache, tok0, pos0, steps0, counts0),
                None,
                length=T_steps,
            )
            # outs: SampleOut of [decode_steps, ...]; (last, steps_f,
            # counts_f) is the ON-DEVICE carry the next dispatch chains to.
            return outs, last, steps_f, counts_f, cache

        def _gather(cache, page_ids):
            # Batched block gather for host offload; OOB padding ids clamp
            # (their slices are ignored at store time).
            return cache.pages[:, page_ids]

        def _inject(cache, page_ids, new_pages):
            # Donated in-place page scatter for KV imports; padding ids are
            # out of range and dropped, so callers can bucket the page count
            # to bound recompiles.
            # Same quantization as the ragged write path (shared helper) —
            # injected/sp-prefilled blocks must never diverge numerically
            # from normal-prefill blocks under the same hashes.
            from ..ops.ragged_attention import quantize_for_cache

            pages = cache.pages.at[:, page_ids].set(
                quantize_for_cache(new_pages, cache.pages.dtype), mode="drop"
            )
            return PagedKVCache(pages)

        donate = (1,)
        if self.mesh is None:
            self._step_fn = jax.jit(_step, donate_argnums=donate)
            self._multi_fn = jax.jit(_multi, donate_argnums=donate)
            self._inject_fn = jax.jit(_inject, donate_argnums=(0,))
        else:
            cache_sh = sharding_tree(cache, PagedKVCache(pages_pspec()), self.mesh)
            self._step_fn = jax.jit(
                _step, donate_argnums=donate, out_shardings=(None, cache_sh)
            )
            self._multi_fn = jax.jit(
                _multi,
                donate_argnums=donate,
                out_shardings=(None, None, None, None, cache_sh),
            )
            self._inject_fn = jax.jit(
                _inject, donate_argnums=(0,), out_shardings=cache_sh
            )
        self._gather_fn = jax.jit(_gather)  # host offload (no donation)

        if cfg.sp > 1:
            from ..models.llama import forward_sp_prefill

            def _sp(params, toks, valid):
                return forward_sp_prefill(
                    params, model_config, toks, valid, mesh
                )

            self._sp_fn = jax.jit(_sp)
        else:
            self._sp_fn = None
        # Cached all-zeros penalty-counts buffer (see _sampling_arrays).
        self._zero_counts = jnp.zeros(
            (S, self.model_config.vocab_size), jnp.int16
        )
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._zero_counts = jax.device_put(
                self._zero_counts, NamedSharding(self.mesh, PartitionSpec())
            ) if jax.process_count() == 1 else self._prep(
                np.zeros((S, self.model_config.vocab_size), np.int16)
            )

    def _calibrate_kv_scales(self, params) -> np.ndarray:
        """Per-layer quantization scales from a probe forward: run a short
        deterministic token run through the model with a throwaway bf16
        cache, take each layer's max |K/V|, and map it to the target
        dtype's representable max.  Runs on the engine's own mesh (sharded
        params + sharded probe cache), so tp>1 models that don't fit one
        chip calibrate fine; multi-host deployments pass the calibrated
        vector explicitly via kv_scale."""
        if jax.process_count() > 1:
            raise ValueError(
                "kv_scale='auto' calibrates on one process; run calibration "
                "single-host and pass the resulting scales explicitly"
            )
        cfg, mc = self.cfg, self.model_config
        # Probe length bounded so nb (+1 slack) fits a single row's table.
        T = min(128, (cfg.max_blocks_per_seq - 1) * cfg.block_size)
        nb = (T + cfg.block_size - 1) // cfg.block_size + 1
        probe = PagedKVCache.create(mc, nb, cfg.block_size, dtype=jnp.bfloat16)
        if self.mesh is not None:
            probe = shard_tree(probe, PagedKVCache(pages_pspec()), self.mesh)
        toks = ((np.arange(T) * 2654435761) % mc.vocab_size).astype(np.int32)
        pos = np.arange(T, dtype=np.int32)
        S = cfg.max_batch
        # Table width = the probe's own nb pages, NOT max_blocks_per_seq:
        # the XLA fallback materializes [T, width*bs, 2KV, hd] f32, which
        # at long-context configs would be tens of GB.
        tables = np.zeros((S, nb), np.int32)
        tables[0, :nb] = np.arange(nb)
        cu = np.zeros((S + 1,), np.int32)
        cu[1:] = T
        rb = RaggedBatch(
            token_ids=toks,
            positions=pos,
            slot_mapping=pos,  # consecutive slots in blocks 0..nb
            kv_lens=np.asarray([T] + [0] * (S - 1), np.int32),
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([1], np.int32),
        )
        _, probe = jax.jit(
            lambda p, c: forward_ragged(
                p, mc, rb, c, attn_impl="xla", mesh=self.mesh
            )
        )(params, probe)
        # [L, nb, ps, 2KV, hd] → per-layer max |value| over everything else.
        maxabs = np.asarray(
            jnp.max(
                jnp.abs(probe.pages.astype(jnp.float32)), axis=(1, 2, 3, 4)
            )
        )
        dt = jnp.dtype(cfg.cache_dtype)
        if jnp.issubdtype(dt, jnp.integer):
            qmax = float(jnp.iinfo(dt).max)
        else:
            qmax = float(jnp.finfo(dt).max)  # e4m3 → 448
        scales = np.maximum(maxabs / qmax, 1e-6).astype(np.float32)
        logger.info(
            "calibrated per-layer kv scales (dtype %s): min %.4g max %.4g",
            dt, scales.min(), scales.max(),
        )
        return scales

    def _kv_scale_repr(self):
        """JSON-safe scale for transfer payloads: None, float, or list."""
        if self.kv_scale is None:
            return None
        a = np.asarray(self.kv_scale, np.float32).reshape(-1)
        return [float(x) for x in a] if a.size > 1 else float(a[0])

    # ------------------------------------------------------------ multi-host
    def attach_publisher(self, publisher) -> None:
        """Leader side: broadcast every device dispatch to the followers
        (engine/multihost.py StepPublisher)."""
        self._publisher = publisher

    def _prep(self, tree: Any) -> Any:
        """Host arrays → replicated global arrays when multi-process."""
        if self._rep_sharding is None:
            return tree
        from ..parallel.distributed import global_array

        return jax.tree_util.tree_map(
            lambda x: global_array(x, self._rep_sharding), tree
        )

    async def run_warmup(self) -> Dict[str, int]:
        """warmup() that keeps followers in lockstep (use in serving paths;
        plain warmup() is fine single-process)."""
        async with self._device_lock:
            if self._publisher is not None:
                await self._publisher.publish("warmup")
            return await asyncio.to_thread(self.warmup)

    async def mirror_step(self, kind: str, payload: Tuple) -> None:
        """Follower side: replay one leader dispatch (same jitted fns, same
        global arrays, same order → SPMD lockstep)."""
        if kind == "warmup":
            await asyncio.to_thread(self.warmup)
        elif kind == "unified":
            rb, samp = payload

            def run_u():
                _, self.cache = self._step_fn(
                    self.params,
                    self.cache,
                    self._prep(rb),
                    self._prep(samp),
                )

            async with self._device_lock:
                await asyncio.to_thread(run_u)
        elif kind == "multi":
            tok0, pos0, tables, limits, samp = payload
            carry = self._mirror_carry if tok0 is None else None

            def run_m():
                samp_d = self._prep(samp)
                if carry is None:
                    tok, steps0, counts0 = (
                        self._prep(tok0), samp_d.steps, samp_d.counts
                    )
                else:
                    tok, steps0, counts0 = carry
                _, last, steps_f, counts_f, self.cache = self._multi_fn(
                    self.params,
                    self.cache,
                    tok,
                    steps0,
                    counts0,
                    *self._prep((pos0, tables, limits)),
                    samp_d,
                )
                return (last, steps_f, counts_f)

            async with self._device_lock:
                self._mirror_carry = await asyncio.to_thread(run_m)
        elif kind == "inject":
            page_ids, comb_p = payload

            def run_i():
                self.cache = self._inject_fn(
                    self.cache, *self._prep((page_ids, comb_p))
                )

            async with self._device_lock:
                await asyncio.to_thread(run_i)
        elif kind == "offload":
            ids, hashes = payload
            async with self._device_lock:
                await asyncio.to_thread(self._offload_store, ids, hashes)
        elif kind == "restore_host":
            page_ids, hashes = payload
            async with self._device_lock:
                await asyncio.to_thread(self._restore_inject, page_ids, hashes)
        else:
            raise ValueError(f"unknown mirror step kind {kind!r}")

    # ---------------------------------------------------------------- warmup
    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program count per jitted entry (cache sizes).  The bench
        asserts these do not grow inside its timed window."""
        out: Dict[str, int] = {}
        for name, fn in (
            ("step", self._step_fn),
            ("multi", self._multi_fn),
            ("inject", self._inject_fn),
        ):
            try:
                out[name] = fn._cache_size()
            except AttributeError:  # older jax: best-effort
                out[name] = -1
        return out

    def reachable_token_buckets(self) -> List[int]:
        """Every token bucket the scheduler can hand _run_unified: up to
        max_batch decode rows ride alongside up to prefill_chunk prompt
        tokens in one step (decode rows don't consume the prefill budget),
        so totals range 1..prefill_chunk + max_batch."""
        hi = self.cfg.bucket_tokens(self.cfg.prefill_chunk + self.cfg.max_batch)
        buckets, b = [], self.cfg.bucket_tokens(1)
        while b < hi:
            buckets.append(b)
            b *= 2
        buckets.append(hi)
        return buckets

    def warmup(self) -> Dict[str, int]:
        """Pre-compile every device program the serving loop can dispatch —
        one unified step per reachable token bucket plus the fused decode
        program — so no cold XLA compile (~15s on TPU) ever lands inside a
        request.  All runs carry slot/pos = -1 so cache writes are dropped
        (write_kv_ragged) and contents are untouched.  Returns compile_counts.
        """
        cfg = self.cfg
        S, PP = cfg.max_batch, cfg.max_blocks_per_seq
        samp = self._sampling_arrays([])  # greedy defaults, cached counts
        for T in self.reachable_token_buckets():
            cu = np.zeros((S + 1,), np.int32)
            cu[1:] = T  # one row owns every token; others empty
            rb = RaggedBatch(
                token_ids=np.zeros((T,), np.int32),
                positions=np.zeros((T,), np.int32),
                slot_mapping=np.full((T,), -1, np.int32),  # writes dropped
                # kv_len == q_len: the ragged contract (and the pallas
                # kernel's validation) requires q_len <= kv_len per row.
                kv_lens=np.asarray([T] + [0] * (S - 1), np.int32),
                page_indices=np.zeros((S, PP), np.int32),
                cu_q_lens=cu,
                num_seqs=np.asarray([1], np.int32),
            )
            out, self.cache = self._step_fn(
                self.params, self.cache, self._prep(rb), self._prep(samp)
            )
        if cfg.decode_steps > 1:
            args = self._prep(
                (
                    np.full((S,), -1, np.int32),  # every row inactive
                    np.zeros((S, PP), np.int32),
                    np.zeros((S,), np.int32),
                )
            )
            _, last, steps_f, counts_f, self.cache = self._multi_fn(
                self.params,
                self.cache,
                self._prep(np.zeros((S,), np.int32)),
                self._prep(samp.steps),
                samp.counts,
                *args,
                self._prep(samp),
            )
            # Chain once more with the DEVICE carry: pipeline dispatches 2+
            # feed the previous outputs back in, and committed device arrays
            # key a different executable-cache entry than the uncommitted
            # numpy first dispatch.
            _, last, _, _, self.cache = self._multi_fn(
                self.params, self.cache, last, steps_f, counts_f,
                *args, self._prep(samp)
            )
            # A real fetch, not block_until_ready: some remote-execution
            # backends treat block_until_ready as a local no-op, and warmup
            # must not return with compiles/executions still queued (the
            # first real request would absorb them).
            np.asarray(last)
        else:
            np.asarray(out.tokens)
        if self._sp_fn is not None:
            # Every reachable sp-prefill token bucket (pow2, sp multiple,
            # sp_prefill_min..max_model_len) — a cold whole-model compile
            # must never land inside a request.
            lo = max(cfg.sp, 1 << (max(1, cfg.sp_prefill_min) - 1).bit_length())
            hi = max(lo, 1 << (cfg.max_model_len - 1).bit_length())
            t = lo
            while True:
                Tg = t + (-t) % cfg.sp
                logits_sp, _ = self._sp_fn(
                    self.params,
                    np.zeros((Tg,), np.int32),
                    np.asarray(Tg, np.int32),
                )
                np.asarray(logits_sp)  # real fetch (see above)
                if t >= hi:
                    break
                t *= 2
        return self.compile_counts()

    # ------------------------------------------------------------ public API
    async def generate(self, request: Context) -> ResponseStream:
        if self._closed:
            raise RuntimeError("engine is closed")
        pre = PreprocessedRequest.from_dict(request.data)
        if len(pre.token_ids) > self.cfg.max_model_len:
            raise ValueError(
                f"prompt length {len(pre.token_ids)} exceeds max_model_len "
                f"{self.cfg.max_model_len}"
            )
        self._ensure_loop()
        prepared = 0
        if self.host_kv is not None and len(self.host_kv):
            # Pull any evicted prefix blocks back from host RAM BEFORE
            # admission, so the scheduler sees them as prefix-cache hits
            # (the reference's restore-ahead-of-prefill TTFT win).
            prepared += await self._restore_from_host(list(pre.token_ids))
        if (
            self._sp_fn is not None
            and len(pre.token_ids) >= self.cfg.sp_prefill_min
            and jax.process_count() == 1
        ):
            # Long prompt: one sequence-parallel whole-prompt pass seals the
            # complete blocks ahead of admission (ring attention over "sp").
            # DELIBERATELY single-process: sp prefill is scoped to dedicated
            # disagg PREFILL WORKERS (cli run --disagg prefill --sp N), each
            # a single-host engine owning its own sp mesh — decode fleets
            # scale across hosts via dp/tp while prefill workers ring over
            # their local slice and ship blocks through the KV transfer
            # plane (the reference's disagg split, docs/architecture.md).
            prepared += await self._sp_prefill(list(pre.token_ids))
        seq = SequenceState.from_request(request.id, pre, self.cfg)
        if prepared:
            # PIN the just-sealed prefix until admission: the sealed blocks
            # sit in the reuse pool, where a concurrent request's
            # allocations could LRU-evict them before allocate_sequence
            # matches — silently wasting the whole sp/restore pass.  The
            # scheduler releases the pin when admission lands (or the
            # request is rejected/cancelled).
            seq.pin_ids = self._pin_prefix(list(pre.token_ids))
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request.id] = queue
        self._contexts[request.id] = request.ctx
        self.scheduler.add(seq)
        self._wake.set()

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            try:
                while True:
                    item = await queue.get()
                    if item is _FINISHED:
                        return
                    yield item
            finally:
                self._queues.pop(request.id, None)
                self._contexts.pop(request.id, None)

        return ResponseStream(gen(), request.ctx)

    def set_event_callback(
        self, callback: Optional[Callable[[KvCacheEvent], None]]
    ) -> None:
        """Attach/replace the KV event sink (e.g. a KvEventPublisher) after
        construction — the CLI builds the engine before the runtime exists."""
        self.kv._event_callback = callback

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            request_active_slots=self.scheduler.num_running,
            request_total_slots=self.cfg.max_batch,
            kv_active_blocks=self.kv.active_blocks,
            kv_total_blocks=self.kv.num_blocks,
            num_requests_waiting=self.scheduler.num_waiting,
            gpu_cache_usage_perc=self.kv.usage,
            gpu_prefix_cache_hit_rate=self.kv.hit_rate,
        )

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        if self._offload_task is not None:
            self._offload_task.cancel()
            try:
                await self._offload_task
            except asyncio.CancelledError:
                pass
            self._offload_task = None
        if self._publisher is not None:
            await self._publisher.close()
            self._publisher = None
        # Fail whatever is still in flight so no generate() stream hangs.
        self._fail_all()

    # --------------------------------------------------- KV export / import
    #
    # TPU counterpart of the reference's block_copy.cu + NIXL transfer
    # (lib/llm/src/kernels/block_copy.cu, kv/layer.rs:100-772): whole pages
    # move between workers as host-staged arrays (msgpack binary over the
    # service plane; ICI device-to-device when workers share a pod slice).
    # Imported pages are sealed under their chained hashes, so the decode
    # scheduler sees remote-prefilled prompts as ordinary prefix-cache hits.

    async def export_prompt_blocks(
        self, token_ids: List[int], start_block: int = 0, max_blocks: int = 0
    ) -> Optional[Dict[str, Any]]:
        """Gather cached KV for ``token_ids``'s complete blocks to host.

        Exports the longest RESIDENT run starting at ``start_block`` (not
        all-or-nothing — a prompt that lost tail blocks to eviction still
        transfers its resident prefix; round-2 returned None in that case
        and recomputed everything).  ``max_blocks`` bounds the run (chunked
        transfer).  Returns None when nothing is resident at start_block.
        """
        from ..tokens import hash_token_blocks

        if jax.process_count() > 1:
            # Sharded global pages can't be gathered from one host (same
            # restriction as host_cache_bytes); refuse cleanly at request
            # time so the caller falls back to local prefill instead of
            # hanging on a non-addressable array (ADVICE r3).
            return None
        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        ids: List[int] = []
        for tb in blocks[start_block:]:
            bid = self.kv._by_hash.get(tb.sequence_hash)
            if bid is None:
                break
            ids.append(bid)
            if max_blocks and len(ids) >= max_blocks:
                break
        if not ids:
            return None
        async with self._device_lock:
            pages = np.asarray(self.cache.pages[:, np.asarray(ids, np.int32)])
        k = pages[:, :, :, 0::2]  # [L, n, page_size, KV, hd]
        v = pages[:, :, :, 1::2]
        return {
            "n_blocks": len(ids),
            "start_block": start_block,
            "block_size": self.cfg.block_size,
            "dtype": str(k.dtype),
            # Stored representation metadata: the importer must match (a
            # different quantization scale/dtype would seal wrongly-scaled
            # KV under valid hashes).
            "kv_scale": self._kv_scale_repr(),
            "shape": list(k.shape),
            "k": np.ascontiguousarray(k).tobytes(),
            "v": np.ascontiguousarray(v).tobytes(),
        }

    async def inject_blocks(self, token_ids: List[int], payload: Dict[str, Any]) -> int:
        """Write transferred KV into this engine's cache as sealed blocks.

        ``payload["start_block"]`` supports chunked transfers: chunk k's
        blocks seal under their chained hashes as they arrive, so decode can
        overlap with the remaining chunks' transfer (match_prefix walks from
        block 0, so chunks are useful as soon as their predecessors landed —
        the sender streams them in order).

        Returns the number of tokens covered by this injection.  The blocks
        are immediately released to the reuse pool (contents intact), so the
        very next generate() for these tokens admits with a prefix hit — no
        special remote-prefill state in the scheduler.
        """
        from ..tokens import hash_token_blocks

        start = int(payload.get("start_block", 0))
        blocks = hash_token_blocks(token_ids, self.cfg.block_size)[start:]
        n = min(int(payload["n_blocks"]), len(blocks))
        if n == 0:
            return 0
        blocks = blocks[:n]
        alloc = self.kv.allocate_sequence(blocks, n)
        if alloc is None:
            return 0  # no capacity; caller falls back to local prefill
        if int(payload.get("block_size", self.cfg.block_size)) != self.cfg.block_size:
            # Mismatched layouts would seal misaligned KV under valid hashes
            # — refuse and let the caller prefill locally.
            logger.warning(
                "rejecting KV import: block_size %s != local %s",
                payload.get("block_size"),
                self.cfg.block_size,
            )
            self.kv.free_sequence(alloc[0])
            return 0
        local_scale = self._kv_scale_repr()
        if (
            payload.get("dtype", str(jnp.dtype(self.cfg.cache_dtype)))
            != str(jnp.dtype(self.cfg.cache_dtype))
            or not _scales_close(
                payload.get("kv_scale", local_scale), local_scale
            )
        ):
            # Stored-representation mismatch (quantization dtype/scale):
            # importing raw rows would mis-scale the prefix silently.
            logger.warning(
                "rejecting KV import: stored repr %s/scale %s != local %s/%s",
                payload.get("dtype"), payload.get("kv_scale"),
                jnp.dtype(self.cfg.cache_dtype), local_scale,
            )
            self.kv.free_sequence(alloc[0])
            return 0
        ids, cached = alloc
        shape = tuple(payload["shape"])
        name = payload["dtype"]
        dt = jnp.dtype(name)  # ml_dtypes registers bf16/fp8 names
        k = np.frombuffer(payload["k"], dtype=dt).reshape(shape)[:, :n]
        v = np.frombuffer(payload["v"], dtype=dt).reshape(shape)[:, :n]
        # Interleave back to combined pages [L, n, ps, 2KV, hd] (K even).
        comb = np.stack([k, v], axis=4).reshape(
            k.shape[0], n, k.shape[2], 2 * k.shape[3], k.shape[4]
        )
        # Pad the page count to a power-of-two bucket so _inject_fn compiles
        # once per bucket, not once per distinct imported prompt length.
        pad = 1 << max(0, (n - 1).bit_length())
        page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
        page_ids[:n] = ids
        comb_p = np.zeros(comb.shape[:1] + (pad,) + comb.shape[2:], comb.dtype)
        comb_p[:, :n] = comb

        async with self._device_lock:
            # Lock-HOLD wall only (t0 inside the lock — queueing behind a
            # decode chunk is the scheduler working as intended, not import
            # cost): the decode/transfer-overlap contract is that an import
            # never blocks decode longer than ONE chunk's scatter
            # (tests/test_disagg.py overlap test reads this).
            t0 = time.perf_counter()
            # Publish under the device lock (broadcast order == enqueue
            # order; see _run_unified).
            if self._publisher is not None:
                await self._publisher.publish("inject", (page_ids, comb_p))
            # to_thread: compile/execute must not stall the engine loop.
            self.cache = await asyncio.to_thread(
                self._inject_fn, self.cache, *self._prep((page_ids, comb_p))
            )
            hold = time.perf_counter() - t0
        self.step_trace.append(("inject", hold, n, 0))
        for bid, tb in zip(ids, blocks):
            self.kv.seal_block(bid, tb)
        self.kv.free_sequence(ids)
        return n * self.cfg.block_size

    async def inject_blocks_from_device(
        self, token_ids: List[int], pages_dev, n: int, start_block: int = 0
    ) -> int:
        """Seal ``n`` transferred blocks whose pages are ALREADY on device
        (the ICI/device_put fast path — no host staging).  ``pages_dev`` is
        [L, pad, ps, 2KV, hd] with the first n slots valid."""
        from ..tokens import hash_token_blocks

        if jax.process_count() > 1:
            # Device handles can't cross the leader/follower broadcast; the
            # host-staged inject_blocks path handles multi-host transfers.
            return 0
        blocks = hash_token_blocks(token_ids, self.cfg.block_size)[start_block:]
        n = min(n, len(blocks))
        if n == 0:
            return 0
        alloc = self.kv.allocate_sequence(blocks[:n], n)
        if alloc is None:
            return 0
        ids, _ = alloc
        pad = pages_dev.shape[1]
        page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
        page_ids[:n] = ids
        async with self._device_lock:
            t0 = time.perf_counter()  # lock HOLD, not wait (see inject_blocks)
            self.cache = await asyncio.to_thread(
                self._inject_fn, self.cache, page_ids, pages_dev
            )
            hold = time.perf_counter() - t0
        self.step_trace.append(("inject", hold, n, 0))
        for bid, tb in zip(ids, blocks[:n]):
            self.kv.seal_block(bid, tb)
        self.kv.free_sequence(ids)
        return n * self.cfg.block_size

    def _pin_prefix(self, token_ids: List[int]):
        """Take references on the resident prefix blocks of ``token_ids``
        (see generate(): keeps pre-admission sp/restore work alive)."""
        from ..tokens import hash_token_blocks

        return self.kv.acquire_prefix(
            hash_token_blocks(token_ids, self.cfg.block_size)
        )

    def estimate_prefix_hit(self, token_ids: List[int]) -> int:
        """Tokens of ``token_ids`` already resident locally (router input)."""
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        return len(self.kv.match_prefix(blocks)) * self.cfg.block_size

    # -------------------------------------------------------------- the loop
    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(self._run_loop())
        if self.host_kv is not None and (
            self._offload_task is None or self._offload_task.done()
        ):
            self._offload_task = asyncio.get_running_loop().create_task(
                self._offload_pump()
            )

    async def _run_loop(self) -> None:
        while not self._closed:
            self._cancel_stopped()
            try:
                while (
                    self._pending_fetches
                    and self._pending_fetches[0][1].done()
                ):
                    # Completed background fetches apply for free — parked
                    # rows resume without the loop ever blocking on D2H.
                    await self._harvest_pending()
            except Exception:
                # Same engine-fatal contract as the step path below: a
                # failed D2H must fail all streams, never strand them.
                logger.exception("deferred fetch failed")
                self._fail_all()
                return
            plan = self.scheduler.schedule()
            for seq in self.scheduler.take_rejected():
                self._finish(seq, FinishReason.ERROR)
            if plan is None:
                if self._pending_fetches:
                    try:
                        await self._harvest_pending(all_pending=True)
                    except Exception:
                        logger.exception("deferred fetch failed")
                        self._fail_all()
                        return
                    continue
                if self.scheduler.num_waiting and not self.scheduler.num_running:
                    # e.g. decode just preempted everyone back to waiting:
                    # retry admission immediately (terminates: each pass
                    # admits or rejects at least one waiting sequence).
                    await asyncio.sleep(0)
                    continue
                # Idle: running is empty (running sequences always yield
                # work), so sleep until a new request arrives.
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                did_work = False
                if plan.pure_decode and self.cfg.decode_steps > 1:
                    if self._pending_fetches:
                        # Parked rows must not sit out a whole fused
                        # pipeline run — fold them in first.
                        await self._harvest_pending(all_pending=True)
                        continue
                    # Leaving the mixed regime: a stale chunk count must not
                    # trigger an immediate burst in the NEXT mixed phase.
                    self._chunks_since_burst = 0
                    did_work = await self._decode_pipeline(
                        [seq for seq, _, _ in plan.items]
                    )
                if not did_work and self.cfg.decode_steps > 1:
                    # Mixed phase (prefill + decode in one plan): running
                    # decode rows inside the unified step gives them ONE
                    # token per dispatch+fetch round trip — with prefill
                    # almost always active under continuous arrivals, that
                    # made conc 16 SLOWER than conc 8 (r4 ladder).  Instead:
                    # fetch-free prefill-only steps at device rate, and
                    # every cfg.prefill_chunks_per_burst of them one fused
                    # burst advancing every decode row decode_steps tokens
                    # for a single round trip.  (Bursting after EVERY chunk
                    # was tried first and throttled prefill ~3x: 8 requests'
                    # first wave alone is ~47 chunks.)
                    decode_items = [
                        it for it in plan.items if it[1] >= len(it[0].prompt)
                    ]
                    prefill_items = [
                        it for it in plan.items if it[1] < len(it[0].prompt)
                    ]
                    if decode_items and prefill_items:
                        await self._run_unified(StepPlan(prefill_items))
                        self._chunks_since_burst += 1
                        if (
                            self._chunks_since_burst
                            >= self.cfg.prefill_chunks_per_burst
                        ):
                            self._chunks_since_burst = 0
                            if not await self._decode_burst(
                                [s for s, _, _ in decode_items]
                            ):
                                # No KV headroom for a whole burst: the
                                # 1-token slots are already allocated.
                                self.step_trace.append(
                                    ("burst_fallback", 0.0, len(decode_items), 0)
                                )
                                await self._run_unified(StepPlan(decode_items))
                        did_work = True
                if not did_work:
                    # Not enough KV headroom for a fused window (or not a
                    # pure-decode state): single unified step still advances
                    # every sequence one token, and finishes free blocks.
                    await self._run_unified(plan)
            except Exception:  # engine-fatal: fail all inflight requests
                logger.exception("engine step failed")
                self._fail_all()
                return
            self._steps += 1
            await asyncio.sleep(0)  # let ingress/egress run between steps

    def _cancel_stopped(self) -> None:
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            ctx = self._contexts.get(seq.request_id)
            if ctx is not None and ctx.is_stopped and not seq.finished:
                seq.finished = True
                self.scheduler.remove(seq)
                self._finish(seq, FinishReason.CANCELLED)

    def _fail_all(self) -> None:
        self._pending_fetches.clear()  # drop in-flight token fetches
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            seq.awaiting_fetch = False
            self.scheduler.remove(seq)
            self._finish(seq, FinishReason.ERROR)

    # ------------------------------------------------------------ batch build
    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _sampling_arrays(self, seqs: List[SequenceState]) -> SamplingParams:
        """Build the per-row device sampling state for this step.

        The counts matrix ([S, V], penalties) is the engine's cached
        all-zeros DEVICE buffer unless some row actually uses a penalty —
        the common path never pays the [S, V] host→device transfer."""
        S = self.cfg.max_batch
        V = self.model_config.vocab_size
        seeds = np.zeros((S,), np.uint32)
        steps = np.zeros((S,), np.int32)
        temp = np.zeros((S,), np.float32)
        topk = np.zeros((S,), np.int32)
        topp = np.ones((S,), np.float32)
        fpen = np.zeros((S,), np.float32)
        ppen = np.zeros((S,), np.float32)
        need_lp = False
        any_pen = False
        for i, seq in enumerate(seqs):
            seeds[i] = seq.sampling_seed
            steps[i] = seq.num_output_tokens
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
            fpen[i] = seq.freq_penalty
            ppen[i] = seq.pres_penalty
            need_lp = need_lp or seq.logprobs is not None
            any_pen = any_pen or seq.freq_penalty != 0 or seq.pres_penalty != 0
        if any_pen:
            counts_np = np.zeros((S, V), np.int16)
            for i, seq in enumerate(seqs):
                out = np.asarray(seq.output, np.int64)
                if out.size:
                    np.add.at(counts_np[i], out % V, 1)
            if self._rep_sharding is not None:
                counts = self._prep(counts_np)
            else:
                counts = jnp.asarray(counts_np)  # committed, key matches cache
        else:
            counts = self._zero_counts
        return SamplingParams(
            seeds=seeds,
            steps=steps,
            temperature=temp,
            top_k=topk,
            top_p=topp,
            freq_penalty=fpen,
            pres_penalty=ppen,
            counts=counts,
            need_logprobs=np.asarray(need_lp),
        )

    def _tables_row(self, out: np.ndarray, i: int, seq: SequenceState) -> None:
        ids = seq.block_ids[: out.shape[1]]
        out[i, : len(ids)] = ids

    def _build_ragged(self, items) -> RaggedBatch:
        bs = self.cfg.block_size
        S = self.cfg.max_batch
        PP = self.cfg.max_blocks_per_seq
        total = sum(n for _, _, n in items)
        T = self.cfg.bucket_tokens(total)

        tok = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        slots = np.full((T,), -1, np.int32)
        kv_lens = np.zeros((S,), np.int32)
        tables = np.zeros((S, PP), np.int32)
        cu = np.zeros((S + 1,), np.int32)
        at = 0
        for i, (seq, start, n) in enumerate(items):
            all_toks = seq.prompt + seq.output
            tok[at : at + n] = all_toks[start : start + n]
            p = np.arange(start, start + n, dtype=np.int32)
            pos[at : at + n] = p
            blk = np.asarray(seq.block_ids, np.int32)
            slots[at : at + n] = blk[p // bs] * bs + p % bs
            self._tables_row(tables, i, seq)
            kv_lens[i] = start + n
            at += n
            cu[i + 1] = at
        cu[len(items) + 1 :] = at
        return RaggedBatch(
            token_ids=tok,
            positions=pos,
            slot_mapping=slots,
            kv_lens=kv_lens,
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([len(items)], np.int32),
        )

    # ------------------------------------------------------ unified step path
    async def _run_unified(self, plan: StepPlan) -> None:
        rb = self._build_ragged(plan.items)
        samp = self._sampling_arrays([s for s, _, _ in plan.items])
        need_lp = bool(samp.need_logprobs)
        # A step whose every row stays mid-prefill produces sampled tokens
        # nobody consumes — skip the device→host fetch entirely and let the
        # next chunk's dispatch queue behind this one.  Over the tunneled
        # chip a blocking fetch costs ~100ms/chunk, which made chunked
        # prefill RTT-bound (r3: TTFT 1343ms for ISL 3000 vs ~200ms of
        # device compute); co-located it still saves a sync per chunk.
        need_tokens = any(
            start + n >= len(seq.prompt) for seq, start, n in plan.items
        )
        if self._rep_sharding is not None:
            rb_d, samp_d = self._prep((rb, samp))
        else:
            rb_d, samp_d = rb, samp
        step = self._step_fn
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete

        def run():
            out, self.cache = step(self.params, self.cache, rb_d, samp_d)
            if need_tokens:
                # Start the D2H now; the accept is deferred to a harvest
                # point so the round trip overlaps later dispatches.
                try:
                    out.tokens.copy_to_host_async()
                    if need_lp:
                        out.logprob.copy_to_host_async()
                        out.top_ids.copy_to_host_async()
                        out.top_logprobs.copy_to_host_async()
                except AttributeError:
                    pass
            return out

        t0 = time.perf_counter()
        async with self._device_lock:
            # Publish INSIDE the device lock: broadcast order must equal
            # device enqueue order or followers replay a different program
            # sequence than the leader ran (SPMD divergence).
            if self._publisher is not None:
                await self._publisher.publish(
                    "unified",
                    (rb, jax.tree_util.tree_map(np.asarray, samp)),
                )
            out = await asyncio.to_thread(run)
        self.step_trace.append(
            (
                "unified_fetch" if need_tokens else "unified",
                time.perf_counter() - t0,
                len(plan.items),
                len(rb.token_ids),
            )
        )

        pending_rows: List[Tuple[SequenceState, int]] = []
        for i, (seq, start, n) in enumerate(plan.items):
            if seq.finished:
                continue
            if start >= len(seq.prompt):
                # Decode row: the fed token joins the hash stream.
                seq.block_seq.append((seq.prompt + seq.output)[start])
            seq.num_computed = start + n
            self._seal_completed_blocks(seq)
            if not seq.in_prefill:
                # This row's sampled token is in flight; park the row until
                # a harvest point applies it.
                seq.awaiting_fetch = True
                pending_rows.append((seq, i))
        if pending_rows:
            self._stash_fetch("first", out, need_lp, pending_rows)

    def _stash_fetch(self, kind: str, out, need_lp: bool, *meta) -> None:
        """Park a dispatched step's token fetch: the np.asarray runs on a
        worker thread STARTING NOW (the D2H was already initiated with
        copy_to_host_async), and the loop applies the result at a harvest
        point once the task completes — the device round trip never blocks
        dispatching."""

        def fetch():
            if need_lp:
                return (
                    np.asarray(out.tokens),
                    np.asarray(out.logprob),
                    np.asarray(out.top_ids),
                    np.asarray(out.top_logprobs),
                )
            return np.asarray(out.tokens), None, None, None

        task = asyncio.get_running_loop().create_task(asyncio.to_thread(fetch))
        self._pending_fetches.append((kind, task, *meta))

    async def _harvest_pending(self, all_pending: bool = False) -> None:
        """Apply deferred fetches in dispatch order.  Harvests the oldest
        entry (awaiting its background task), or everything outstanding."""
        while self._pending_fetches:
            entry = self._pending_fetches.pop(0)
            kind, task = entry[0], entry[1]

            t0 = time.perf_counter()
            sampled, logp, top_ids, top_lp = await task
            self.step_trace.append(
                (
                    f"{kind}_harvest",
                    time.perf_counter() - t0,
                    len(entry[2]),
                    0,
                )
            )
            if kind == "first":
                for seq, i in entry[2]:
                    seq.awaiting_fetch = False
                    if seq.finished:
                        continue  # cancelled while the token was in flight
                    self._accept_token(
                        seq,
                        int(sampled[i]),
                        logprobs=self._lp_info(seq, i, logp, top_ids, top_lp),
                    )
            else:  # burst
                members, pos0 = entry[2], entry[3]
                bs = self.cfg.block_size
                finished: List[SequenceState] = []
                for t in range(sampled.shape[0]):
                    for i, seq in enumerate(members):
                        seq.awaiting_fetch = False
                        if seq.finished or pos0[i] < 0:
                            continue
                        if seq.num_computed != pos0[i] + t:
                            continue  # stopped earlier in this burst
                        if seq.num_computed >= len(seq.block_ids) * bs:
                            continue  # beyond allocation: never KV-backed
                        fed = (seq.prompt + seq.output)[seq.num_computed]
                        if seq.num_computed >= len(seq.prompt):
                            seq.block_seq.append(fed)
                        seq.num_computed += 1
                        self._seal_completed_blocks(seq)
                        self._accept_token(
                            seq,
                            int(sampled[t, i]),
                            defer_removal=True,
                            logprobs=self._lp_info(
                                seq,
                                i,
                                None if logp is None else logp[t],
                                None if top_ids is None else top_ids[t],
                                None if top_lp is None else top_lp[t],
                            ),
                        )
                        if seq.finished:
                            finished.append(seq)
                for seq in finished:
                    self.scheduler.remove(seq)
            if not all_pending:
                break

    # -------------------------------------------------- fused decode pipeline
    async def _decode_pipeline(self, members: List[SequenceState]) -> bool:
        """Steady-state decode: fused multi-step dispatches with the token
        carry on device, up to cfg.pipeline_depth dispatches in flight, host
        readback overlapped.  Runs until membership must change (a sequence
        finished/cancelled, a new request arrived, or blocks ran out), then
        drains in-flight work before returning so the scheduler can rebuild.

        Invariant: no member's KV blocks are freed while any dispatch that
        writes them is in flight — finishes are deferred to the drain point.
        """
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        n = len(members)

        tok0 = np.zeros((S,), np.int32)
        pos_disp = np.full((S,), -1, np.int32)  # dispatch frontier (-1 = pad)
        for i, seq in enumerate(members):
            all_toks = seq.prompt + seq.output
            tok0[i] = all_toks[seq.num_computed]
            pos_disp[i] = seq.num_computed
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        for i, seq in enumerate(members):
            self._tables_row(tables, i, seq)
        samp = self._sampling_arrays(members)
        # Host copy only needed for the follower broadcast — np.asarray on
        # samp.counts would otherwise drag the [S, V] device buffer to host
        # on every pipeline build.
        samp_np = (
            jax.tree_util.tree_map(np.asarray, samp)
            if self._publisher is not None
            else None
        )
        need_lp = bool(samp.need_logprobs)
        # (token, rng-step, penalty-counts) carry: numpy seeds for the first
        # dispatch, then the previous dispatch's on-device outputs.
        carry: Optional[Tuple[Any, Any, Any]] = None
        multi = self._multi_fn

        inflight: deque = deque()
        finished_members: List[SequenceState] = []
        rebuild = False
        dispatched_any = False

        def want_rebuild() -> bool:
            # Waiting requests only force a rebuild when one could actually
            # be ADMITTED (free slot + blocks).  At oversubscription the
            # queue is never empty; gating on num_waiting alone would keep
            # the fused pipeline permanently disabled (round-3 saturation
            # collapse: conc 32 throughput below conc 16).
            return (
                self._closed
                or self.scheduler.admission_ready()
                or any(s.finished for s in members)
                or any(
                    (c := self._contexts.get(s.request_id)) is not None
                    and c.is_stopped
                    for s in members
                )
            )

        while True:
            # Top up the dispatch window.  With requests queued, cap the
            # in-flight depth at 2 (enough to overlap fetch with compute) so
            # the drain a newcomer's admission must wait for stays bounded.
            depth = (
                min(cfg.pipeline_depth, 2)
                if self.scheduler.num_waiting
                else cfg.pipeline_depth
            )
            while not rebuild and len(inflight) < depth:
                # Don't dispatch chunks no row can still use: once every
                # member's in-flight frontier covers its remaining token
                # budget, further chunks are pure waste (their tokens would
                # all be discarded host-side).  Checked BEFORE allocating
                # lookahead blocks below — a never-dispatched chunk must not
                # take KV capacity from other sequences.
                if not self._any_useful_rows(members, pos_disp):
                    rebuild = True
                    break
                # Ensure every active member has KV room for this chunk.
                limits = np.zeros((S,), np.int32)
                ok = True
                for i, seq in enumerate(members):
                    if seq.finished:
                        pos_disp[i] = -1
                        continue
                    need = int(pos_disp[i]) + T - seq.num_computed
                    if not self.scheduler._ensure_slot(seq, lookahead=need):
                        ok = False
                    self._tables_row(tables, i, seq)
                    limits[i] = min(
                        len(seq.block_ids) * bs,
                        cfg.max_blocks_per_seq * bs,
                    )
                if not ok:
                    # Out of KV headroom: drain any in-flight work, then
                    # return so schedule() can preempt with nothing pending.
                    rebuild = True
                    break
                pos0 = pos_disp.copy()
                first = carry is None
                pub_payload = (
                    tok0 if first else None,  # None → follower's own carry
                    pos0,
                    tables.copy(),
                    limits,
                    samp_np,
                )
                if first:
                    c_tok, c_steps, c_counts = tok0, samp.steps, samp.counts
                    if self._rep_sharding is not None:
                        c_tok, c_steps = self._prep((c_tok, c_steps))
                else:
                    c_tok, c_steps, c_counts = carry
                if self._rep_sharding is not None:
                    d_args = self._prep((pos0, tables.copy(), limits, samp))
                else:
                    d_args = (pos0, tables, limits, samp)

                def dispatch(args=d_args, tok_in=c_tok, st=c_steps, ct=c_counts):
                    outs, last, steps_f, counts_f, self.cache = multi(
                        self.params, self.cache, tok_in, st, ct, *args
                    )
                    return outs, (last, steps_f, counts_f)

                t0 = time.perf_counter()
                async with self._device_lock:
                    # Broadcast order must equal enqueue order (see
                    # _run_unified) — publish under the device lock.
                    if self._publisher is not None:
                        await self._publisher.publish("multi", pub_payload)
                    outs, carry = await asyncio.to_thread(dispatch)
                self.step_trace.append(
                    ("decode_dispatch", time.perf_counter() - t0, n, n * T)
                )
                # Start the D2H copy NOW: it proceeds in the background while
                # later chunks compute, so the drain fetch below pays ~zero
                # round-trip instead of compute + full link latency (round-2
                # measured 323ms per serial fetch over the tunneled chip).
                try:
                    outs.tokens.copy_to_host_async()
                    if need_lp:
                        outs.logprob.copy_to_host_async()
                        outs.top_ids.copy_to_host_async()
                        outs.top_logprobs.copy_to_host_async()
                except AttributeError:
                    pass
                inflight.append((outs, pos0))
                dispatched_any = True
                pos_disp = np.where(pos_disp >= 0, pos_disp + T, pos_disp)
                if want_rebuild():
                    rebuild = True

            if not inflight:
                break

            # Await the oldest chunk's tokens and apply them.
            outs, pos0 = inflight.popleft()
            t0 = time.perf_counter()

            def fetch(o=outs):
                if need_lp:
                    return (
                        np.asarray(o.tokens),
                        np.asarray(o.logprob),
                        np.asarray(o.top_ids),
                        np.asarray(o.top_logprobs),
                    )
                return np.asarray(o.tokens), None, None, None

            sampled, logp, top_ids, top_lp = await asyncio.to_thread(fetch)
            self.step_trace.append(
                # "wait" not "fetch": the D2H copy was started at dispatch,
                # so this wall is dominated by the chunk's device compute.
                ("decode_wait", time.perf_counter() - t0, n, n * T)
            )
            for t in range(T):
                for i, seq in enumerate(members):
                    if seq.finished or pos0[i] < 0:
                        continue
                    if seq.num_computed != pos0[i] + t:
                        continue  # stopped earlier in this chunk
                    limit = len(seq.block_ids) * bs
                    if seq.num_computed >= limit:
                        continue  # beyond allocation: token was never KV-backed
                    fed = (seq.prompt + seq.output)[seq.num_computed]
                    if seq.num_computed >= len(seq.prompt):
                        seq.block_seq.append(fed)
                    seq.num_computed += 1
                    self._seal_completed_blocks(seq)
                    self._accept_token(
                        seq,
                        int(sampled[t, i]),
                        defer_removal=True,
                        logprobs=self._lp_info(
                            seq,
                            i,
                            None if logp is None else logp[t],
                            None if top_ids is None else top_ids[t],
                            None if top_lp is None else top_lp[t],
                        ),
                    )
                    if seq.finished:
                        finished_members.append(seq)
            if want_rebuild():
                rebuild = True
            if rebuild and not inflight:
                break
            await asyncio.sleep(0)  # let ingress/egress run between chunks

        # Drained: now it is safe to release finished members' blocks.
        for seq in finished_members:
            self.scheduler.remove(seq)
        return dispatched_any

    async def _decode_burst(self, members: List[SequenceState]) -> bool:
        """ONE fused multi-step dispatch for ``members`` (all decoding):
        decode_steps tokens per row for a single device round trip, used in
        mixed phases where prefill rows keep the full pipeline from
        engaging.  Same discard semantics as the pipeline: tokens past a
        row's stop/limit are dropped host-side.  Returns False (dispatching
        nothing) when KV headroom for a full burst is missing."""
        cfg = self.cfg
        bs = cfg.block_size
        S, T = cfg.max_batch, cfg.decode_steps
        n = len(members)
        tok0 = np.zeros((S,), np.int32)
        pos0 = np.full((S,), -1, np.int32)
        tables = np.zeros((S, cfg.max_blocks_per_seq), np.int32)
        limits = np.zeros((S,), np.int32)
        for i, seq in enumerate(members):
            if seq.finished:
                return False  # membership changed under us: replan
            if not self.scheduler._ensure_slot(seq, lookahead=T):
                return False
            all_toks = seq.prompt + seq.output
            tok0[i] = all_toks[seq.num_computed]
            pos0[i] = seq.num_computed
            self._tables_row(tables, i, seq)
            limits[i] = min(
                len(seq.block_ids) * bs, cfg.max_blocks_per_seq * bs
            )
        while self._pending_fetches and self._pending_fetches[0][1].done():
            await self._harvest_pending()  # free: task already complete
        samp = self._sampling_arrays(members)
        need_lp = bool(samp.need_logprobs)
        c_tok, c_steps = tok0, samp.steps
        if self._rep_sharding is not None:
            c_tok, c_steps = self._prep((c_tok, c_steps))
            d_args = self._prep((pos0, tables, limits, samp))
        else:
            d_args = (pos0, tables, limits, samp)
        multi = self._multi_fn

        def run():
            outs, _last, _steps, _counts, self.cache = multi(
                self.params, self.cache, c_tok, c_steps, samp.counts, *d_args
            )
            # Async D2H + deferred accept: the burst's tokens are only
            # needed at the next harvest point (its rows are parked), so
            # the round trip overlaps the following prefill chunks instead
            # of stalling behind the device queue.
            try:
                outs.tokens.copy_to_host_async()
                if need_lp:
                    outs.logprob.copy_to_host_async()
                    outs.top_ids.copy_to_host_async()
                    outs.top_logprobs.copy_to_host_async()
            except AttributeError:
                pass
            return outs

        t0 = time.perf_counter()
        async with self._device_lock:
            if self._publisher is not None:
                await self._publisher.publish(
                    "multi",
                    (
                        tok0,
                        pos0,
                        tables.copy(),
                        limits,
                        jax.tree_util.tree_map(np.asarray, samp),
                    ),
                )
            outs = await asyncio.to_thread(run)
        self.step_trace.append(
            ("decode_burst", time.perf_counter() - t0, n, n * T)
        )
        for seq in members:
            seq.awaiting_fetch = True
        self._stash_fetch("burst", outs, need_lp, members, pos0)
        return True

    def _any_useful_rows(
        self, members: List[SequenceState], pos_disp: np.ndarray
    ) -> bool:
        """True if any active member could still accept a token from one more
        fused chunk, given how far its dispatch frontier already overshoots
        its accepted position (in-flight tokens count against the budget)."""
        for i, seq in enumerate(members):
            if seq.finished or pos_disp[i] < 0:
                continue
            overshoot = int(pos_disp[i]) - seq.num_computed
            budget = self.cfg.max_model_len - seq.total_tokens
            if seq.max_new_tokens is not None:
                budget = min(budget, seq.max_new_tokens - seq.num_output_tokens)
            if budget - overshoot > 0:
                return True
        return False

    # ------------------------------------------------------------ per-token
    def _seal_completed_blocks(self, seq: SequenceState) -> None:
        complete = seq.num_computed // self.cfg.block_size
        hashed = len(seq.block_seq.blocks)
        while seq.num_sealed_blocks < min(complete, hashed):
            idx = seq.num_sealed_blocks
            tb = seq.block_seq.blocks[idx]
            self.kv.seal_block(seq.block_ids[idx], tb)
            seq.num_sealed_blocks += 1
            if self.host_kv is not None and not self.host_kv.contains(
                tb.sequence_hash
            ):
                self._offload_queue.append((seq.block_ids[idx], tb))

    # ------------------------------------------------------- host KV offload
    async def _offload_pump(self) -> None:
        """Write-behind: batch-gather queued sealed blocks to the host tier
        (one device gather + one D2H per cycle, not per block)."""
        while not self._closed:
            await asyncio.sleep(self.cfg.host_offload_interval)
            if self._offload_queue:
                try:
                    await self.drain_offload()
                except Exception:
                    # Offload is an optimization; never let it kill serving.
                    logger.exception("host KV offload cycle failed")

    async def drain_offload(self, max_blocks: int = 64) -> int:
        """Copy up to ``max_blocks`` queued sealed blocks to host RAM.
        Returns how many were stored (public so tests can force a cycle)."""
        if self.host_kv is None or not self._offload_queue:
            return 0
        batch, self._offload_queue = (
            self._offload_queue[:max_blocks],
            self._offload_queue[max_blocks:],
        )
        async with self._device_lock:
            # A block may have been recycled since sealing; only blocks
            # still holding their hash are snapshotted.
            live = [
                (bid, tb)
                for bid, tb in batch
                if self.kv._blocks[bid].sequence_hash == tb.sequence_hash
            ]
            if not live:
                return 0
            pad = 1 << max(0, (len(live) - 1).bit_length())
            ids = np.zeros((pad,), np.int32)
            ids[: len(live)] = [bid for bid, _ in live]
            hashes = [tb.sequence_hash for _, tb in live]
            # Leader stores FIRST, publish only on success — still under
            # the device lock, so no other dispatch can interleave and the
            # followers' execution position matches the leader's.  A
            # leader-side failure then leaves every tier unchanged instead
            # of followers holding blocks the leader lacks (tier skew would
            # surface later as a fatal restore divergence).
            await asyncio.to_thread(self._offload_store, ids, hashes)
            if self._publisher is not None:
                await self._publisher.publish("offload", (ids, hashes))
        return len(live)

    def _offload_store(self, ids: np.ndarray, hashes: List[int]) -> None:
        """Gather ``ids``'s pages and store THIS PROCESS's portion in the
        host tier.  Single-process: the whole block (contiguous, one
        array).  Multi-process: one slice per locally-held shard, keyed by
        the shard's heads-axis offset (combined-head axis 3)."""
        # _prep: in multi-process runs the gather's index operand must be a
        # replicated GLOBAL array like every other mirrored dispatch.
        pages_g = self._gather_fn(self.cache, self._prep(ids))
        if jax.process_count() == 1:
            pages = np.asarray(pages_g)
            for i, h in enumerate(hashes):
                self.host_kv.put(h, np.ascontiguousarray(pages[:, i]))
            return
        shards: Dict[int, np.ndarray] = {}
        for s in pages_g.addressable_shards:
            start = s.index[3].start or 0
            if start not in shards:
                shards[start] = np.asarray(s.data)
        for i, h in enumerate(hashes):
            self.host_kv.put(
                h,
                {
                    start: np.ascontiguousarray(arr[:, i])
                    for start, arr in shards.items()
                },
            )

    async def _sp_prefill(self, token_ids: List[int]) -> int:
        """Whole-prompt sequence-parallel prefill: compute the prompt's KV in
        one ring-attention pass over the "sp" mesh axis and seal its complete
        blocks into the paged cache (released to the reuse pool), so
        admission sees a full prefix hit.  The trailing partial block plus
        the last token recompute through the normal unified step (which also
        produces the first sampled token's logits).  Returns sealed tokens.
        """
        from ..tokens import hash_token_blocks

        cfg = self.cfg
        bs = cfg.block_size
        n_complete = len(token_ids) // bs
        blocks = hash_token_blocks(token_ids, bs)
        resident = len(self.kv.match_prefix(blocks))
        if resident >= n_complete or n_complete == 0:
            return 0
        # Token bucket: power of two, multiple of sp (bounds recompiles).
        Tg = max(cfg.sp, 1 << (len(token_ids) - 1).bit_length())
        Tg += (-Tg) % cfg.sp
        toks = np.zeros((Tg,), np.int32)
        toks[: len(token_ids)] = token_ids
        valid = np.asarray(len(token_ids), np.int32)
        # No _device_lock here: the forward is a pure function of
        # params+tokens (touches no donated cache), so decode dispatches
        # interleave in the device queue instead of stalling behind the
        # whole-prompt pass.  (Dedicated disagg prefill workers remain the
        # intended fit for sp — config.py.)
        _, kv_rows = await asyncio.to_thread(
            self._sp_fn, self.params, toks, valid
        )
        # [L, Tg, 2KV, hd] → complete-block pages [L, n, bs, 2KV, hd]
        L = kv_rows.shape[0]
        if self.kv_scale is not None:
            # Quantized cache stores value/scale (write_kv_ragged contract);
            # per-layer calibration vectors broadcast over [L, Tg, 2KV, hd].
            sc = np.asarray(self.kv_scale, np.float32).reshape(-1, 1, 1, 1)
            kv_rows = kv_rows.astype(jnp.float32) / sc
        pages = kv_rows[:, : n_complete * bs].reshape(
            L, n_complete, bs, kv_rows.shape[2], kv_rows.shape[3]
        )[:, resident:]
        n_new = n_complete - resident
        pad = 1 << max(0, (n_new - 1).bit_length())
        if pad != n_new:
            pages = jnp.pad(pages, ((0, 0), (0, pad - n_new), (0, 0), (0, 0), (0, 0)))
        covered = await self.inject_blocks_from_device(
            token_ids, pages, n_new, start_block=resident
        )
        if covered:
            logger.info(
                "sp prefill sealed %d tokens of %d (sp=%d, bucket %d)",
                covered, len(token_ids), cfg.sp, Tg,
            )
        return covered

    async def _restore_from_host(self, token_ids: List[int]) -> int:
        """Scatter host-tier blocks beyond the HBM-resident prefix back into
        the device cache (sealed + released to the reuse pool), so admission
        sees them as ordinary prefix-cache hits.  Returns restored blocks."""
        if self.host_kv is None:
            return 0
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        resident = len(self.kv.match_prefix(blocks))
        run: List[Tuple[Any, np.ndarray]] = []
        for tb in blocks[resident:]:
            # peek, not get: this is candidate selection (possibly
            # truncated below); touching the LRU here would diverge the
            # leader's eviction order from the followers'.
            host = self.host_kv.peek(tb.sequence_hash)
            if host is None:
                break
            run.append((tb, host))
        run = run[: max(0, self.kv.free_blocks - 1)]
        if not run:
            return 0
        # PIN the resident prefix (take references) while allocating the
        # tail: the prefix blocks sit in the reuse pool and are otherwise
        # legitimate LRU eviction victims for our own allocations — which
        # would replace recompute-the-tail with recompute-everything.
        prefix_ids: List[int] = (
            self.kv.acquire_prefix(blocks[:resident]) or [] if resident else []
        )
        try:
            ids: List[int] = []
            for _ in run:
                bid = self.kv.allocate_block()
                if bid is None:
                    break
                ids.append(bid)
            run = run[: len(ids)]
            if not run:
                self.kv.free_sequence(ids)
                return 0
            n = len(run)
            pad = 1 << max(0, (n - 1).bit_length())
            page_ids = np.full((pad,), self.cfg.num_blocks, np.int32)  # OOB pad
            page_ids[:n] = ids
            if jax.process_count() > 1:
                # Per-host sharded tier: every process reassembles ITS
                # devices' slice of each block from its own store — the
                # broadcast carries only ids + hashes, never page data.
                hashes = [tb.sequence_hash for tb, _ in run]
                async with self._device_lock:
                    # Revalidate UNDER the lock: the offload pump may have
                    # LRU-evicted a candidate while we awaited it.  Tiers
                    # mutate only under this lock and in broadcast order,
                    # so leader-present-here implies follower-present-there;
                    # a miss now means recompute-prefill, not a crash.
                    if any(
                        not isinstance(self.host_kv.peek(h), dict)
                        for h in hashes
                    ):
                        self.kv.free_sequence(ids)
                        return 0
                    # Inject locally first; publish only on success (same
                    # ordering argument as drain_offload).
                    await asyncio.to_thread(
                        self._restore_inject, page_ids, hashes
                    )
                    if self._publisher is not None:
                        await self._publisher.publish(
                            "restore_host", (page_ids, hashes)
                        )
            else:
                comb = np.stack([h for _, h in run], axis=1)  # [L,n,ps,2KV,hd]
                comb_p = np.zeros(
                    comb.shape[:1] + (pad,) + comb.shape[2:], comb.dtype
                )
                comb_p[:, :n] = comb
                async with self._device_lock:
                    if self._publisher is not None:
                        await self._publisher.publish(
                            "inject", (page_ids, comb_p)
                        )
                    self.cache = await asyncio.to_thread(
                        self._inject_fn,
                        self.cache,
                        *self._prep((page_ids, comb_p)),
                    )
                # Candidate selection peeked; refresh recency for the
                # blocks actually restored (single-process has no
                # cross-process lockstep to preserve).
                for tb, _ in run:
                    self.host_kv.get(tb.sequence_hash)
            for bid, (tb, _) in zip(ids, run):
                self.kv.seal_block(bid, tb)
            self.kv.free_sequence(ids)
            self.host_kv.restored_blocks += n
            return n
        finally:
            if prefix_ids:
                self.kv.free_sequence(prefix_ids)

    def _restore_inject(self, page_ids: np.ndarray, hashes: List[int]) -> None:
        """Multi-process host restore: build this process's devices' slices
        of the [L, pad, ps, 2KV, hd] block stack from the per-host sharded
        tier and scatter them into the cache (every process runs this — the
        leader inline, followers via the 'restore_host' mirror step)."""
        from jax.sharding import NamedSharding

        from ..parallel.mesh import pages_pspec

        L, _, ps, KV2, hd = self.cache.pages.shape
        pad = int(page_ids.shape[0])
        shape = (L, pad, ps, KV2, hd)
        sharding = NamedSharding(self.mesh, pages_pspec())
        # Touch each hash exactly once (same broadcast order on every
        # process → identical LRU order), then build ONE local stack per
        # distinct head-shard offset — local devices sharing an offset
        # (dp/ep replicas) reuse the same array.
        fetched = []
        for h in hashes:
            blk = self.host_kv.get(h)
            if not isinstance(blk, dict):
                # Tiers mutate only in broadcast order, so after the
                # leader's under-lock revalidation this cannot happen on a
                # healthy deployment — fail LOUDLY rather than inject
                # zeros under a valid hash.
                raise RuntimeError(f"host tier missing block {h:#x}")
            fetched.append(blk)
        idx_map = sharding.addressable_devices_indices_map(shape)
        locals_by_start: Dict[int, np.ndarray] = {}
        for index in idx_map.values():
            start = index[3].start or 0
            if start in locals_by_start:
                continue
            parts = []
            for h, blk in zip(hashes, fetched):
                if start not in blk:
                    raise RuntimeError(
                        f"host tier missing shard {start} of block {h:#x}"
                    )
                parts.append(blk[start])  # [L, ps, local_heads, hd]
            local = np.stack(parts, axis=1)  # [L, n, ps, lh, hd]
            if pad != len(hashes):
                z = np.zeros(
                    local.shape[:1] + (pad,) + local.shape[2:], local.dtype
                )
                z[:, : len(hashes)] = local
                local = z
            locals_by_start[start] = local
        arrays = [
            jax.device_put(locals_by_start[index[3].start or 0], dev)
            for dev, index in idx_map.items()
        ]
        comb = jax.make_array_from_single_device_arrays(
            shape, sharding, arrays
        )
        self.cache = self._inject_fn(
            self.cache, self._prep(page_ids), comb
        )

    def _lp_info(
        self, seq: SequenceState, i: int, logp, top_ids, top_lp
    ) -> Optional[Dict[str, Any]]:
        """Per-token logprob payload for row ``i`` (None unless requested)."""
        if seq.logprobs is None or logp is None:
            return None
        k = min(int(seq.logprobs), top_ids.shape[-1])
        return {
            "logprob": float(logp[i]),
            "top": [
                (int(top_ids[i, j]), float(top_lp[i, j])) for j in range(k)
            ],
        }

    def _accept_token(
        self,
        seq: SequenceState,
        token: int,
        defer_removal: bool = False,
        logprobs: Optional[Dict[str, Any]] = None,
    ) -> None:
        seq.output.append(token)
        reason = self._check_stop(seq, token)
        queue = self._queues.get(seq.request_id)
        # Stop-triggering tokens (eos / stop_token_ids) are not emitted,
        # matching the reference Backend's stop handling (backend.rs:234-423).
        if queue is not None and reason is not FinishReason.STOP:
            item = LLMEngineOutput.token(token)
            if logprobs is not None:
                item["logprobs"] = logprobs
            queue.put_nowait(item)
        if reason is not None:
            seq.finished = True
            if not defer_removal:
                self.scheduler.remove(seq)
            self._finish(seq, reason)

    def _check_stop(self, seq: SequenceState, token: int) -> Optional[FinishReason]:
        n_out = seq.num_output_tokens  # survives preemption's prompt-folding
        min_ok = seq.min_new_tokens is None or n_out >= seq.min_new_tokens
        if min_ok and token in seq.stop_token_ids:
            return FinishReason.STOP
        if (
            min_ok
            and not seq.ignore_eos
            and token in self.model_config.eos_token_ids
        ):
            return FinishReason.STOP
        if seq.max_new_tokens is not None and n_out >= seq.max_new_tokens:
            return FinishReason.LENGTH
        if seq.total_tokens >= self.cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    def _finish(self, seq: SequenceState, reason: FinishReason) -> None:
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        queue.put_nowait(
            LLMEngineOutput.finished(
                reason,
                usage={
                    "prompt_tokens": seq.orig_prompt_len,
                    "completion_tokens": seq.num_output_tokens,
                    "total_tokens": seq.total_tokens,
                },
            )
        )
        queue.put_nowait(_FINISHED)

    def step_summary(self) -> Dict[str, Any]:
        """Aggregate the dispatch trace: counts, wall time, and latency
        percentiles per step kind (the VERDICT r1 profiling ask)."""
        out: Dict[str, Any] = {}
        for kind in sorted({k for k, *_ in self.step_trace}):
            times = sorted(t for k, t, _, _ in self.step_trace if k == kind)
            toks = sum(n for k, _, _, n in self.step_trace if k == kind)
            m = len(times)
            out[kind] = {
                "dispatches": m,
                "wall_s": round(sum(times), 4),
                "device_tokens": toks,
                "p50_ms": round(times[m // 2] * 1e3, 2),
                "p99_ms": round(times[min(m - 1, int(m * 0.99))] * 1e3, 2),
            }
        return out


async def transfer_blocks_device(src: TpuEngine, dst: TpuEngine, token_ids) -> int:
    """Co-located prefill→decode KV transfer that never stages in host RAM:
    device gather from the source cache → ``jax.device_put`` onto the
    destination's sharding → in-place scatter.  On one chip this is an HBM
    copy; across chips of a shared slice the put rides ICI — the reference's
    NIXL/GPUDirect block path (SURVEY §2.6) for same-slice deployments.
    Returns tokens covered (the longest resident prefix run)."""
    from ..tokens import hash_token_blocks

    if jax.process_count() > 1:
        return 0  # same single-process restriction as export_prompt_blocks
    if src.cfg.block_size != dst.cfg.block_size:
        return 0
    if src.cache.pages.shape[0] != dst.cache.pages.shape[0]:
        return 0  # different layer counts: not the same model
    if src.cache.pages.dtype != dst.cache.pages.dtype or not _scales_close(
        src._kv_scale_repr(), dst._kv_scale_repr()
    ):
        return 0  # stored representation differs: host path will also refuse
    blocks = hash_token_blocks(token_ids, src.cfg.block_size)
    src_ids: List[int] = []
    for tb in blocks:
        bid = src.kv._by_hash.get(tb.sequence_hash)
        if bid is None:
            break
        src_ids.append(bid)
    if not src_ids:
        return 0
    n = len(src_ids)
    pad = 1 << max(0, (n - 1).bit_length())
    gather_ids = np.zeros((pad,), np.int32)
    gather_ids[:n] = src_ids
    async with src._device_lock:
        pages = await asyncio.to_thread(src._gather_fn, src.cache, gather_ids)
    if dst.mesh is not None:
        pages = jax.device_put(
            pages, jax.tree_util.tree_leaves(dst.cache)[0].sharding
        )
    elif pages.devices() != dst.cache.pages.devices():
        pages = jax.device_put(pages, next(iter(dst.cache.pages.devices())))
    return await dst.inject_blocks_from_device(token_ids, pages, n)
