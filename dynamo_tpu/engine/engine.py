"""TpuEngine: the native JAX engine behind the AsyncEngine interface.

This is the component the reference delegates to vLLM/sglang subprocesses
(lib/engines/* — SURVEY.md §2.8); here it is in-process and TPU-native:

- one jitted step function (forward + fused sampling) per shape bucket;
  batch/prefill-length buckets are powers of two so a handful of XLA
  programs cover every workload mix;
- the KV cache lives in HBM as donated jit operands — scatters update it
  in place, no reallocation per step;
- the asyncio step loop runs device dispatch in a worker thread so request
  ingress/egress stay responsive (dispatch is async, but fetching sampled
  tokens blocks);
- per-request cancellation is polled between steps (a batched synchronous
  device loop can't preempt mid-step — SURVEY.md §7 hard part (c));
- KV events (stored/removed, chained hashes) and ForwardPassMetrics are
  emitted exactly as the reference's C-API hooks do
  (lib/bindings/c/src/lib.rs:51-296), feeding the KV-aware router.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.kv_router.protocols import ForwardPassMetrics, KvCacheEvent
from ..llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from ..models.config import ModelConfig, get_config
from ..models.llama import KVCache, ModelBatch, forward, init_params
from ..ops.sampling import sample_tokens
from ..parallel.mesh import (
    MeshConfig,
    cache_pspec,
    make_mesh,
    param_pspecs,
    shard_tree,
    sharding_tree,
)
from ..runtime.engine import AsyncEngine, Context, ResponseStream
from .config import EngineConfig
from .kv_manager import KvBlockManager
from .scheduler import DecodeWork, PrefillWork, Scheduler, SequenceState

logger = logging.getLogger(__name__)

_FINISHED = object()  # queue sentinel


class TpuEngine(AsyncEngine):
    """Token-in/token-out engine (ExecutionContext equivalent)."""

    def __init__(
        self,
        cfg: EngineConfig,
        event_callback: Optional[Callable[[KvCacheEvent], None]] = None,
        params: Any = None,
    ):
        self.cfg = cfg
        self.model_config: ModelConfig = get_config(cfg.model).with_overrides(
            dtype=cfg.dtype
        )
        self.kv = KvBlockManager(
            cfg.num_blocks,
            cfg.block_size,
            event_callback=event_callback,
            enable_prefix_caching=cfg.enable_prefix_caching,
        )
        self.scheduler = Scheduler(cfg, self.kv)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Any] = {}
        self._wake = asyncio.Event()
        self._closed = False
        self._loop_task: Optional[asyncio.Task] = None
        # Serialises device-state access: step functions donate the cache
        # buffers, so export/import must never observe a mid-step cache.
        self._device_lock = asyncio.Lock()
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._steps = 0

        # --- device state -------------------------------------------------
        mesh_cfg = MeshConfig(dp=cfg.dp, tp=cfg.tp, ep=cfg.ep)
        self.mesh = make_mesh(mesh_cfg) if mesh_cfg.num_devices > 1 else None
        if params is None:
            if cfg.checkpoint_path:
                from ..models.loader import load_params

                params = load_params(self.model_config, cfg.checkpoint_path)
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(cfg.seed))
        cache = KVCache.create(
            self.model_config,
            cfg.num_blocks,
            cfg.block_size,
            dtype=jnp.dtype(cfg.cache_dtype),
        )
        if self.mesh is not None:
            params = shard_tree(params, param_pspecs(self.model_config), self.mesh)
            cache = shard_tree(
                cache, KVCache(cache_pspec(), cache_pspec()), self.mesh
            )
        self.params = params
        self.cache = cache

        model_config, block_size = self.model_config, cfg.block_size
        attn_impl = cfg.attn_impl
        if attn_impl == "auto":
            from ..ops.attention import on_tpu

            # Measured on v5e (4096-token window, ctx 3000, B=16): jax's
            # paged kernel 4.7ms < XLA gather 5.9ms < our per-page Pallas
            # kernel (needs multi-page DMA batching before it competes).
            attn_impl = "jax" if on_tpu() else "xla"
        self.attn_impl = attn_impl

        def _step(params, cache, batch, temp, topk, topp, rng):
            logits, cache = forward(
                params, model_config, batch, cache, block_size, attn_impl=attn_impl
            )
            tokens = sample_tokens(logits, rng, temp, topk, topp)
            return tokens, cache

        def _multi_step(
            params, cache, tok0, pos0, tables, limits, temp, topk, topp, rng
        ):
            """``decode_steps`` fused decode iterations: one dispatch, the
            sampled token feeds the next step on device (amortises dispatch
            latency — SURVEY §7 hard part (c) meets a tunneled chip).

            ``limits[b]`` = allocated slots for row b; steps whose position
            reaches it skip the KV write (their sampled tokens are discarded
            host-side, which stops the sequence at LENGTH anyway).
            """
            B = tok0.shape[0]
            active = pos0 >= 0  # padding rows carry pos -1

            def body(carry, step_rng):
                cache, tok, pos = carry
                posc = jnp.maximum(pos, 0)
                slot = jnp.take_along_axis(
                    tables, posc[:, None] // block_size, axis=1
                )[:, 0] * block_size + posc % block_size
                writable = active & (posc < limits)
                slot = jnp.where(writable, slot, -1)
                batch = ModelBatch(
                    token_ids=tok[:, None],
                    positions=posc[:, None],
                    slot_mapping=slot[:, None],
                    block_tables=tables,
                    context_lens=jnp.where(active, jnp.minimum(pos + 1, limits), 0),
                    logits_idx=jnp.zeros((B,), jnp.int32),
                )
                logits, cache = forward(
                    params, model_config, batch, cache, block_size,
                    attn_impl=attn_impl,
                )
                nxt = sample_tokens(logits, step_rng, temp, topk, topp)
                return (cache, nxt, jnp.where(active, pos + 1, pos)), nxt

            rngs = jax.random.split(rng, cfg.decode_steps)
            (cache, _, _), toks = jax.lax.scan(body, (cache, tok0, pos0), rngs)
            return toks, cache  # toks: [T, B]

        def _inject(cache, slots, k_new, v_new):
            # Donated in-place scatter: no transient second full-cache copy
            # in HBM during KV imports (the out-of-jit .at[].set would
            # materialise one per transferred prompt).  Padding rows carry an
            # out-of-range slot and are dropped, so callers can bucket the
            # slot count to bound recompiles.
            ck = cache.k.at[:, :, slots].set(
                k_new.astype(cache.k.dtype), mode="drop"
            )
            cv = cache.v.at[:, :, slots].set(
                v_new.astype(cache.v.dtype), mode="drop"
            )
            return KVCache(ck, cv)

        donate = (1,)
        if self.mesh is None:
            self._step_fn = jax.jit(_step, donate_argnums=donate)
            self._multi_step_fn = jax.jit(_multi_step, donate_argnums=donate)
            self._inject_fn = jax.jit(_inject, donate_argnums=(0,))
        else:
            cache_sh = sharding_tree(
                cache, KVCache(cache_pspec(), cache_pspec()), self.mesh
            )
            self._step_fn = jax.jit(
                _step,
                donate_argnums=donate,
                out_shardings=(None, cache_sh),
            )
            self._multi_step_fn = jax.jit(
                _multi_step,
                donate_argnums=donate,
                out_shardings=(None, cache_sh),
            )
            self._inject_fn = jax.jit(
                _inject, donate_argnums=(0,), out_shardings=cache_sh
            )

    # ------------------------------------------------------------ public API
    async def generate(self, request: Context) -> ResponseStream:
        if self._closed:
            raise RuntimeError("engine is closed")
        pre = PreprocessedRequest.from_dict(request.data)
        if len(pre.token_ids) > self.cfg.max_model_len:
            raise ValueError(
                f"prompt length {len(pre.token_ids)} exceeds max_model_len "
                f"{self.cfg.max_model_len}"
            )
        self._ensure_loop()
        seq = SequenceState.from_request(request.id, pre, self.cfg)
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request.id] = queue
        self._contexts[request.id] = request.ctx
        self.scheduler.add(seq)
        self._wake.set()

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            try:
                while True:
                    item = await queue.get()
                    if item is _FINISHED:
                        return
                    yield item
            finally:
                self._queues.pop(request.id, None)
                self._contexts.pop(request.id, None)

        return ResponseStream(gen(), request.ctx)

    def set_event_callback(
        self, callback: Optional[Callable[[KvCacheEvent], None]]
    ) -> None:
        """Attach/replace the KV event sink (e.g. a KvEventPublisher) after
        construction — the CLI builds the engine before the runtime exists."""
        self.kv._event_callback = callback

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            request_active_slots=self.scheduler.num_running,
            request_total_slots=self.cfg.max_batch,
            kv_active_blocks=self.kv.active_blocks,
            kv_total_blocks=self.kv.num_blocks,
            num_requests_waiting=self.scheduler.num_waiting,
            gpu_cache_usage_perc=self.kv.usage,
            gpu_prefix_cache_hit_rate=self.kv.hit_rate,
        )

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        # Fail whatever is still in flight so no generate() stream hangs.
        self._fail_all()

    # --------------------------------------------------- KV export / import
    #
    # TPU counterpart of the reference's block_copy.cu + NIXL transfer
    # (lib/llm/src/kernels/block_copy.cu, kv/layer.rs:100-772): whole blocks
    # move between workers as host-staged arrays (msgpack binary over the
    # service plane; ICI device-to-device when workers share a pod slice).
    # Imported blocks are sealed under their chained hashes, so the decode
    # scheduler sees remote-prefilled prompts as ordinary prefix-cache hits.

    def _kv_slots(self, block_ids: List[int]) -> np.ndarray:
        bs = self.cfg.block_size
        ids = np.asarray(block_ids, np.int32)
        return (ids[:, None] * bs + np.arange(bs, dtype=np.int32)[None, :]).reshape(-1)

    async def export_prompt_blocks(
        self, token_ids: List[int]
    ) -> Optional[Dict[str, Any]]:
        """Gather the cached KV for ``token_ids``'s complete blocks to host.

        Returns None unless every complete block of the prompt is resident
        (blocks are looked up by chained hash — reuse-pool contents count).
        """
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        if not blocks:
            return None
        ids: List[int] = []
        for tb in blocks:
            bid = self.kv._by_hash.get(tb.sequence_hash)
            if bid is None:
                return None
            ids.append(bid)
        slots = self._kv_slots(ids)
        async with self._device_lock:
            k = np.asarray(self.cache.k[:, :, slots])  # [L, KV, n*bs, hd]
            v = np.asarray(self.cache.v[:, :, slots])
        return {
            "n_blocks": len(ids),
            "block_size": self.cfg.block_size,
            "dtype": str(k.dtype),
            "shape": list(k.shape),
            "k": k.tobytes(),
            "v": v.tobytes(),
        }

    async def inject_blocks(self, token_ids: List[int], payload: Dict[str, Any]) -> int:
        """Write transferred KV into this engine's cache as sealed blocks.

        Returns the number of tokens now covered by the local prefix cache.
        The blocks are immediately released to the reuse pool (contents
        intact), so the very next generate() for these tokens admits with a
        full prefix hit — no special remote-prefill state in the scheduler.
        """
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        n = min(int(payload["n_blocks"]), len(blocks))
        if n == 0:
            return 0
        blocks = blocks[:n]
        alloc = self.kv.allocate_sequence(blocks, n)
        if alloc is None:
            return 0  # no capacity; caller falls back to local prefill
        if int(payload.get("block_size", self.cfg.block_size)) != self.cfg.block_size:
            # Mismatched layouts would seal misaligned KV under valid hashes
            # — refuse and let the caller prefill locally.
            logger.warning(
                "rejecting KV import: block_size %s != local %s",
                payload.get("block_size"),
                self.cfg.block_size,
            )
            self.kv.free_sequence(alloc[0])
            return 0
        ids, cached = alloc
        shape = tuple(payload["shape"])
        name = payload["dtype"]
        dt = jnp.bfloat16 if name == "bfloat16" else np.dtype(name)
        k = np.frombuffer(payload["k"], dtype=dt).reshape(shape)
        v = np.frombuffer(payload["v"], dtype=dt).reshape(shape)
        take = n * self.cfg.block_size
        # Pad the slot count to a power-of-two bucket so _inject_fn compiles
        # once per bucket, not once per distinct imported prompt length.
        pad = (1 << max(0, (n - 1).bit_length())) * self.cfg.block_size
        oob = np.int32(self.cfg.num_blocks * self.cfg.block_size)  # dropped
        slots = np.full((pad,), oob, np.int32)
        slots[:take] = self._kv_slots(ids)
        kp = np.zeros(k.shape[:2] + (pad,) + k.shape[3:], k.dtype)
        vp = np.zeros_like(kp)
        kp[:, :, :take] = k[:, :, :take]
        vp[:, :, :take] = v[:, :, :take]

        async with self._device_lock:
            # to_thread: compile/execute must not stall the engine loop.
            self.cache = await asyncio.to_thread(
                self._inject_fn, self.cache, slots, kp, vp
            )
        for bid, tb in zip(ids, blocks):
            self.kv.seal_block(bid, tb)
        self.kv.free_sequence(ids)
        return n * self.cfg.block_size

    def estimate_prefix_hit(self, token_ids: List[int]) -> int:
        """Tokens of ``token_ids`` already resident locally (router input)."""
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size)
        return len(self.kv.match_prefix(blocks)) * self.cfg.block_size

    # -------------------------------------------------------------- the loop
    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(self._run_loop())

    async def _run_loop(self) -> None:
        while not self._closed:
            self._cancel_stopped()
            work = self.scheduler.schedule()
            for seq in self.scheduler.take_rejected():
                self._finish(seq, FinishReason.ERROR)
            if work is None:
                if self.scheduler.num_waiting and not self.scheduler.num_running:
                    # e.g. decode just preempted everyone back to waiting:
                    # retry admission immediately (terminates: each pass
                    # admits or rejects at least one waiting sequence).
                    await asyncio.sleep(0)
                    continue
                # Idle: running is empty (running sequences always yield
                # work), so sleep until a new request arrives.
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                if isinstance(work, PrefillWork):
                    await self._run_prefill(work)
                else:
                    await self._run_decode(work)
            except Exception:  # engine-fatal: fail all inflight requests
                logger.exception("engine step failed")
                self._fail_all()
                return
            self._steps += 1
            await asyncio.sleep(0)  # let ingress/egress run between steps

    def _cancel_stopped(self) -> None:
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            ctx = self._contexts.get(seq.request_id)
            if ctx is not None and ctx.is_stopped and not seq.finished:
                seq.finished = True
                self.scheduler.remove(seq)
                self._finish(seq, FinishReason.CANCELLED)

    def _fail_all(self) -> None:
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            self.scheduler.remove(seq)
            self._finish(seq, FinishReason.ERROR)

    # ------------------------------------------------------------ batch build
    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _pad_tables(self, rows: List[List[int]]) -> np.ndarray:
        width = self.cfg.max_blocks_per_seq
        out = np.zeros((len(rows), width), np.int32)
        for i, r in enumerate(rows):
            out[i, : len(r)] = r[:width]
        return out

    async def _run_prefill(self, work: PrefillWork) -> None:
        bs = self.cfg.block_size
        B = self.cfg.bucket_batch(len(work.items))
        Sq = self.cfg.bucket_prefill(max(chunk for _, _, chunk in work.items))

        tokens = np.zeros((B, Sq), np.int32)
        positions = np.zeros((B, Sq), np.int32)
        slots = np.full((B, Sq), -1, np.int32)
        tables_rows: List[List[int]] = []
        ctx_lens = np.zeros((B,), np.int32)
        logits_idx = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        topk = np.zeros((B,), np.int32)
        topp = np.ones((B,), np.float32)

        for i, (seq, start, chunk) in enumerate(work.items):
            all_toks = seq.prompt + seq.output
            tokens[i, :chunk] = all_toks[start : start + chunk]
            pos = np.arange(start, start + chunk, dtype=np.int32)
            positions[i, :chunk] = pos
            blk_ids = np.asarray(seq.block_ids, np.int32)
            slots[i, :chunk] = blk_ids[pos // bs] * bs + pos % bs
            tables_rows.append(seq.block_ids)
            ctx_lens[i] = start + chunk
            logits_idx[i] = chunk - 1
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
        tables_rows += [[] for _ in range(B - len(work.items))]

        # Plain numpy: host→device transfer happens inside the jitted call on
        # the dispatch thread, not on the event loop (which must stay live
        # for lease keepalives during long compiles).
        batch = ModelBatch(
            token_ids=tokens,
            positions=positions,
            slot_mapping=slots,
            block_tables=self._pad_tables(tables_rows),
            context_lens=ctx_lens,
            logits_idx=logits_idx,
        )
        sampled = await self._dispatch(batch, temp, topk, topp)

        for i, (seq, start, chunk) in enumerate(work.items):
            seq.num_computed = start + chunk
            self._seal_completed_blocks(seq)
            if not seq.in_prefill:  # prompt fully computed → first output token
                self._accept_token(seq, int(sampled[i]))

    async def _run_decode(self, work: DecodeWork) -> None:
        if self.cfg.decode_steps > 1:
            await self._run_decode_multi(work)
            return
        bs = self.cfg.block_size
        B = self.cfg.bucket_batch(len(work.items))

        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B, 1), np.int32)
        slots = np.full((B, 1), -1, np.int32)
        tables_rows: List[List[int]] = []
        ctx_lens = np.zeros((B,), np.int32)
        logits_idx = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        topk = np.zeros((B,), np.int32)
        topp = np.ones((B,), np.float32)

        for i, seq in enumerate(work.items):
            all_toks = seq.prompt + seq.output
            p = seq.num_computed
            tokens[i, 0] = all_toks[p]
            positions[i, 0] = p
            slots[i, 0] = seq.block_ids[p // bs] * bs + p % bs
            tables_rows.append(seq.block_ids)
            ctx_lens[i] = p + 1
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
        tables_rows += [[] for _ in range(B - len(work.items))]

        batch = ModelBatch(
            token_ids=tokens,
            positions=positions,
            slot_mapping=slots,
            block_tables=self._pad_tables(tables_rows),
            context_lens=ctx_lens,
            logits_idx=logits_idx,
        )
        sampled = await self._dispatch(batch, temp, topk, topp)

        for i, seq in enumerate(work.items):
            fed = (seq.prompt + seq.output)[seq.num_computed]
            if seq.num_computed >= len(seq.prompt):
                seq.block_seq.append(fed)
            seq.num_computed += 1
            self._seal_completed_blocks(seq)
            self._accept_token(seq, int(sampled[i]))

    async def _run_decode_multi(self, work: DecodeWork) -> None:
        bs = self.cfg.block_size
        B = self.cfg.bucket_batch(len(work.items))
        T = self.cfg.decode_steps

        tok0 = np.zeros((B,), np.int32)
        pos0 = np.full((B,), -1, np.int32)  # -1 = padding row
        limits = np.zeros((B,), np.int32)
        tables_rows: List[List[int]] = []
        temp = np.zeros((B,), np.float32)
        topk = np.zeros((B,), np.int32)
        topp = np.ones((B,), np.float32)

        for i, seq in enumerate(work.items):
            p = seq.num_computed
            tok0[i] = (seq.prompt + seq.output)[p]
            pos0[i] = p
            limits[i] = len(seq.block_ids) * bs
            tables_rows.append(seq.block_ids)
            temp[i] = seq.sampling_temperature
            topk[i] = seq.sampling_top_k
            topp[i] = seq.sampling_top_p
        tables_rows += [[] for _ in range(B - len(work.items))]
        tables = self._pad_tables(tables_rows)

        rng = self._next_rng()
        step = self._multi_step_fn

        def run() -> np.ndarray:
            toks_dev, self.cache = step(
                self.params, self.cache, tok0, pos0, tables, limits,
                jnp.asarray(temp), jnp.asarray(topk), jnp.asarray(topp), rng,
            )
            return np.asarray(toks_dev)  # [T, B]

        async with self._device_lock:
            sampled = await asyncio.to_thread(run)

        for i, seq in enumerate(work.items):
            for t in range(T):
                if seq.finished:
                    break  # rest of the chunk is discarded
                if seq.num_computed >= limits[i]:
                    break  # beyond allocation: token was never KV-backed
                fed = (seq.prompt + seq.output)[seq.num_computed]
                if seq.num_computed >= len(seq.prompt):
                    seq.block_seq.append(fed)
                seq.num_computed += 1
                self._seal_completed_blocks(seq)
                self._accept_token(seq, int(sampled[t, i]))

    async def _dispatch(self, batch, temp, topk, topp) -> np.ndarray:
        rng = self._next_rng()
        step = self._step_fn

        def run() -> np.ndarray:
            tokens_dev, self.cache = step(
                self.params,
                self.cache,
                batch,
                jnp.asarray(temp),
                jnp.asarray(topk),
                jnp.asarray(topp),
                rng,
            )
            return np.asarray(tokens_dev)

        async with self._device_lock:
            return await asyncio.to_thread(run)

    # ------------------------------------------------------------ per-token
    def _seal_completed_blocks(self, seq: SequenceState) -> None:
        complete = seq.num_computed // self.cfg.block_size
        hashed = len(seq.block_seq.blocks)
        while seq.num_sealed_blocks < min(complete, hashed):
            idx = seq.num_sealed_blocks
            self.kv.seal_block(seq.block_ids[idx], seq.block_seq.blocks[idx])
            seq.num_sealed_blocks += 1

    def _accept_token(self, seq: SequenceState, token: int) -> None:
        seq.output.append(token)
        reason = self._check_stop(seq, token)
        queue = self._queues.get(seq.request_id)
        # Stop-triggering tokens (eos / stop_token_ids) are not emitted,
        # matching the reference Backend's stop handling (backend.rs:234-423).
        if queue is not None and reason is not FinishReason.STOP:
            queue.put_nowait(LLMEngineOutput.token(token))
        if reason is not None:
            seq.finished = True
            self.scheduler.remove(seq)
            self._finish(seq, reason)

    def _check_stop(self, seq: SequenceState, token: int) -> Optional[FinishReason]:
        n_out = seq.num_output_tokens  # survives preemption's prompt-folding
        min_ok = seq.min_new_tokens is None or n_out >= seq.min_new_tokens
        if min_ok and token in seq.stop_token_ids:
            return FinishReason.STOP
        if (
            min_ok
            and not seq.ignore_eos
            and token in self.model_config.eos_token_ids
        ):
            return FinishReason.STOP
        if seq.max_new_tokens is not None and n_out >= seq.max_new_tokens:
            return FinishReason.LENGTH
        if seq.total_tokens >= self.cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    def _finish(self, seq: SequenceState, reason: FinishReason) -> None:
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        queue.put_nowait(
            LLMEngineOutput.finished(
                reason,
                usage={
                    "prompt_tokens": seq.orig_prompt_len,
                    "completion_tokens": seq.num_output_tokens,
                    "total_tokens": seq.total_tokens,
                },
            )
        )
        queue.put_nowait(_FINISHED)
