"""TpuEngine: the native JAX engine behind the AsyncEngine interface.

This is the component the reference delegates to vLLM/sglang subprocesses
(lib/engines/* — SURVEY.md §2.8); here it is in-process and TPU-native.
Round-2 architecture, shaped by measurement on real hardware:

- ONE unified step program per token-count bucket: a flat ragged run of
  tokens mixing prompt chunks and decode tokens (models/llama.py
  forward_ragged over ops/ragged_attention.py).  Decode rows ride along in
  every prefill step, so prefills never starve ITL, and the compile count
  stays tiny (the round-1 separate prefill/decode bucket grid still hit
  cold shapes in production mixes — a single cold XLA compile costs ~15s).
- a fused multi-step decode program (``decode_steps`` iterations per
  dispatch, sampled tokens fed forward ON DEVICE) for the steady state;
- an asynchronous decode PIPELINE: up to ``pipeline_depth`` fused dispatches
  in flight, with the token carry staying on device between dispatches and
  host readback overlapped.  Measured on the tunneled v5e chip: a
  device→host fetch costs ~100ms while a batch-16 decode step costs ~5ms —
  without the pipeline the fetch dominates 20:1.  Stop conditions are
  applied with bounded lag; over-decoded tokens are discarded host-side and
  never land in sealed KV blocks (block sealing happens host-side only for
  accepted tokens).
- KV cache lives in HBM as donated jit operands — scatters update in place;
- KV events (stored/removed, chained hashes) and ForwardPassMetrics are
  emitted exactly as the reference's C-API hooks do
  (lib/bindings/c/src/lib.rs:51-296), feeding the KV-aware router.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.kv_router.protocols import ForwardPassMetrics, KvCacheEvent
from ..llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from ..models.config import ModelConfig, get_config
from ..models.llama import PagedKVCache, RaggedBatch, forward_ragged, init_params
from ..ops.sampling import SamplingParams, sample_tokens
from ..parallel.mesh import (
    MeshConfig,
    make_mesh,
    pages_pspec,
    param_pspecs,
    shard_tree,
    sharding_tree,
)
from ..runtime.engine import AsyncEngine, Context, ResponseStream
from .config import EngineConfig
from .kv_manager import KvBlockManager
from .scheduler import Scheduler, SequenceState, StepPlan

logger = logging.getLogger(__name__)




from .migrate import MigrationMixin
from .offload import HostOffloadMixin
from .pipeline import _FINISHED, DecodePipelineMixin
from .spec import AcceptanceController, SpecDecodeMixin
from .transfer import KvTransferMixin, _scales_close, transfer_blocks_device  # noqa: F401 — compat re-export


class TpuEngine(
    KvTransferMixin, HostOffloadMixin, DecodePipelineMixin, SpecDecodeMixin,
    MigrationMixin, AsyncEngine,
):
    """Token-in/token-out engine (ExecutionContext equivalent)."""

    def __init__(
        self,
        cfg: EngineConfig,
        event_callback: Optional[Callable[[KvCacheEvent], None]] = None,
        params: Any = None,
    ):
        self.cfg = cfg
        from .xla_cache import setup_compilation_cache

        setup_compilation_cache(cfg.compilation_cache_dir)
        self.model_config: ModelConfig = get_config(cfg.model).with_overrides(
            dtype=cfg.dtype
        )
        if cfg.tp > 1 and self.model_config.num_kv_heads % cfg.tp != 0:
            # pages_pspec shards the combined 2*kv_heads axis over tp; a tp
            # that doesn't divide num_kv_heads would split a K/V pair of one
            # head across shards (XLA's divisibility check alone would let
            # e.g. tp == 2*num_kv_heads through).
            raise ValueError(
                f"tp={cfg.tp} must divide num_kv_heads="
                f"{self.model_config.num_kv_heads} (KV pages shard by head)"
            )
        self.kv = KvBlockManager(
            cfg.num_blocks,
            cfg.block_size,
            event_callback=event_callback,
            enable_prefix_caching=cfg.enable_prefix_caching,
        )
        self.scheduler = Scheduler(cfg, self.kv)
        # Draft-free speculative decoding (engine/spec.py): None = off.
        self._spec_ctl = (
            AcceptanceController(cfg.spec_decode)
            if cfg.spec_decode.enable
            else None
        )
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Any] = {}
        self._wake = asyncio.Event()
        self._closed = False
        self._loop_task: Optional[asyncio.Task] = None
        # Serialises device-state access: step functions donate the cache
        # buffers, so export/import must never observe a mid-step cache.
        self._device_lock = asyncio.Lock()
        self._rng = jax.random.PRNGKey(cfg.seed)
        self._steps = 0
        # Multi-host: leader broadcasts every dispatch over this plane so
        # followers keep their device queues in SPMD lockstep (multihost.py).
        self._publisher = None
        self._mirror_carry: Any = None
        # Host KV offload tier (engine/host_cache.py).
        self.host_kv = None
        self.disk_kv = None
        # Durable object-store tier (engine/object_store.py): the only
        # tier that OUTLIVES this process — never removed at close().
        self.object_kv = None
        self._offload_queue: List[Tuple[int, Any]] = []
        self._offload_task: Optional[asyncio.Task] = None
        # Cross-worker prefix pull hook (llm/kv_router/pull.py): the serving
        # layer wires a PrefixPuller; None = pulls disabled.
        self._prefix_puller = None
        # KV integrity plane (engine/integrity.py): negative cache of
        # checksum-failed hashes (always on — the wire plane needs it even
        # without tiers) + the optional self-corruption reporter the
        # serving layer wires to feed the health watchdog.
        from .integrity import CorruptionCache

        self.integrity = CorruptionCache(ttl_s=cfg.kv_corrupt_ttl_s)
        self._integrity_reporter = None
        if cfg.host_cache_bytes > 0:
            # Multi-process: every host keeps a PER-HOST SHARDED tier — it
            # stores only the shards its own devices hold (gathers and
            # restores ride the leader→follower mirror plane, so all
            # processes run the same device programs in the same order).
            from .host_cache import HostKvStore

            self.host_kv = HostKvStore(cfg.host_cache_bytes)
            if cfg.disk_cache_bytes > 0:
                if jax.process_count() > 1:
                    # Per-host sharded tiers hold dict shards the disk
                    # container refuses; multi-host overflow keeps the
                    # pre-tier drop behaviour.
                    logger.warning(
                        "disk KV tier disabled: multi-process runs keep "
                        "per-host sharded host tiers only"
                    )
                else:
                    import os as _os
                    import tempfile as _tempfile

                    from .disk_cache import DiskKvStore

                    # The per-PID default is deliberate: block hashes do
                    # not encode params identity, so a STABLE shared dir
                    # could restore a previous (differently-seeded) run's
                    # KV under valid hashes.  Engine-owned dirs are
                    # removed at close(); only an EXPLICIT disk_cache_dir
                    # (operator owns params stability) survives restarts
                    # and benefits from the re-index.
                    self._disk_dir_owned = cfg.disk_cache_dir is None
                    d = cfg.disk_cache_dir or _os.path.join(
                        _tempfile.gettempdir(),
                        f"dynamo_tpu_kv_{_os.getpid()}",
                    )
                    fsync = cfg.disk_fsync or _os.environ.get(
                        "DYN_DISK_FSYNC", ""
                    ) not in ("", "0", "false")
                    self.disk_kv = DiskKvStore(
                        cfg.disk_cache_bytes, d, fsync=fsync
                    )
                    self.host_kv.on_evict = self._demote_to_disk
                    if cfg.object_store_bytes > 0:
                        from .object_store import ObjectKvStore

                        ofsync = cfg.object_store_fsync or _os.environ.get(
                            "DYN_OBJSTORE_FSYNC", ""
                        ) not in ("", "0", "false")
                        self.object_kv = ObjectKvStore(
                            cfg.object_store_bytes,
                            cfg.object_store_dir,
                            fsync=ofsync,
                        )
                        self.disk_kv.on_evict = self._demote_to_objstore
            # HBM eviction of a block a lower tier retains emits a
            # tier-tagged event instead of Removed (kv_manager).
            self.kv.tier_lookup = self._tier_of
        # Per-dispatch trace: (kind, wall_s, rows, device_tokens); the
        # pipeline records dispatch and fetch separately since they
        # overlap.  Bounded: a long-lived server must not grow it forever.
        self.step_trace: deque = deque(maxlen=65536)
        # Largest observed gap between engine-loop iterations (stall
        # attribution; reset by clearing alongside step_trace readers).
        self.loop_gap_max = 0.0
        # Mixed-phase cadence: prefill chunks run since the last decode
        # burst (see _run_loop).
        self._chunks_since_burst = 0
        # Preemption/migration requeues of mid-prefill sequences observed
        # via the scheduler counter; a requeue resets the cadence so the
        # NEXT mixed phase does not inherit a stale chunk count and burst
        # immediately (_note_prefill_requeues).
        self._prefill_requeues_seen = 0
        # Prefill-chunk accounting (pipeline._run_unified): cumulative
        # chunk count / wall / prompt tokens plus a bounded per-chunk wall
        # trace for the latency quantiles on /metrics
        # (dynamo_tpu_prefill_chunk_seconds) and in the bench JSON.
        self.prefill_chunks = 0
        self.prefill_wall_s = 0.0
        self.prefill_tokens = 0
        self._prefill_chunk_trace: deque = deque(maxlen=4096)
        # Deferred token fetches (FIFO).  Prompt-completing unified steps
        # AND mixed-phase decode bursts start their token D2H
        # asynchronously, park their rows (awaiting_fetch), and keep the
        # loop dispatching; accepts happen at harvest points once the
        # round trip has overlapped with real work.  r4 measured one
        # blocking ~230ms fetch per request plus ~230ms of queue+RTT per
        # burst on the tunneled chip — together over half of
        # mid-concurrency wall time.
        self._pending_fetches: List[Tuple] = []
        # Request ids with fused-pipeline dispatches potentially in flight
        # (maintained DYNAMICALLY across each _decode_pipeline session —
        # continuous admission adds ids as sequences join, retirement
        # removes them once the write barrier passes); live migration's
        # freeze waits until its sequence leaves this set.
        self._pipeline_members: set = set()
        # Continuous-batching pipeline health (engine/pipeline.py): how
        # often fused sessions start/drain, and how much membership churn
        # the in-loop paths absorbed without a drain.  Exported on /metrics
        # as dynamo_tpu_engine_dispatch_* (llm/metrics.py) and folded into
        # the bench JSON.
        self.pipeline_sessions = 0       # _decode_pipeline runs begun
        self.pipeline_rebuilds = 0       # sessions drained by a rebuild event
        self.continuous_admissions = 0   # sequences admitted in-loop
        self.continuous_retired = 0      # rows retired in-loop (no drain)
        self.pipeline_wall_s = 0.0       # cumulative fused-session wall
        # Device-busy wall accumulated INSIDE fused sessions (decode
        # dispatch/wait + interleaved admission-prefill steps).  Unbounded
        # like pipeline_wall_s — host_gap_frac must never be derived from
        # the BOUNDED step_trace, whose eviction after 65k entries would
        # drift the ratio toward 1.0 on a long-lived server.
        self.decode_busy_s = 0.0
        # Decode-stall watchdog (r5 diagnosed a ~3-minute decode_wait hang
        # with NO engine-side detector): a token fetch / device dispatch
        # that exceeds the threshold trips a loud log with the recent
        # dispatch trace, bumps this counter (dynamo_tpu_engine_stall_total
        # on /metrics) and surfaces in dispatch_summary() so the health
        # watchdog's straggler path can see a wedged device even while the
        # worker still answers probes.  Config decode_stall_s; None
        # resolves DYN_DECODE_STALL_S; 0 = off (default).
        import os as _os

        self._stall_threshold_s = float(
            cfg.decode_stall_s
            if cfg.decode_stall_s is not None
            else _os.environ.get("DYN_DECODE_STALL_S", "0") or 0
        )
        self.decode_stalls = 0  # fetches that exceeded the threshold
        self.last_stall: Optional[Dict[str, Any]] = None
        # Injectable pace hook: awaited before every device-op await
        # (pipeline._pace) when set.  None (the default) is a single attr
        # check — zero hot-path cost.  Tests use it to throttle decode
        # deterministically (e.g. so a migration's copy loop provably
        # outpaces the sequence on slow containers) instead of racing
        # wall-clock sleeps.  Contract: the hook is awaited OUTSIDE the
        # device lock, so it may BLOCK indefinitely — barrier hooks (the
        # migration copy-round gate in tests/test_migration.py) cannot
        # deadlock the KV copy/export plane, which takes the lock only
        # between paced ops.
        self.pace_hook: Optional[Callable[[], Any]] = None
        # Multi-tenancy (llm/tenancy): LoRA adapter registry (None = LoRA
        # disabled), optional served-model allowlist (unknown names →
        # ModelNotFoundError → 404 at the edge), and the deserialized
        # grammar-automaton LRU (requests ship automata by content hash).
        self._lora_registry = None
        self._served_models: Optional[set] = None
        from collections import OrderedDict as _OD

        self._grammar_lru: "Any" = _OD()

        # --- device state -------------------------------------------------
        mesh_cfg = MeshConfig(dp=cfg.dp, tp=cfg.tp, ep=cfg.ep, sp=cfg.sp)
        self.mesh = make_mesh(mesh_cfg) if mesh_cfg.num_devices > 1 else None
        # In a multi-process (multi-host) run, host-side step inputs must be
        # assembled into replicated GLOBAL arrays before they can feed a jit
        # over the global mesh.
        self._rep_sharding = None
        if jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            if self.mesh is None:
                raise ValueError(
                    "multi-process run needs a device mesh (dp*tp*ep == "
                    f"global devices, got {mesh_cfg.num_devices})"
                )
            self._rep_sharding = NamedSharding(self.mesh, PartitionSpec())
        if params is None:
            if cfg.checkpoint_path:
                from ..models.loader import load_params

                params = load_params(
                    self.model_config, cfg.checkpoint_path, quant=cfg.weight_quant
                )
            elif cfg.weight_quant:
                from ..models.quant import init_params_quantized

                # Direct int8 init — full-depth random bf16 would OOM the
                # chip before it could be quantized.
                params = init_params_quantized(
                    self.model_config, jax.random.PRNGKey(cfg.seed)
                )
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(cfg.seed))
        elif cfg.weight_quant:
            from ..models.quant import quantize_params

            params = quantize_params(params)  # no-op if already quantized
        if (
            cfg.fuse_projections
            and not self.model_config.is_moe
            and self.mesh is None  # single-shard only (see fuse_projections)
        ):
            from ..models.quant import fuse_projections

            params = fuse_projections(params)
        if cfg.lora.enable:
            # Fixed-shape multi-LoRA device banks (llm/tenancy/lora.py):
            # R resident slots × rank-r A/B factors per attention
            # projection, zero-initialized (an all-zero slot is exactly the
            # base model).  Added AFTER quantize/fuse so the base tree is
            # final — adapters are merge-free and never touch it.  The
            # leaves live in params["layers"] so the layer scan slices them
            # per layer like any other stacked weight.
            if self.mesh is not None:
                raise ValueError(
                    "lora.enable requires a single-shard engine in this "
                    "build (tp/dp/ep/sp == 1): the adapter banks have no "
                    "PartitionSpecs yet"
                )
            from ..llm.tenancy.lora import bank_leaves

            dt = jnp.dtype(cfg.dtype)
            for name, leaf in bank_leaves(
                self.model_config, cfg.lora.max_adapters, cfg.lora.rank
            ).items():
                params["layers"][name] = jnp.asarray(leaf, dt)
        cache = PagedKVCache.create(
            self.model_config,
            cfg.num_blocks,
            cfg.block_size,
            dtype=jnp.dtype(cfg.cache_dtype),
        )
        if self.mesh is not None:
            params = shard_tree(params, param_pspecs(self.model_config), self.mesh)
            cache = shard_tree(cache, PagedKVCache(pages_pspec()), self.mesh)
        self.params = params
        self.cache = cache
        # Quantized-scale resolution AFTER sharding: the calibration probe
        # runs over the (possibly tp/dp-sharded) params on the engine's own
        # mesh — a single-device probe would materialize the whole model on
        # one chip, OOMing exactly the tp>1 configurations quantized KV
        # exists for.
        if jnp.dtype(cfg.cache_dtype).itemsize == 1:
            if isinstance(cfg.kv_scale, str):
                if cfg.kv_scale != "auto":
                    raise ValueError(f"unknown kv_scale {cfg.kv_scale!r}")
                self.kv_scale = self._calibrate_kv_scales(params)
            elif isinstance(cfg.kv_scale, (list, tuple, np.ndarray)):
                self.kv_scale = np.asarray(cfg.kv_scale, np.float32)
            else:
                self.kv_scale = float(cfg.kv_scale)
        else:
            self.kv_scale = None

        model_config, bs = self.model_config, cfg.block_size
        attn_impl = cfg.attn_impl
        if attn_impl == "auto":
            from ..ops.ragged_attention import on_tpu

            attn_impl = "tpu" if on_tpu() else "xla"
        self.attn_impl = attn_impl
        # Decode-path kernel selector (config > DYN_DECODE_KERNEL env >
        # auto) + the tuned block-hint table for this engine's geometry
        # (tools/tune_decode.py; built-in defaults when no entry matches).
        from ..ops.decode_attention import install_tuned_hints
        from ..ops.ragged_attention import (
            resolve_decode_kernel,
            resolve_prefill_kernel,
        )

        decode_kernel = resolve_decode_kernel(
            cfg.decode_kernel, attn_impl=attn_impl
        )
        self.decode_kernel = decode_kernel
        prefill_kernel = resolve_prefill_kernel(
            cfg.prefill_kernel, attn_impl=attn_impl
        )
        self.prefill_kernel = prefill_kernel
        install_tuned_hints(cfg.model, cfg.max_batch, cfg.block_size)
        logger.info(
            "decode kernel: %s, prefill kernel: %s (attn_impl=%s)",
            decode_kernel, prefill_kernel, attn_impl,
        )
        S = cfg.max_batch
        mesh = self.mesh
        # Quantized (1-byte) KV pages: a static scale, or per-layer scales
        # calibrated at init (kv_scale == "auto"; resolved above, before
        # sharding).  Arrays fold into the forward algebraically
        # (models/llama.py), so they stay fully traced.
        kv_scale = self.kv_scale
        # Static LoRA bank geometry (0 = disabled): captured by the jitted
        # closures, so constrained/LoRA rows run the SAME compiled programs
        # as base rows — the whole point of the per-row design.
        lora_rank = cfg.lora.rank if cfg.lora.enable else 0
        self._lora_rank = lora_rank

        def _step(params, cache, rb, samp):
            logits, cache = forward_ragged(
                params, model_config, rb, cache, attn_impl=attn_impl,
                mesh=mesh, kv_scale=kv_scale, lora_rank=lora_rank,
                prefill_kernel=prefill_kernel,
            )
            out = sample_tokens(
                logits,
                samp.seeds,
                samp.steps,
                samp.temperature,
                samp.top_k,
                samp.top_p,
                samp.freq_penalty,
                samp.pres_penalty,
                samp.counts,
                samp.need_logprobs,
                samp.mask_words,
                samp.any_mask,
            )
            return out, cache

        T_steps = cfg.decode_steps

        def _multi(params, cache, tok0, steps0, counts0, pos0, tables, limits, samp):
            """``decode_steps`` fused decode iterations: one dispatch, the
            sampled token feeds the next step ON DEVICE, and the final token
            carry is returned un-fetched so the next dispatch can chain to it
            without a host round trip.

            ``pos0[s]`` is -1 for padding rows; ``limits[s]`` is the
            allocated KV capacity — steps whose position reaches it skip the
            cache write (their tokens are discarded host-side).  Output-token
            counts (penalties) and per-row rng stream positions advance ON
            DEVICE across the fused steps.
            """
            cu = jnp.arange(S + 1, dtype=jnp.int32)
            num = jnp.full((1,), S, jnp.int32)
            active = pos0 >= 0

            def body(carry, _):
                cache, tok, pos, steps, counts = carry
                posc = jnp.maximum(pos, 0)
                slot = (
                    tables[jnp.arange(S), posc // bs] * bs + posc % bs
                )
                writable = active & (posc < limits)
                slot = jnp.where(writable, slot, -1)
                rb = RaggedBatch(
                    token_ids=tok,
                    positions=posc,
                    slot_mapping=slot,
                    # Padding rows attend over 1 garbage token (never 0 —
                    # keeps the kernel's per-row loop well-defined).
                    kv_lens=jnp.where(active, jnp.minimum(pos + 1, limits), 1),
                    page_indices=tables,
                    cu_q_lens=cu,
                    num_seqs=num,
                    # Decode rows: one token per row, so the per-row slots
                    # (llm/tenancy multi-LoRA) are the per-token slots.
                    adapter_slots=samp.adapter_slots,
                )
                logits, cache = forward_ragged(
                    params, model_config, rb, cache, attn_impl=attn_impl,
                    mesh=mesh, kv_scale=kv_scale, decode=True,
                    decode_kernel=decode_kernel, lora_rank=lora_rank,
                )
                out = sample_tokens(
                    logits,
                    samp.seeds,
                    steps,
                    samp.temperature,
                    samp.top_k,
                    samp.top_p,
                    samp.freq_penalty,
                    samp.pres_penalty,
                    counts,
                    samp.need_logprobs,
                    samp.mask_words,
                    samp.any_mask,
                )
                nxt = out.tokens
                counts = counts.at[jnp.arange(S), nxt].add(
                    active.astype(counts.dtype)
                )
                carry = (
                    cache,
                    nxt,
                    jnp.where(active, pos + 1, pos),
                    jnp.where(active, steps + 1, steps),
                    counts,
                )
                return carry, out

            (cache, last, _, steps_f, counts_f), outs = jax.lax.scan(
                body,
                (cache, tok0, pos0, steps0, counts0),
                None,
                length=T_steps,
            )
            # outs: SampleOut of [decode_steps, ...]; (last, steps_f,
            # counts_f) is the ON-DEVICE carry the next dispatch chains to.
            return outs, last, steps_f, counts_f, cache

        def _gather(cache, page_ids):
            # Batched block gather for host offload; OOB padding ids clamp
            # (their slices are ignored at store time).
            return cache.pages[:, page_ids]

        def _inject(cache, page_ids, new_pages):
            # Donated in-place page scatter for KV imports; padding ids are
            # out of range and dropped, so callers can bucket the page count
            # to bound recompiles.
            # Same quantization as the ragged write path (shared helper) —
            # injected/sp-prefilled blocks must never diverge numerically
            # from normal-prefill blocks under the same hashes.
            from ..ops.ragged_attention import quantize_for_cache

            pages = cache.pages.at[:, page_ids].set(
                quantize_for_cache(new_pages, cache.pages.dtype), mode="drop"
            )
            return PagedKVCache(pages)

        donate = (1,)
        if self.mesh is None:
            self._step_fn = jax.jit(_step, donate_argnums=donate)
            self._multi_fn = jax.jit(_multi, donate_argnums=donate)
            self._inject_fn = jax.jit(_inject, donate_argnums=(0,))
        else:
            cache_sh = sharding_tree(cache, PagedKVCache(pages_pspec()), self.mesh)
            self._step_fn = jax.jit(
                _step, donate_argnums=donate, out_shardings=(None, cache_sh)
            )
            self._multi_fn = jax.jit(
                _multi,
                donate_argnums=donate,
                out_shardings=(None, None, None, None, cache_sh),
            )
            self._inject_fn = jax.jit(
                _inject, donate_argnums=(0,), out_shardings=cache_sh
            )
        self._gather_fn = jax.jit(_gather)  # host offload (no donation)

        if cfg.sp > 1:
            from ..models.llama import forward_sp_prefill

            def _sp(params, toks, valid):
                return forward_sp_prefill(
                    params, model_config, toks, valid, mesh
                )

            self._sp_fn = jax.jit(_sp)
        else:
            self._sp_fn = None
        # copy_to_host_async capability, probed ONCE on a real device array:
        # the per-dispatch ``except AttributeError: pass`` it replaces could
        # mask a genuine attribute error raised INSIDE the logprobs D2H path
        # (a renamed SampleOut field, a None leaf) — silently degrading
        # every fetch to a synchronous round trip instead of failing loudly
        # (engine/pipeline.py _start_d2h).
        self._copy_async = hasattr(
            jnp.zeros((1,), jnp.int32), "copy_to_host_async"
        )
        # Cached all-zeros penalty-counts buffer (see _sampling_arrays).
        self._zero_counts = jnp.zeros(
            (S, self.model_config.vocab_size), jnp.int16
        )
        # Cached all-zeros grammar-mask buffer ([S, ceil(V/32)] packed
        # bits): rides every unconstrained step cond-skipped, so the
        # common path pays no H2D for the tenancy machinery.
        self._mask_w = (self.model_config.vocab_size + 31) // 32
        self._zero_mask = jnp.zeros((S, self._mask_w), jnp.uint32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            if jax.process_count() == 1:
                rep = NamedSharding(self.mesh, PartitionSpec())
                self._zero_counts = jax.device_put(self._zero_counts, rep)
                self._zero_mask = jax.device_put(self._zero_mask, rep)
            else:
                self._zero_counts = self._prep(
                    np.zeros((S, self.model_config.vocab_size), np.int16)
                )
                self._zero_mask = self._prep(
                    np.zeros((S, self._mask_w), np.uint32)
                )
        if cfg.lora.enable:
            from ..llm.tenancy.lora import AdapterRegistry

            self._lora_registry = AdapterRegistry(
                cfg.lora.max_adapters,
                cfg.lora.rank,
                self._lora_apply,
                promote_timeout_s=cfg.lora.promote_timeout_s,
            )

    def _calibrate_kv_scales(self, params) -> np.ndarray:
        """Per-layer quantization scales from a probe forward: run a short
        deterministic token run through the model with a throwaway bf16
        cache, take each layer's max |K/V|, and map it to the target
        dtype's representable max.  Runs on the engine's own mesh (sharded
        params + sharded probe cache), so tp>1 models that don't fit one
        chip calibrate fine; multi-host deployments pass the calibrated
        vector explicitly via kv_scale."""
        if jax.process_count() > 1:
            raise ValueError(
                "kv_scale='auto' calibrates on one process; run calibration "
                "single-host and pass the resulting scales explicitly"
            )
        cfg, mc = self.cfg, self.model_config
        # Probe length bounded so nb (+1 slack) fits a single row's table.
        T = min(128, (cfg.max_blocks_per_seq - 1) * cfg.block_size)
        nb = (T + cfg.block_size - 1) // cfg.block_size + 1
        probe = PagedKVCache.create(mc, nb, cfg.block_size, dtype=jnp.bfloat16)
        if self.mesh is not None:
            probe = shard_tree(probe, PagedKVCache(pages_pspec()), self.mesh)
        toks = ((np.arange(T) * 2654435761) % mc.vocab_size).astype(np.int32)
        pos = np.arange(T, dtype=np.int32)
        S = cfg.max_batch
        # Table width = the probe's own nb pages, NOT max_blocks_per_seq:
        # the XLA fallback materializes [T, width*bs, 2KV, hd] f32, which
        # at long-context configs would be tens of GB.
        tables = np.zeros((S, nb), np.int32)
        tables[0, :nb] = np.arange(nb)
        cu = np.zeros((S + 1,), np.int32)
        cu[1:] = T
        rb = RaggedBatch(
            token_ids=toks,
            positions=pos,
            slot_mapping=pos,  # consecutive slots in blocks 0..nb
            kv_lens=np.asarray([T] + [0] * (S - 1), np.int32),
            page_indices=tables,
            cu_q_lens=cu,
            num_seqs=np.asarray([1], np.int32),
        )
        _, probe = jax.jit(
            lambda p, c: forward_ragged(
                p, mc, rb, c, attn_impl="xla", mesh=self.mesh
            )
        )(params, probe)
        # [L, nb, ps, 2KV, hd] → per-layer max |value| over everything else.
        maxabs = np.asarray(
            jnp.max(
                jnp.abs(probe.pages.astype(jnp.float32)), axis=(1, 2, 3, 4)
            )
        )
        dt = jnp.dtype(cfg.cache_dtype)
        if jnp.issubdtype(dt, jnp.integer):
            qmax = float(jnp.iinfo(dt).max)
        else:
            qmax = float(jnp.finfo(dt).max)  # e4m3 → 448
        scales = np.maximum(maxabs / qmax, 1e-6).astype(np.float32)
        logger.info(
            "calibrated per-layer kv scales (dtype %s): min %.4g max %.4g",
            dt, scales.min(), scales.max(),
        )
        return scales

    def _kv_scale_repr(self):
        """JSON-safe scale for transfer payloads: None, float, or list."""
        if self.kv_scale is None:
            return None
        a = np.asarray(self.kv_scale, np.float32).reshape(-1)
        return [float(x) for x in a] if a.size > 1 else float(a[0])

    # ------------------------------------------------------------ multi-host
    def attach_publisher(self, publisher) -> None:
        """Leader side: broadcast every device dispatch to the followers
        (engine/multihost.py StepPublisher)."""
        self._publisher = publisher

    def _prep(self, tree: Any) -> Any:
        """Host arrays → replicated global arrays when multi-process."""
        if self._rep_sharding is None:
            return tree
        from ..parallel.distributed import global_array

        return jax.tree_util.tree_map(
            lambda x: global_array(x, self._rep_sharding), tree
        )

    async def run_warmup(self) -> Dict[str, int]:
        """warmup() that keeps followers in lockstep (use in serving paths;
        plain warmup() is fine single-process)."""
        async with self._device_lock:
            if self._publisher is not None:
                await self._publisher.publish("warmup")
            return await asyncio.to_thread(self.warmup)

    async def mirror_step(self, kind: str, payload: Tuple) -> None:
        """Follower side: replay one leader dispatch (same jitted fns, same
        global arrays, same order → SPMD lockstep)."""
        if kind == "warmup":
            await asyncio.to_thread(self.warmup)
        elif kind == "unified":
            rb, samp = payload

            def run_u():
                _, self.cache = self._step_fn(
                    self.params,
                    self.cache,
                    self._prep(rb),
                    self._prep(samp),
                )

            async with self._device_lock:
                await asyncio.to_thread(run_u)
        elif kind == "multi":
            tok0, pos0, tables, limits, samp = payload
            carry = self._mirror_carry if tok0 is None else None

            def run_m():
                samp_d = self._prep(samp)
                if carry is None:
                    tok, steps0, counts0 = (
                        self._prep(tok0), samp_d.steps, samp_d.counts
                    )
                else:
                    tok, steps0, counts0 = carry
                _, last, steps_f, counts_f, self.cache = self._multi_fn(
                    self.params,
                    self.cache,
                    tok,
                    steps0,
                    counts0,
                    *self._prep((pos0, tables, limits)),
                    samp_d,
                )
                return (last, steps_f, counts_f)

            async with self._device_lock:
                self._mirror_carry = await asyncio.to_thread(run_m)
        elif kind == "inject":
            page_ids, comb_p = payload

            def run_i():
                self.cache = self._inject_fn(
                    self.cache, *self._prep((page_ids, comb_p))
                )

            async with self._device_lock:
                await asyncio.to_thread(run_i)
        elif kind == "offload":
            ids, hashes = payload
            async with self._device_lock:
                await asyncio.to_thread(self._offload_store, ids, hashes)
            # Followers record host-tier drops too (no event callback to
            # publish to, but the transition list must not grow forever).
            self._flush_tier_events()
        elif kind == "restore_host":
            page_ids, hashes = payload
            async with self._device_lock:
                await asyncio.to_thread(self._restore_inject, page_ids, hashes)
            self._flush_tier_events()
        else:
            raise ValueError(f"unknown mirror step kind {kind!r}")

    # ---------------------------------------------------------------- warmup
    def compile_counts(self) -> Dict[str, int]:
        """Compiled-program count per jitted entry (cache sizes).  The bench
        asserts these do not grow inside its timed window."""
        out: Dict[str, int] = {}
        for name, fn in (
            ("step", self._step_fn),
            ("multi", self._multi_fn),
            ("inject", self._inject_fn),
        ):
            try:
                out[name] = fn._cache_size()
            except AttributeError:  # older jax: best-effort
                out[name] = -1
        return out

    def reachable_token_buckets(self) -> List[int]:
        """Every token bucket the scheduler can hand _run_unified: up to
        max_batch decode rows ride alongside up to prefill_chunk prompt
        tokens in one step (decode rows don't consume the prefill budget),
        so totals range 1..prefill_chunk + max_batch."""
        hi = self.cfg.bucket_tokens(self.cfg.prefill_chunk + self.cfg.max_batch)
        buckets, b = [], self.cfg.bucket_tokens(1)
        while b < hi:
            buckets.append(b)
            b *= 2
        buckets.append(hi)
        return buckets

    def warmup(self) -> Dict[str, int]:
        """Pre-compile every device program the serving loop can dispatch —
        one unified step per reachable token bucket plus the fused decode
        program — so no cold XLA compile (~15s on TPU) ever lands inside a
        request.  All runs carry slot/pos = -1 so cache writes are dropped
        (write_kv_ragged) and contents are untouched.  Returns compile_counts.
        """
        cfg = self.cfg
        S, PP = cfg.max_batch, cfg.max_blocks_per_seq
        samp = self._sampling_arrays([])  # greedy defaults, cached counts
        for T in self.reachable_token_buckets():
            cu = np.zeros((S + 1,), np.int32)
            cu[1:] = T  # one row owns every token; others empty
            rb = RaggedBatch(
                token_ids=np.zeros((T,), np.int32),
                positions=np.zeros((T,), np.int32),
                slot_mapping=np.full((T,), -1, np.int32),  # writes dropped
                # kv_len == q_len: the ragged contract (and the pallas
                # kernel's validation) requires q_len <= kv_len per row.
                kv_lens=np.asarray([T] + [0] * (S - 1), np.int32),
                page_indices=np.zeros((S, PP), np.int32),
                cu_q_lens=cu,
                num_seqs=np.asarray([1], np.int32),
                adapter_slots=(
                    np.full((T,), -1, np.int32) if self._lora_rank else None
                ),
            )
            out, self.cache = self._step_fn(
                self.params, self.cache, self._prep(rb), self._prep(samp)
            )
        if cfg.decode_steps > 1:
            args = self._prep(
                (
                    np.full((S,), -1, np.int32),  # every row inactive
                    np.zeros((S, PP), np.int32),
                    np.zeros((S,), np.int32),
                )
            )
            _, last, steps_f, counts_f, self.cache = self._multi_fn(
                self.params,
                self.cache,
                self._prep(np.zeros((S,), np.int32)),
                self._prep(samp.steps),
                samp.counts,
                *args,
                self._prep(samp),
            )
            # Chain once more with the DEVICE carry: pipeline dispatches 2+
            # feed the previous outputs back in, and committed device arrays
            # key a different executable-cache entry than the uncommitted
            # numpy first dispatch.
            _, last, _, _, self.cache = self._multi_fn(
                self.params, self.cache, last, steps_f, counts_f,
                *args, self._prep(samp)
            )
            # A real fetch, not block_until_ready: some remote-execution
            # backends treat block_until_ready as a local no-op, and warmup
            # must not return with compiles/executions still queued (the
            # first real request would absorb them).
            np.asarray(last)
        else:
            np.asarray(out.tokens)
        if self._sp_fn is not None:
            # Every reachable sp-prefill token bucket (pow2, sp multiple,
            # sp_prefill_min..max_model_len) — a cold whole-model compile
            # must never land inside a request.
            lo = max(cfg.sp, 1 << (max(1, cfg.sp_prefill_min) - 1).bit_length())
            hi = max(lo, 1 << (cfg.max_model_len - 1).bit_length())
            t = lo
            while True:
                Tg = t + (-t) % cfg.sp
                logits_sp, _ = self._sp_fn(
                    self.params,
                    np.zeros((Tg,), np.int32),
                    np.asarray(Tg, np.int32),
                )
                np.asarray(logits_sp)  # real fetch (see above)
                if t >= hi:
                    break
                t *= 2
        return self.compile_counts()

    # ----------------------------------------------------------- tenancy API
    def register_adapter(self, adapter) -> None:
        """Host-register a LoraAdapter (llm/tenancy/lora.py) — no engine
        restart, no recompile; promotion to a device slot happens lazily on
        first request."""
        if self._lora_registry is None:
            raise RuntimeError(
                "LoRA serving is disabled (EngineConfig.lora.enable)"
            )
        self._lora_registry.register(adapter, self.model_config)
        if self._served_models is not None:
            self._served_models.add(adapter.name)

    def unregister_adapter(self, name: str) -> None:
        if self._lora_registry is not None:
            self._lora_registry.unregister(name)
            # Keep the allowlist in lockstep: a name left behind would let
            # requests for the removed adapter silently run the base model.
            if self._served_models is not None:
                self._served_models.discard(name)

    def adapter_names(self) -> List[str]:
        return self._lora_registry.names() if self._lora_registry else []

    def set_served_models(self, names) -> None:
        """Optional allowlist of model names this engine serves (base +
        adapters).  When set, a request naming anything else fails with
        ModelNotFoundError (the 404 model_not_found body at the edge)
        instead of silently running the base model."""
        self._served_models = set(names) if names is not None else None

    async def _lora_apply(self, slot: int, adapter) -> None:
        """Registry promotion hook: write one slot's (rank-padded) factors
        into the device banks.  Functional .at[].set under the device lock —
        in-flight dispatches keep their old param tree; the registry
        guarantees the slot has no live rows."""
        from ..llm.tenancy.lora import LORA_TARGETS, padded_factors

        r = self.cfg.lora.rank
        lo, hi = slot * r, (slot + 1) * r

        def run():
            layers = self.params["layers"]
            for tgt in LORA_TARGETS:
                a, b = padded_factors(adapter, self.model_config, tgt, r)
                dt = layers[f"lora_a_{tgt}"].dtype
                layers[f"lora_a_{tgt}"] = (
                    layers[f"lora_a_{tgt}"].at[:, :, lo:hi].set(jnp.asarray(a, dt))
                )
                layers[f"lora_b_{tgt}"] = (
                    layers[f"lora_b_{tgt}"].at[:, lo:hi, :].set(jnp.asarray(b, dt))
                )

        async with self._device_lock:
            await asyncio.to_thread(run)

    def _grammar_automaton(self, g: Dict[str, Any]):
        """Deserialize (or LRU-hit) a request's token-mask automaton and fix
        its mask geometry to this engine's vocab/eos.

        Hash-first wire protocol (llm/tenancy): the preprocessor ships a
        hash-only stub by default; a content-hash LRU hit resolves it with
        zero table bytes on the wire, a miss raises GrammarCacheMissError
        (prologue kind ``grammar_miss``) and the preprocessor re-sends the
        full edge table exactly once."""
        from ..llm.metrics import tenancy_metrics
        from ..llm.tenancy.grammar import (
            GrammarCacheMissError,
            TokenMaskAutomaton,
        )

        key = g.get("hash")
        automaton = self._grammar_lru.pop(key, None) if key else None
        if automaton is None:
            if g.get("stub") or "edges" not in g:
                tenancy_metrics.grammar_hash_misses_total += 1
                raise GrammarCacheMissError(str(key))
            automaton = TokenMaskAutomaton.from_dict(g)
        elif g.get("stub"):
            tenancy_metrics.grammar_hash_hits_total += 1
        self._grammar_lru[automaton.hash] = automaton  # LRU refresh/insert
        while len(self._grammar_lru) > 32:
            self._grammar_lru.pop(next(iter(self._grammar_lru)))
        automaton.set_mask_context(
            self.model_config.vocab_size, self.model_config.eos_token_ids
        )
        return automaton

    def _resolve_adapter(self, pre: PreprocessedRequest) -> Optional[str]:
        """Adapter name for this request, or None for the base model.
        Raises ModelNotFoundError for names nobody serves (satellite: never
        silently fall through to the base model)."""
        from ..llm.metrics import tenancy_metrics
        from ..llm.protocols import ModelNotFoundError

        name = pre.annotations.get("adapter")
        if not isinstance(name, str) or not name:
            name = None
        if name is None and pre.model:
            if self._lora_registry is not None and self._lora_registry.has(
                pre.model
            ):
                name = pre.model
            elif self._served_models is not None:
                if pre.model not in self._served_models:
                    tenancy_metrics.adapter_not_found_total += 1
                    raise ModelNotFoundError(pre.model)
            elif (
                self._lora_registry is not None
                and pre.model != self.cfg.model
            ):
                # LoRA-enabled engines serve many logical models by NAME, so
                # a name that is neither the base model nor a registered
                # adapter is a routing mistake — fail it rather than
                # silently running the base model.  (LoRA-less engines keep
                # the historical behaviour: the model field is advisory.)
                tenancy_metrics.adapter_not_found_total += 1
                raise ModelNotFoundError(pre.model)
        if name is not None and (
            self._lora_registry is None or not self._lora_registry.has(name)
        ):
            tenancy_metrics.adapter_not_found_total += 1
            raise ModelNotFoundError(name)
        return name

    # ------------------------------------------------------------ public API
    async def generate(self, request: Context) -> ResponseStream:
        if self._closed:
            raise RuntimeError("engine is closed")
        pre = PreprocessedRequest.from_dict(request.data)
        if len(pre.token_ids) > self.cfg.max_model_len:
            raise ValueError(
                f"prompt length {len(pre.token_ids)} exceeds max_model_len "
                f"{self.cfg.max_model_len}"
            )
        # Multi-tenancy resolution (llm/tenancy) BEFORE admission: the
        # adapter decides the KV salt, which must root the block-hash chain
        # from the very first sealed block.
        adapter = self._resolve_adapter(pre)
        automaton = None
        if pre.grammar:
            from ..llm.metrics import tenancy_metrics

            automaton = self._grammar_automaton(pre.grammar)
            tenancy_metrics.grammar_requests_total += 1
        if adapter is not None:
            from ..llm.tenancy.lora import kv_salt_for_adapter

            pre.annotations.setdefault("kv_salt", kv_salt_for_adapter(adapter))
        # Tenant salt (llm/tenancy): every pre-admission KV preparation
        # below hashes with it, so a tenant request can only ever see —
        # and seal — blocks under its own chain.
        salt = pre.annotations.get("kv_salt") or None
        # Distributed tracing (runtime/tracing.py): the context arrives via
        # annotations.trace (preprocessor / disagg item / migration resume)
        # or the service-transport header (request.ctx.trace); None keeps
        # every instrumentation point below a single attr check.
        from ..runtime.tracing import parse_trace as _parse_trace
        from ..runtime.tracing import span as _trace_span

        trace = _parse_trace(pre.annotations.get("trace")) or getattr(
            request.ctx, "trace", None
        )
        self._ensure_loop()
        prepared = 0
        if self.host_kv is not None and (
            len(self.host_kv)
            or (self.disk_kv is not None and len(self.disk_kv))
            or (self.object_kv is not None and len(self.object_kv))
        ):
            # Pull any evicted prefix blocks back from the host/disk tiers
            # BEFORE admission, so the scheduler sees them as prefix-cache
            # hits (the reference's restore-ahead-of-prefill TTFT win).
            # The tiers index blocks by the (salted) hashes they sealed
            # under, so tenant restores hit exactly their own blocks.
            from ..llm.metrics import kv_tier_metrics

            t0 = time.perf_counter()
            with _trace_span(trace, "engine.kv_restore", "engine") as rs:
                restored = await self._restore_from_host(
                    list(pre.token_ids), salt
                )
                rs.set(restored_tokens=restored)
            prepared += restored
            if restored:
                kv_tier_metrics.restore_latency_ms.observe(
                    (time.perf_counter() - t0) * 1e3
                )
                kv_tier_metrics.restore_hits_total += 1
            else:
                kv_tier_metrics.restore_misses_total += 1
        if self._prefix_puller is not None and pre.annotations.get("kv_pull"):
            # Cross-worker prefix pull (llm/kv_router/pull.py): the router
            # stamped a peer that holds a strictly longer prefix than any
            # local tier; pull the sealed delta blocks over the transfer
            # plane instead of recomputing prefill.  Bounded by the
            # configured byte/latency budgets; ANY failure degrades to
            # local prefill (the disagg degraded-mode shape).
            with _trace_span(trace, "engine.kv_pull", "engine") as ps:
                pulled = await self._prefix_puller.pull(
                    list(pre.token_ids), salt, pre.annotations["kv_pull"],
                    trace=trace,
                )
                ps.set(pulled_tokens=pulled)
            prepared += pulled
        if (
            self._sp_fn is not None
            and len(pre.token_ids) >= self.cfg.sp_prefill_min
            and jax.process_count() == 1
            and salt is None
        ):
            # Long prompt: one sequence-parallel whole-prompt pass seals the
            # complete blocks ahead of admission (ring attention over "sp").
            # DELIBERATELY single-process: sp prefill is scoped to dedicated
            # disagg PREFILL WORKERS (cli run --disagg prefill --sp N), each
            # a single-host engine owning its own sp mesh — decode fleets
            # scale across hosts via dp/tp while prefill workers ring over
            # their local slice and ship blocks through the KV transfer
            # plane (the reference's disagg split, docs/architecture.md).
            prepared += await self._sp_prefill(list(pre.token_ids))
        seq = SequenceState.from_request(request.id, pre, self.cfg)
        if trace is not None:
            from ..runtime.tracing import SeqTrace

            # Anchors queue-wait (scheduler._record_admission) and prefill
            # (first-token accept, pipeline._trace_first_token) spans.
            seq.trace = SeqTrace(trace)
        if automaton is not None:
            seq.grammar = automaton
            # Resumed sequences (llm/migration splice, seeded crash
            # recovery) fold already-delivered OUTPUT into the prompt: the
            # automaton state is the start state advanced through those
            # tokens (every delivered token was mask-admissible, so the
            # walk only fails on a corrupt resume — a request error).
            state: Optional[int] = automaton.start
            for t in seq.prompt[seq.orig_prompt_len:]:
                state = automaton.advance(state, int(t))
                if state is None:
                    raise ValueError(
                        "resume stream violates its grammar constraint"
                    )
            seq.grammar_state = state
        if adapter is not None:
            from ..llm.metrics import tenancy_metrics
            from ..llm.protocols import ModelNotFoundError

            seq.adapter = adapter
            try:
                # Resolve to a resident device slot (async H2D promotion,
                # LRU eviction of idle residents).  The ref pins the slot
                # until _finish — a running row's slot is never rewritten.
                seq.adapter_slot = await self._lora_registry.acquire(adapter)
            except KeyError:
                tenancy_metrics.adapter_not_found_total += 1
                raise ModelNotFoundError(adapter) from None
            tenancy_metrics.adapter_requests_total += 1
        if prepared:
            # PIN the just-sealed prefix until admission: the sealed blocks
            # sit in the reuse pool, where a concurrent request's
            # allocations could LRU-evict them before allocate_sequence
            # matches — silently wasting the whole sp/restore pass.  The
            # scheduler releases the pin when admission lands (or the
            # request is rejected/cancelled).
            seq.pin_ids = self._pin_prefix(list(pre.token_ids), salt)
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request.id] = queue
        self._contexts[request.id] = request.ctx
        self.scheduler.add(seq)
        self._wake.set()
        # Server-side seed resolution (llm/qos satellite): UNSEEDED sampled
        # requests get their engine-assigned seed stamped onto the first
        # stream item, so the routed client's _StreamGuard can build a
        # byte-identical resume request after a mid-stream crash —
        # previously only explicit-seed streams were crash-resumable.
        # Greedy (temperature 0) streams are seed-independent and stay
        # unstamped: their output must not vary with the request id
        # (recorder replay and A/B comparisons rely on that), and resume
        # determinism never needed a seed for them.  Resumed requests
        # always carry an explicit seed, so they are never re-stamped.
        samp_opts = pre.sampling_options
        stamp_seed = (
            samp_opts.seed is None and (samp_opts.temperature or 0.0) > 0.0
        )

        async def gen() -> AsyncIterator[Dict[str, Any]]:
            needs_stamp = stamp_seed
            try:
                while True:
                    item = await queue.get()
                    if item is _FINISHED:
                        return
                    if needs_stamp and isinstance(item, dict):
                        item["resolved_seed"] = int(seq.sampling_seed)
                        needs_stamp = False
                    yield item
            finally:
                self._queues.pop(request.id, None)
                self._contexts.pop(request.id, None)

        return ResponseStream(gen(), request.ctx)

    def set_event_callback(
        self, callback: Optional[Callable[[KvCacheEvent], None]]
    ) -> None:
        """Attach/replace the KV event sink (e.g. a KvEventPublisher) after
        construction — the CLI builds the engine before the runtime exists."""
        self.kv._event_callback = callback

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            request_active_slots=self.scheduler.num_running,
            request_total_slots=self.cfg.max_batch,
            kv_active_blocks=self.kv.active_blocks,
            kv_total_blocks=self.kv.num_blocks,
            num_requests_waiting=self.scheduler.num_waiting,
            gpu_cache_usage_perc=self.kv.usage,
            gpu_prefix_cache_hit_rate=self.kv.hit_rate,
        )

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        if self._offload_task is not None:
            self._offload_task.cancel()
            try:
                await self._offload_task
            except asyncio.CancelledError:
                pass
            self._offload_task = None
        if self._publisher is not None:
            await self._publisher.close()
            self._publisher = None
        if self.disk_kv is not None and getattr(self, "_disk_dir_owned", False):
            # Engine-owned (defaulted) disk-tier dir: remove it so worker
            # restarts don't leak a dead budget's worth of block files.
            import shutil

            shutil.rmtree(self.disk_kv.directory, ignore_errors=True)
            self.disk_kv = None
        # The object-store tier is deliberately NOT removed: it is the
        # durable rung — a respawned worker pointed at the same dir boots
        # warm from it (scale-from-zero; docs/kv_tiering.md).
        self.object_kv = None
        # Fail whatever is still in flight so no generate() stream hangs.
        self._fail_all()

    # --------------------------------------------------- KV export / import
    #
    # TPU counterpart of the reference's block_copy.cu + NIXL transfer
    # (lib/llm/src/kernels/block_copy.cu, kv/layer.rs:100-772): whole pages
    # move between workers as host-staged arrays (msgpack binary over the
    # service plane; ICI device-to-device when workers share a pod slice).
    # Imported pages are sealed under their chained hashes, so the decode
    # scheduler sees remote-prefilled prompts as ordinary prefix-cache hits.





    def estimate_prefix_hit(
        self, token_ids: List[int], salt: Optional[str] = None
    ) -> int:
        """Tokens of ``token_ids`` already resident locally (router input).
        ``salt`` must match the requesting tenant's (llm/tenancy) or the
        estimate is structurally zero."""
        from ..tokens import hash_token_blocks

        blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)
        return len(self.kv.match_prefix(blocks)) * self.cfg.block_size

    # ------------------------------------------------------------ tiered KV
    def _tier_of(self, seq_hash: int) -> Optional[str]:
        """Cheapest LOWER tier still holding ``seq_hash`` (HBM excluded —
        the caller is usually deciding what HBM eviction means)."""
        if self.host_kv is not None and self.host_kv.contains(seq_hash):
            return "host"
        if self.disk_kv is not None and self.disk_kv.contains(seq_hash):
            return "disk"
        if self.object_kv is not None and self.object_kv.contains(seq_hash):
            return "objstore"
        return None

    def _demote_to_disk(self, seq_hash: int, block) -> bool:
        """HostKvStore.on_evict hook: push an evicted host-tier block down
        to disk.  Runs inside the host store's eviction loop (often off the
        event loop) — record-only, events flush later.  The host tier's
        offload-time checksum is CARRIED into the disk envelope (and
        verified by the put), so a bit that rotted in host RAM is refused
        here instead of laundered into a valid-looking file."""
        if self.disk_kv is None:
            return False
        return self.disk_kv.put(
            seq_hash, block, checksum=self.host_kv.checksum(seq_hash)
        )

    def _demote_to_objstore(self, seq_hash: int, path: str) -> bool:
        """DiskKvStore.on_evict hook: re-wrap an evicted disk envelope as
        a durable object.  Runs inside the disk store's eviction loop
        (under its lock, often off the event loop) — record-only, events
        flush later.  The envelope is parsed and its carried CRC
        re-verified at ingest, so disk rot is refused here instead of
        persisted for the whole fleet to trust."""
        if self.object_kv is None:
            return False
        return self.object_kv.ingest_kvblk(seq_hash, path)

    def set_integrity_reporter(self, reporter) -> None:
        """Attach ``reporter(plane: str)`` called on every LOCAL-tier
        corruption detection (disk/host).  The serving layer wires it to
        feed the health watchdog's corruption ledger with this worker's
        own id — a worker whose own media keeps flipping bits is as
        quarantine-worthy as a donor shipping poison.  None detaches."""
        self._integrity_reporter = reporter

    def _record_corruption(
        self,
        plane: str,
        seq_hash: Optional[int],
        chain: Optional[List[int]] = None,
        donor: Optional[int] = None,
    ) -> None:
        """Corruption quarantine, one entry point for every plane:
        count it, negative-cache the hash (TTL — restore/pull loops must
        not thrash on it), drop the block and every CHAINED DESCENDANT
        still held by the local tiers (their contents may be fine, but
        their chain passes through poison — the radix index must stop
        advertising the whole run), attribute a wire donor to the health
        ledger, and report local-tier rot to the serving layer.

        The caller flushes tier events afterwards (this may run in a
        thread; event emission must happen on the loop)."""
        from ..llm.metrics import kv_integrity_metrics

        kv_integrity_metrics.corrupt_total[plane] += 1
        logger.warning(
            "KV corruption detected on plane %r (block %s): dropped before "
            "scatter; falling back to recompute",
            plane, f"{seq_hash:#x}" if seq_hash is not None else "?",
        )
        if seq_hash is not None:
            self.integrity.ban(seq_hash)
            dropped = 0
            descendants: List[int] = []
            if chain:
                try:
                    descendants = chain[chain.index(seq_hash) + 1:]
                except ValueError:
                    descendants = []
            for d in [seq_hash, *descendants]:
                hit = False
                if self.host_kv is not None and self.host_kv.drop(d):
                    hit = True
                if self.disk_kv is not None and self.disk_kv.drop(d):
                    hit = True
                if self.object_kv is not None and self.object_kv.drop(d):
                    hit = True
                if hit and d != seq_hash:
                    dropped += 1
            kv_integrity_metrics.descendants_dropped_total += dropped
        if donor is not None:
            from ..runtime.health import kv_corruption

            kv_corruption.record(donor)
        elif plane != "wire" and self._integrity_reporter is not None:
            try:
                self._integrity_reporter(plane)
            except Exception:  # noqa: BLE001 — reporting must never break serving
                logger.warning("integrity reporter failed", exc_info=True)

    def _flush_tier_events(self) -> None:
        """Publish tier transitions recorded by the host/disk stores since
        the last flush.  Must run on the event loop (the KvEventPublisher
        binds futures to it); every threaded tier mutation's caller flushes
        after the thread returns.  A hash still sealed in HBM publishes
        nothing — the router's view stays 'hbm' until HBM eviction."""
        if self.host_kv is None:
            return
        # Each store's "demote" means "the NEXT tier down took it" — the
        # tier tag depends on which store recorded the transition, so the
        # drains stay separate.
        tagged: List[Tuple[str, str, int]] = [
            ("disk", kind, h) for kind, h in self.host_kv.drain_transitions()
        ]
        if self.disk_kv is not None:
            tagged += [
                ("objstore", kind, h)
                for kind, h in self.disk_kv.drain_transitions()
            ]
        if self.object_kv is not None:
            tagged += [
                ("", kind, h)
                for kind, h in self.object_kv.drain_transitions()
            ]
        demoted: Dict[str, List[int]] = {}
        removed: List[int] = []
        for next_tier, kind, h in tagged:
            if h in self.kv._by_hash:
                continue  # HBM still holds it: best tier unchanged
            if kind == "demote":
                demoted.setdefault(next_tier, []).append(h)
            elif self._tier_of(h) is not None:
                continue  # another tier still holds it
            else:
                removed.append(h)
        for tier, hashes in demoted.items():
            self.kv.emit_tiered(tier, hashes)
        self.kv.emit_removed(removed)

    def local_prefix_blocks(
        self, token_ids: List[int], salt: Optional[str] = None,
        blocks: Optional[List[Any]] = None,
    ) -> int:
        """Leading complete blocks restorable from ANY local tier (HBM,
        host, disk) — what a cross-worker pull must strictly beat before
        moving bytes (llm/kv_router/pull.py).  ``blocks`` lets a caller
        that already hashed the chain skip the second O(prompt) walk."""
        from ..tokens import hash_token_blocks

        if blocks is None:
            blocks = hash_token_blocks(token_ids, self.cfg.block_size, salt)
        n = 0
        for tb in blocks:
            h = tb.sequence_hash
            if h in self.kv._by_hash or self._tier_of(h) is not None:
                n += 1
            else:
                break
        return n

    def set_prefix_puller(self, puller) -> None:
        """Attach the cross-worker prefix puller (llm/kv_router/pull.py);
        None detaches.  The serving layer owns peer discovery — the engine
        only calls ``puller.pull(tokens, salt, hint)`` at admission."""
        self._prefix_puller = puller

    def block_nbytes(self) -> int:
        """Host-side bytes of one KV block in the stored representation."""
        return int(self.cache.pages.nbytes // max(1, self.cfg.num_blocks))

    def kv_tier_summary(self) -> Dict[str, Any]:
        """Per-tier bytes/blocks gauges for /metrics (llm/metrics.py
        kv_tier_metrics source) and the edge SLO publication."""
        bb = self.block_nbytes()
        out: Dict[str, Any] = {
            "hbm": {
                "blocks": len(self.kv._by_hash),
                "bytes": len(self.kv._by_hash) * bb,
            },
            "prefix_hit_rate": self.kv.hit_rate,
        }
        if self.host_kv is not None:
            out["host"] = {
                "blocks": len(self.host_kv),
                "bytes": self.host_kv.used_bytes,
            }
        if self.disk_kv is not None:
            out["disk"] = {
                "blocks": len(self.disk_kv),
                "bytes": self.disk_kv.used_bytes,
            }
        if self.object_kv is not None:
            out["objstore"] = {
                "blocks": len(self.object_kv),
                "bytes": self.object_kv.used_bytes,
            }
        return out

    # -------------------------------------------------------------- the loop
    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(self._run_loop())
        if self.host_kv is not None and (
            self._offload_task is None or self._offload_task.done()
        ):
            self._offload_task = asyncio.get_running_loop().create_task(
                self._offload_pump()
            )

    async def _run_loop(self) -> None:
        last_beat = time.perf_counter()
        while not self._closed:
            # Heartbeat: one iteration = one scheduling decision.  A
            # multi-second gap here localizes tail-latency stalls to the
            # ENGINE side (device dispatch, harvest, GC) vs the network /
            # client — the r4 ladder artifacts carried ~8s TTFT outliers
            # with no compile and no attribution (VERDICT r4 weak #1).
            now = time.perf_counter()
            gap = now - last_beat
            last_beat = now
            if gap > self.loop_gap_max:
                self.loop_gap_max = gap
            if gap > 5.0:
                # One iteration can legitimately span a whole fused
                # pure-decode session (seconds at saturation); beyond that
                # it smells like a genuine stall (device hiccup, GC, host
                # pause) — surface it.
                logger.warning(
                    "engine loop iteration spanned %.2fs "
                    "(long fused-decode session or stall)", gap
                )
            self._cancel_stopped()
            try:
                while (
                    self._pending_fetches
                    and self._pending_fetches[0][1].done()
                ):
                    # Completed background fetches apply for free — parked
                    # rows resume without the loop ever blocking on D2H.
                    await self._harvest_pending()
            except asyncio.CancelledError:
                raise
            except Exception:
                # Same engine-fatal contract as the step path below: a
                # failed D2H must fail all streams, never strand them.
                logger.exception("deferred fetch failed")
                self._fail_all()
                return
            plan = self.scheduler.schedule()
            self._note_prefill_requeues()
            for seq in self.scheduler.take_rejected():
                self._finish(seq, FinishReason.ERROR)
            if plan is None:
                if self._pending_fetches:
                    try:
                        await self._harvest_pending(all_pending=True)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        logger.exception("deferred fetch failed")
                        self._fail_all()
                        return
                    continue
                if self.scheduler.num_waiting and not self.scheduler.num_running:
                    # e.g. decode just preempted everyone back to waiting:
                    # retry admission immediately (terminates: each pass
                    # admits or rejects at least one waiting sequence).
                    await asyncio.sleep(0)
                    continue
                # Idle: running is empty (running sequences always yield
                # work), so sleep until a new request arrives.  Idle time
                # is NOT a stall: re-arm the heartbeat or the first
                # request after a lull reads the whole idle period as an
                # engine-side gap.
                self._wake.clear()
                await self._wake.wait()
                last_beat = time.perf_counter()
                continue
            try:
                did_work = False
                # Speculation first: drafted rows verify multiple tokens
                # per round trip on the unified ragged program (spec.py);
                # an empty draft set (proposer misses, adaptive-k benched,
                # or expected gain below the fused pipeline's) falls
                # through to the fused paths unchanged.
                drafts = (
                    self._spec_propose(plan)
                    if self._spec_ctl is not None
                    else {}
                )
                if drafts:
                    await self._run_spec_unified(plan, drafts)
                    did_work = True
                if (
                    not did_work
                    and plan.pure_decode
                    and self.cfg.decode_steps > 1
                ):
                    if self._pending_fetches:
                        # Parked rows must not sit out a whole fused
                        # pipeline run — fold them in first.
                        await self._harvest_pending(all_pending=True)
                        continue
                    # Leaving the mixed regime: a stale chunk count must not
                    # trigger an immediate burst in the NEXT mixed phase.
                    self._chunks_since_burst = 0
                    did_work = await self._decode_pipeline(
                        [seq for seq, _, _ in plan.items]
                    )
                if not did_work and self.cfg.decode_steps > 1:
                    # Mixed phase (prefill + decode in one plan): running
                    # decode rows inside the unified step gives them ONE
                    # token per dispatch+fetch round trip — with prefill
                    # almost always active under continuous arrivals, that
                    # made conc 16 SLOWER than conc 8 (r4 ladder).  Instead:
                    # fetch-free prefill-only steps at device rate, and
                    # every cfg.prefill_chunks_per_burst of them one fused
                    # burst advancing every decode row decode_steps tokens
                    # for a single round trip.  (Bursting after EVERY chunk
                    # was tried first and throttled prefill ~3x: 8 requests'
                    # first wave alone is ~47 chunks.)
                    decode_items = [
                        it for it in plan.items if it[1] >= len(it[0].prompt)
                    ]
                    prefill_items = [
                        it for it in plan.items if it[1] < len(it[0].prompt)
                    ]
                    # Grammar-constrained decode rows (llm/tenancy) never
                    # burst — their logit mask advances host-side per
                    # token — so they ride the unified prefill steps
                    # instead (one token per step, mask rebuilt each time)
                    # while unconstrained rows keep the fused-burst cadence.
                    burstable = [
                        it for it in decode_items if it[0].grammar is None
                    ]
                    step_extra = [
                        it
                        for it in decode_items
                        if it[0].grammar is not None
                    ]
                    # Without prefill in the plan this branch would starve
                    # the burstable rows (only the periodic burst advances
                    # them): fall through to the plain unified step instead,
                    # which gives EVERY row one token per round trip.
                    if burstable and prefill_items:
                        await self._run_unified(
                            StepPlan(prefill_items + step_extra)
                        )
                        self._chunks_since_burst += 1
                        if (
                            self._chunks_since_burst
                            >= self.cfg.prefill_chunks_per_burst
                        ):
                            self._chunks_since_burst = 0
                            # Replan against freezes/finishes that landed
                            # DURING the awaited prefill step: a frozen
                            # (mid-migration) row advanced here would emit
                            # tokens its cutover snapshot lacks.
                            burst_items = [
                                it
                                for it in burstable
                                if not it[0].finished and not it[0].frozen
                            ]
                            if burst_items and not await self._decode_burst(
                                [s for s, _, _ in burst_items]
                            ):
                                # No KV headroom for a whole burst: the
                                # 1-token slots are already allocated.
                                self.step_trace.append(
                                    ("burst_fallback", 0.0, len(burst_items), 0)
                                )
                                await self._run_unified(StepPlan(burst_items))
                        did_work = True
                if not did_work:
                    # Not enough KV headroom for a fused window (or not a
                    # pure-decode state): single unified step still advances
                    # every sequence one token, and finishes free blocks.
                    await self._run_unified(plan)
            except asyncio.CancelledError:
                raise
            except Exception:  # engine-fatal: fail all inflight requests
                logger.exception("engine step failed")
                self._fail_all()
                return
            self._steps += 1
            await asyncio.sleep(0)  # let ingress/egress run between steps

    def _cancel_stopped(self) -> None:
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            ctx = self._contexts.get(seq.request_id)
            if ctx is not None and ctx.is_stopped and not seq.finished:
                seq.finished = True
                self.scheduler.remove(seq)
                self._finish(seq, FinishReason.CANCELLED)

    def _fail_all(self) -> None:
        self._pending_fetches.clear()  # drop in-flight token fetches
        self._pipeline_members = set()
        for seq in list(self.scheduler.running) + list(self.scheduler.waiting):
            seq.awaiting_fetch = False
            self.scheduler.remove(seq)
            self._finish(seq, FinishReason.ERROR)

    # ------------------------------------------------------------ batch build
    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub




    # ------------------------------------------------------ unified step path



    # -------------------------------------------------- fused decode pipeline



    # ------------------------------------------------------------ per-token

    # ------------------------------------------------------- host KV offload










    def _note_prefill_requeues(self) -> None:
        """Reset the mixed-phase chunk cadence when a mid-prefill sequence
        was requeued since the last scheduling pass (preemption folds the
        partial prompt back into waiting; migration retires it).  The
        requeued sequence restarts its chunk sequence from zero, so a
        chunk count carried over from BEFORE the requeue would trigger the
        first decode burst of the next mixed phase too early and skew its
        cadence (ISSUE 19 satellite)."""
        reqs = getattr(self.scheduler, "prefill_requeues", 0)
        if reqs != self._prefill_requeues_seen:
            self._prefill_requeues_seen = reqs
            self._chunks_since_burst = 0

    def _note_prefill_chunk(self, wall_s: float, tokens: int) -> None:
        """Account one prefill chunk (called by pipeline._run_unified for
        every unified step that advanced prompt tokens): cumulative
        counters feed the bench MFU math, the bounded trace feeds the
        dynamo_tpu_prefill_chunk_seconds quantiles."""
        self.prefill_chunks += 1
        self.prefill_wall_s += wall_s
        self.prefill_tokens += tokens
        self._prefill_chunk_trace.append(wall_s)

    def prefill_summary(self) -> Dict[str, Any]:
        """Prefill-chunk latency breakdown: cumulative counters (unbounded,
        safe for rate math) plus p50/p99 over the bounded per-chunk trace
        window (gauges, like step_summary)."""
        times = sorted(self._prefill_chunk_trace)
        m = len(times)
        return {
            "chunks": self.prefill_chunks,
            "wall_s": round(self.prefill_wall_s, 4),
            "prompt_tokens": self.prefill_tokens,
            "p50_ms": round(times[m // 2] * 1e3, 2) if m else 0.0,
            "p99_ms": (
                round(times[min(m - 1, int(m * 0.99))] * 1e3, 2) if m else 0.0
            ),
        }

    def step_summary(self) -> Dict[str, Any]:
        """Aggregate the dispatch trace: counts, wall time, and latency
        percentiles per step kind (the VERDICT r1 profiling ask)."""
        out: Dict[str, Any] = {}
        for kind in sorted({k for k, *_ in self.step_trace}):
            times = sorted(t for k, t, _, _ in self.step_trace if k == kind)
            toks = sum(n for k, _, _, n in self.step_trace if k == kind)
            m = len(times)
            out[kind] = {
                "dispatches": m,
                "wall_s": round(sum(times), 4),
                "device_tokens": toks,
                "p50_ms": round(times[m // 2] * 1e3, 2),
                "p99_ms": round(times[min(m - 1, int(m * 0.99))] * 1e3, 2),
            }
        return out

    def reset_dispatch_stats(self) -> None:
        """Zero the dispatch trace AND the session counters together (the
        bench's timed window): mixing warm-pass counters with timed-window
        wall time would make rebuilds-per-session vs wall_s internally
        inconsistent in BENCH_r*.json."""
        self.step_trace.clear()
        self.pipeline_sessions = 0
        self.pipeline_rebuilds = 0
        self.continuous_admissions = 0
        self.continuous_retired = 0
        self.pipeline_wall_s = 0.0
        self.decode_busy_s = 0.0
        self.decode_stalls = 0
        self.last_stall = None
        self.prefill_chunks = 0
        self.prefill_wall_s = 0.0
        self.prefill_tokens = 0
        self._prefill_chunk_trace.clear()

    def dispatch_summary(self) -> Dict[str, Any]:
        """Machine-readable decode-pipeline health: the per-kind dispatch
        trace (step_summary — over the BOUNDED trace window, so its counts
        and percentiles are gauges, not counters) plus session/rebuild/
        churn counters and the fused-loop host-gap fraction — what the
        planner and bench read off ``/metrics`` (llm/metrics.py
        engine_dispatch_metrics) instead of parsing bench stderr.

        ``host_gap_frac`` is scoped to fused decode sessions: the fraction
        of pipeline wall NOT covered by in-session device work (decode
        dispatch/wait + the interleaved admission-prefill steps) — the
        host-side planning/accept share the continuous pipeline exists to
        shrink.  Both terms accumulate unbounded (never derived from the
        bounded trace).  0.0 when no session has run."""
        wall = self.pipeline_wall_s
        gap = (
            max(0.0, wall - self.decode_busy_s) / wall if wall > 0 else 0.0
        )
        return {
            "kinds": self.step_summary(),
            "decode_kernel": self.decode_kernel,
            "prefill_kernel": self.prefill_kernel,
            "prefill": self.prefill_summary(),
            "pipeline": {
                "sessions": self.pipeline_sessions,
                "rebuilds": self.pipeline_rebuilds,
                "continuous_admissions": self.continuous_admissions,
                "continuous_retired": self.continuous_retired,
                "wall_s": round(wall, 4),
                "host_gap_frac": round(gap, 4),
                # Stall-watchdog surface (DYN_DECODE_STALL_S): the health
                # watchdog's straggler path reads this off the same
                # summary the planner already consumes.
                "stalls": self.decode_stalls,
                "last_stall": self.last_stall,
            },
        }


