"""Leader→follower dispatch plane for multi-host SPMD serving.

In multi-controller JAX every process must enqueue the SAME device programs
in the SAME order over the global mesh.  The leader (process 0) runs the
full serving stack — HTTP frontend, router, scheduler, KV manager; the
followers (one per additional host) run ``follower_serve``, which replays
the leader's dispatch stream: each message carries only small host metadata
(ragged batch arrays, sampling params, rng keys) — params and KV pages
already live sharded across every host's devices.

Reference counterpart: the vLLM Ray leader/follower processes and sglang's
``nnodes/node_rank/dist_init_addr`` bootstrap
(/root/reference/lib/engines/vllm0_7/src/ray.rs,
/root/reference/lib/engines/sglang/src/sglang_inc.py).  Like those, this is
a trusted intra-deployment plane (same trust domain as the NCCL/gloo
sockets themselves), so frames are pickled numpy payloads with length
framing.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import struct
from typing import Any, Optional, Tuple

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")


def _hello_frame() -> bytes:
    """Fixed-size authentication hello: magic + SHA-256 of DYN_STEP_TOKEN.

    Frames after the hello are pickled, so an attacker reaching the port
    would get code execution on the leader — the port must be firewalled to
    the deployment's trust domain, and setting DYN_STEP_TOKEN on every node
    additionally rejects unauthenticated connections at accept time
    (ADVICE r3).  The hello itself is a raw-bytes compare: nothing from an
    unauthenticated peer is ever unpickled."""
    import hashlib

    token = os.environ.get("DYN_STEP_TOKEN", "")
    return b"DYNSTEP1" + hashlib.sha256(token.encode()).digest()


async def _send(writer: asyncio.StreamWriter, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    writer.write(_LEN.pack(len(blob)) + blob)
    await writer.drain()


async def _recv(reader: asyncio.StreamReader) -> Any:
    head = await reader.readexactly(_LEN.size)
    blob = await reader.readexactly(_LEN.unpack(head)[0])
    return pickle.loads(blob)


class StepPublisher:
    """Leader side: accepts one connection per follower, then broadcasts
    every dispatch in order.  ``publish`` completes only after the frame is
    flushed to every follower, so stream order == dispatch order."""

    def __init__(self, host: str, port: int, num_followers: int):
        self.host, self.port = host, port
        self.num_followers = num_followers
        self._writers: list = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._connected = asyncio.Event()

    async def start(self, timeout: float = 120.0) -> "StepPublisher":
        if not os.environ.get("DYN_STEP_TOKEN"):
            # Post-hello frames are unpickled (code execution); with no
            # token the hello is the well-known sha256("") ANY peer can
            # send.  Refuse the wildcard bind outright; on a specific
            # interface warn loudly (r4 advisory).
            if self.host in ("0.0.0.0", "::"):
                raise RuntimeError(
                    "step plane: refusing to bind a wildcard address with "
                    "no DYN_STEP_TOKEN set — any peer reaching the port "
                    "would get pickle-level code execution on the leader. "
                    "Set DYN_STEP_TOKEN on every node (or bind a private "
                    "interface)."
                )
            logger.warning(
                "step plane: DYN_STEP_TOKEN is unset — any peer that can "
                "reach %s:%d is trusted with pickled frames; set the token "
                "on every node",
                self.host, self.port,
            )
        expect = _hello_frame()

        async def on_conn(reader, writer):
            # The hello is a FIXED-SIZE raw-bytes compare, checked before
            # anything from this peer is unpickled; a wrong/missing token is
            # dropped before it ever counts toward the follower quorum.
            import hmac

            try:
                hello = await asyncio.wait_for(
                    reader.readexactly(len(expect)), 30.0
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                writer.close()
                return
            if not hmac.compare_digest(hello, expect):
                logger.warning("step plane: rejecting unauthenticated peer")
                writer.close()
                return
            self._writers.append((reader, writer))
            logger.info(
                "step follower %d/%d connected",
                len(self._writers),
                self.num_followers,
            )
            if len(self._writers) >= self.num_followers:
                self._connected.set()

        self._server = await asyncio.start_server(
            on_conn, host=self.host, port=self.port
        )
        if self.num_followers == 0:
            self._connected.set()
        await asyncio.wait_for(self._connected.wait(), timeout)
        return self

    async def publish(self, kind: str, payload: Tuple = ()) -> None:
        # One serialization, concurrent drains: this sits in the dispatch
        # hot path, once per device step.
        blob = pickle.dumps((kind, payload), protocol=pickle.HIGHEST_PROTOCOL)
        frame = _LEN.pack(len(blob)) + blob
        for _, writer in self._writers:
            writer.write(frame)
        await asyncio.gather(*(w.drain() for _, w in self._writers))

    async def close(self) -> None:
        try:
            await self.publish("close")
        except asyncio.CancelledError:
            raise
        except Exception:
            pass
        await self.abort()

    async def abort(self) -> None:
        """Tear down WITHOUT the 'close' broadcast: connections just drop.
        Used when the leader rebinds (cli step-plane fallback) — a follower
        that received no step yet treats the drop as transient and
        reconnects (follower_serve), whereas a 'close' frame would make it
        exit for good and the rebound publisher could never reach quorum."""
        for _, writer in self._writers:
            writer.close()
        self._writers.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


async def follower_serve(
    engine, leader: str, *, retry_s: float = 0.5, timeout: float = 120.0
) -> None:
    """Run this process as a dispatch follower of ``leader`` ("host:port").

    ``engine`` is a TpuEngine built with the SAME EngineConfig (and params
    source) as the leader's — identical seeds/checkpoints give identical
    global arrays, so replaying the dispatch stream keeps every process's
    device queue in SPMD lockstep.  Returns when the leader closes.
    """
    host, port = leader.rsplit(":", 1)
    deadline = asyncio.get_event_loop().time() + timeout
    while True:  # outer: reconnect while no step has been replayed yet
        while True:
            try:
                reader, writer = await asyncio.open_connection(host, int(port))
                break
            except OSError:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(retry_s)
        writer.write(_hello_frame())
        await writer.drain()
        logger.info("connected to step leader %s", leader)
        replayed = 0
        try:
            while True:
                kind, payload = await _recv(reader)
                if kind == "close":
                    return
                await engine.mirror_step(kind, payload)
                replayed += 1
        except (asyncio.IncompleteReadError, ConnectionError):
            if replayed:
                # Mid-stream loss after state was applied: resuming on a
                # new connection would diverge from SPMD lockstep — fatal.
                raise
            if asyncio.get_event_loop().time() > deadline:
                raise
            # Dropped before any dispatch (e.g. the leader rebound its
            # step plane to another interface): safe to reconnect.
            logger.info("step leader dropped pre-stream; reconnecting")
            await asyncio.sleep(retry_s)
        finally:
            writer.close()
