"""Paged KV block allocator with hash-based prefix reuse + event emission.

Reference semantics (not code): lib/llm/src/kv/{reuse,reserved,manager}.rs —
freed blocks *retain their contents* and sit in a reuse pool keyed by chained
sequence hash; a new request first matches its prompt's block hashes against
live ("inflight") blocks, then the reuse pool, and only then takes fresh
blocks (evicting the coldest reusable ones).  Every store/evict emits a
``KvCacheEvent`` so the router's index mirrors this pool exactly.

Host-side bookkeeping only — the device never sees hashes, just block ids.
Physical block order is irrelevant to the device (attention gathers via block
tables), so allocation never copies anything in HBM.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..llm.kv_router.protocols import (
    KvCacheEvent,
    KvCacheStoredBlockData,
)
from ..tokens import TokenBlock


@dataclass
class _Block:
    id: int
    ref_count: int = 0
    sequence_hash: Optional[int] = None  # contents identity (None = scratch)
    parent_hash: Optional[int] = None
    tokens_hash: Optional[int] = None


EventCallback = Callable[[KvCacheEvent], None]


class KvBlockManager:
    """Fixed pool of ``num_blocks`` physical blocks of ``block_size`` tokens.

    States a block moves through:
      free+anonymous → active (ref>0) → [sealed w/ hash] → free+reusable
      (contents intact, matchable) → evicted (hash dropped, Removed emitted)
      → active again.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_callback: Optional[EventCallback] = None,
        enable_prefix_caching: bool = True,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._blocks = [_Block(i) for i in range(num_blocks)]
        # Free anonymous blocks (no reusable contents), FIFO.
        self._free_anon: List[int] = list(range(num_blocks))
        # Free blocks with reusable contents, LRU-ordered (oldest first).
        self._free_reusable: "OrderedDict[int, None]" = OrderedDict()
        # seq_hash → block id, for any block (active or free) holding it.
        self._by_hash: Dict[int, int] = {}
        self._event_callback = event_callback
        self._event_id = 0
        self._enable_prefix_caching = enable_prefix_caching
        # Tiered KV cache (engine/{host_cache,disk_cache}.py): maps a
        # sequence hash to the lower tier still holding its contents
        # ("host"/"disk") or None.  When set, HBM eviction of a block a
        # lower tier retains emits a TIER-TAGGED event instead of Removed —
        # the router keeps scoring the worker for that prefix, discounted
        # by restore cost, instead of forgetting it.
        self.tier_lookup: Optional[Callable[[int], Optional[str]]] = None
        # cumulative counters for metrics
        self.lookup_blocks = 0
        self.matched_blocks = 0

    # ------------------------------------------------------------------ stats
    @property
    def free_blocks(self) -> int:
        return len(self._free_anon) + len(self._free_reusable)

    @property
    def active_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.active_blocks / self.num_blocks if self.num_blocks else 0.0

    @property
    def hit_rate(self) -> float:
        return self.matched_blocks / self.lookup_blocks if self.lookup_blocks else 0.0

    # ----------------------------------------------------------------- events
    def _emit(self, event: KvCacheEvent) -> None:
        if self._event_callback is not None:
            self._event_callback(event)

    def _next_event_id(self) -> int:
        self._event_id += 1
        return self._event_id

    def emit_tiered(self, tier: str, block_hashes: Sequence[int]) -> None:
        """Publish a tier change for blocks this manager does not hold in
        HBM (host→disk demotion, disk→host promotion) — the engine's tier
        stores have no event plane of their own."""
        if block_hashes and self._enable_prefix_caching:
            self._emit(
                KvCacheEvent.tiered(
                    self._next_event_id(), tier, list(block_hashes)
                )
            )

    def emit_removed(self, block_hashes: Sequence[int]) -> None:
        """Publish the loss of blocks evicted from the LAST tier holding
        them (see emit_tiered)."""
        if block_hashes and self._enable_prefix_caching:
            self._emit(
                KvCacheEvent.removed(self._next_event_id(), list(block_hashes))
            )

    # ------------------------------------------------------------- allocation
    def match_prefix(self, token_blocks: Sequence[TokenBlock]) -> List[int]:
        """Longest run of leading blocks already resident; returns block ids
        (does NOT take references — pair with allocate_sequence)."""
        matched: List[int] = []
        if not self._enable_prefix_caching:
            return matched
        for tb in token_blocks:
            bid = self._by_hash.get(tb.sequence_hash)
            if bid is None:
                break
            matched.append(bid)
        return matched

    def would_fit(
        self,
        token_blocks: Sequence[TokenBlock],
        num_blocks_needed: int,
        matched: Optional[List[int]] = None,
    ) -> bool:
        """Dry-run of allocate_sequence's capacity check (no side effects,
        no counter updates).  The fused-decode admission gate polls this —
        keeping the math here means it can never drift from real admission.
        ``matched`` lets a caller that already ran match_prefix skip the
        second walk."""
        if matched is None:
            matched = self.match_prefix(token_blocks)
        fresh_needed = num_blocks_needed - len(matched)
        # Matched blocks sitting in the reuse pool get revived and stop
        # counting as free, so subtract them from available capacity.
        revived = sum(1 for b in matched if self._blocks[b].ref_count == 0)
        return fresh_needed <= self.free_blocks - revived

    def allocate_sequence(
        self,
        token_blocks: Sequence[TokenBlock],
        num_blocks_needed: int,
        count_hits: bool = True,
    ) -> Optional[Tuple[List[int], int]]:
        """Allocate ``num_blocks_needed`` blocks for a prompt whose complete
        blocks are ``token_blocks`` (hashed).  Leading blocks already resident
        are shared (ref++) instead of recomputed.

        ``count_hits=False`` skips the hit-rate counters — transfer-plane
        injections (inject_blocks) are bookkeeping, not request admissions,
        and counting them would skew gpu_prefix_cache_hit_rate the same way
        acquire_prefix's docstring warns about pinning.

        Returns (block_ids, num_cached_tokens) or None if out of capacity.
        """
        matched = self.match_prefix(token_blocks)
        if count_hits:
            self.lookup_blocks += len(token_blocks)
            self.matched_blocks += len(matched)
        if not self.would_fit(token_blocks, num_blocks_needed, matched):
            return None
        fresh_needed = num_blocks_needed - len(matched)
        ids: List[int] = []
        for bid in matched:
            blk = self._blocks[bid]
            if blk.ref_count == 0:
                self._free_reusable.pop(bid, None)  # revive from reuse pool
            blk.ref_count += 1
            ids.append(bid)
        for _ in range(fresh_needed):
            bid = self._take_free_block()
            if bid is None:  # rollback
                self.free_sequence(ids)
                return None
            self._blocks[bid].ref_count = 1
            ids.append(bid)
        return ids, len(matched) * self.block_size

    def acquire_prefix(self, token_blocks: Sequence[TokenBlock]) -> Optional[List[int]]:
        """Take references on the resident leading blocks WITHOUT touching
        the hit-rate counters (pre-admission pinning is bookkeeping, not a
        cache lookup — counting it would double-count every pinned prefix
        and inflate gpu_prefix_cache_hit_rate)."""
        matched = self.match_prefix(token_blocks)
        if not matched:
            return None
        ids: List[int] = []
        for bid in matched:
            blk = self._blocks[bid]
            if blk.ref_count == 0:
                self._free_reusable.pop(bid, None)
            blk.ref_count += 1
            ids.append(bid)
        return ids

    def allocate_block(self) -> Optional[int]:
        """One fresh anonymous block (decode growth)."""
        bid = self._take_free_block()
        if bid is not None:
            self._blocks[bid].ref_count = 1
        return bid

    def evict_hashes(self, seq_hashes: Sequence[int]) -> int:
        """Force-evict specific REUSABLE (ref==0, sealed-hash) blocks as if
        allocation pressure had recycled them: contents forgotten, the
        tier-aware Removed/tiered event emitted, the block returned to the
        anonymous pool.  Deterministic HBM-pressure simulation for chaos /
        bench harnesses (benchmarks/goodput.py L7 storm) — the real LRU
        path runs end to end, so event semantics cannot drift from organic
        eviction.  Active (referenced) blocks are never touched."""
        n = 0
        for h in list(seq_hashes):
            bid = self._by_hash.get(h)
            if bid is None:
                continue
            blk = self._blocks[bid]
            if blk.ref_count > 0 or bid not in self._free_reusable:
                continue
            # Rotate the victim to the LRU head and mask the anonymous
            # pool (the allocator prefers it); _take_free_block then
            # evicts exactly this block through the ordinary path.
            self._free_reusable.move_to_end(bid, last=False)
            anon, self._free_anon = self._free_anon, []
            try:
                got = self._take_free_block()
            finally:
                self._free_anon = anon
            if got is not None:
                self._free_anon.append(got)
                n += 1
        return n

    def _take_free_block(self) -> Optional[int]:
        if self._free_anon:
            return self._free_anon.pop()
        if self._free_reusable:
            bid, _ = self._free_reusable.popitem(last=False)  # LRU evict
            blk = self._blocks[bid]
            if blk.sequence_hash is not None:
                self._by_hash.pop(blk.sequence_hash, None)
                # Tiered cache: a lower tier still holding the contents
                # demotes the router's view instead of erasing it.
                tier = (
                    self.tier_lookup(blk.sequence_hash)
                    if self.tier_lookup is not None
                    else None
                )
                if tier is not None:
                    self._emit(
                        KvCacheEvent.tiered(
                            self._next_event_id(), tier, [blk.sequence_hash]
                        )
                    )
                else:
                    self._emit(
                        KvCacheEvent.removed(
                            self._next_event_id(), [blk.sequence_hash]
                        )
                    )
            blk.sequence_hash = blk.parent_hash = blk.tokens_hash = None
            return bid
        return None

    # ---------------------------------------------------------------- sealing
    def seal_block(self, block_id: int, token_block: TokenBlock) -> None:
        """Mark a block's contents complete + reusable; emits Stored.

        Called when prefill writes a full block or decode fills one up.  If
        another block already holds this hash (a race between two identical
        prompts), the newer block stays anonymous (no double-publish).
        """
        if not self._enable_prefix_caching:
            return
        blk = self._blocks[block_id]
        if token_block.sequence_hash in self._by_hash:
            return
        blk.sequence_hash = token_block.sequence_hash
        blk.parent_hash = token_block.parent_hash
        blk.tokens_hash = token_block.block_hash
        self._by_hash[token_block.sequence_hash] = block_id
        self._emit(
            KvCacheEvent.stored(
                self._next_event_id(),
                token_block.parent_hash,
                [
                    KvCacheStoredBlockData(
                        block_hash=token_block.sequence_hash,
                        tokens_hash=token_block.block_hash,
                    )
                ],
            )
        )

    # ---------------------------------------------------------------- freeing
    def free_sequence(self, block_ids: Sequence[int]) -> None:
        """Release references; blocks with hashes park in the reuse pool
        (contents intact), anonymous ones return to the free list."""
        # Tail blocks are appended to the reuse pool first so eviction
        # (oldest-first popitem) consumes a sequence tail-before-head: heads
        # are the shareable prefixes and must outlive their tails, otherwise
        # match_prefix (which stops at the first missing block) can never
        # reach the surviving tail blocks.
        for bid in reversed(list(block_ids)):
            blk = self._blocks[bid]
            blk.ref_count -= 1
            if blk.ref_count > 0:
                continue
            if blk.sequence_hash is not None:
                self._free_reusable[bid] = None
            else:
                self._free_anon.append(bid)

    def clear(self) -> None:
        """Drop everything (emits Cleared)."""
        for blk in self._blocks:
            blk.ref_count = 0
            blk.sequence_hash = blk.parent_hash = blk.tokens_hash = None
        self._free_anon = list(range(self.num_blocks))
        self._free_reusable.clear()
        self._by_hash.clear()
        self._emit(KvCacheEvent(self._next_event_id(), None))
