"""Disk KV tier: the third rung of the memory hierarchy (HBM → host → disk).

Reference direction: CacheGen / Mooncake-style KV tiering (PAPERS.md) — at
millions of users the working set of shared system prompts and multi-turn
sessions outgrows host RAM, and a prefix that fell off the host tier is
still ~100x cheaper to reload from NVMe than to recompute.  Blocks arrive
here ONLY by demotion from the host tier (``HostKvStore.on_evict``) and
leave by promotion back into it (``HostOffloadMixin._promote_from_disk``)
or by LRU eviction — the device never talks to this tier directly.

Layout: one file per block, named by the block's chained sequence hash
(``{hash:016x}.kvblk``) — the same salted chained-hash identity every other
tier and the router index key on, so tenant isolation (llm/tenancy KV
salts) holds structurally here too: a tenant's hashes are the only handles
that can name its files.  Each file is a small self-describing container
(magic + JSON header {dtype, shape, checksum} + raw payload) validated
byte-for-byte on read, mirroring ``inject_blocks``'s validate-before-
allocate contract: a truncated or corrupt file is deleted and treated as
a miss, never scattered into the cache.  The ``checksum`` (CRC-32 over
the payload bytes — engine/integrity.py) is *carried* from the host
tier's offload stamp, not recomputed here, so a bit that rotted in host
RAM between offload and demotion is refused at the write instead of
laundered into a structurally-valid file; reads verify it before any
promotion.  Files without the field (pre-integrity envelopes) stay
readable — omit-when-absent, like the wire plane.

Thread-safety: all mutation happens under one internal lock because
callers run file I/O off the event loop (``asyncio.to_thread``).  Tier
transitions (evictions) are RECORDED, not published — event emission must
happen on the event loop, so the engine drains ``drain_transitions()``
after each threaded call and publishes from there
(``TpuEngine._flush_tier_events``).
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
from collections import OrderedDict
from typing import Callable, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_MAGIC = b"DKVB1\n"
_HLEN = struct.Struct("<I")


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16/fp8 names register with numpy on ml_dtypes import.
        import ml_dtypes  # noqa: F401

        return np.dtype(name)


class DiskKvStore:
    """hash → one block's pages [L, page_size, 2*kv_heads, head_dim] on disk.

    Byte-budgeted LRU like the host tier; counters mirror HostKvStore so
    the tier metrics read uniformly.  Single-process only (the demoting
    host tier holds whole contiguous blocks only in single-process runs —
    multi-host per-shard dicts are refused at ``put``)."""

    def __init__(self, capacity_bytes: int, directory: str, fsync: bool = False):
        self.capacity_bytes = capacity_bytes
        self.directory = directory
        # Demotion hook (mirrors HostKvStore.on_evict): with an object
        # store configured (engine/object_store.py) LRU eviction DEMOTES
        # instead of dropping — ``on_evict(hash, path) -> bool`` receives
        # the block's envelope PATH (not bytes: the next tier parses and
        # re-verifies the file itself, so rot on this tier is refused at
        # the handoff) and a True return means the object tier took it.
        self.on_evict: Optional[Callable[[int, str], bool]] = None
        # Durability knob (DYN_DISK_FSYNC / EngineConfig.disk_fsync):
        # ``os.replace`` is rename-atomic but a power loss can persist the
        # renamed file with unflushed payload pages; fsync-before-rename
        # closes that window at a per-demotion latency cost.  Default OFF
        # because the read-side checksum already catches the torn payload
        # (deleted + recompute) — docs/kv_tiering.md has the tradeoff.
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        # Transition records get their OWN tiny lock: the event loop drains
        # them (drain_transitions via _flush_tier_events) and must never
        # wait behind a thread holding the main lock through file I/O.
        self._tlock = threading.Lock()
        # hash → file bytes, LRU-ordered (oldest first).
        self._index: "OrderedDict[int, int]" = OrderedDict()
        self._bytes = 0
        # counters (metrics / tests)
        self.stored_blocks = 0
        self.promoted_blocks = 0
        self.evicted_blocks = 0
        self.rejected_blocks = 0
        self.corrupt_blocks = 0
        self.demoted_blocks = 0
        # (kind, hash) records for the engine's event flush; "drop" and
        # "demote" (object-tier handoff) — promotion is driven (and
        # recorded) by the engine side.
        self._transitions: List[Tuple[str, int]] = []
        # Rebuild the index from an existing directory (a restarted worker
        # finds its demoted blocks again): coldest = oldest mtime.  Orphaned
        # ``*.kvblk.tmp`` files (a crash mid-write) are deleted here — they
        # hold no indexable content but consume disk OUTSIDE the byte
        # budget, forever, across every restart.
        entries = []
        for name in os.listdir(directory):
            if name.endswith(".kvblk.tmp"):
                try:
                    os.remove(os.path.join(directory, name))
                except OSError:
                    pass
                continue
            if not name.endswith(".kvblk"):
                continue
            try:
                h = int(name[: -len(".kvblk")], 16)
            except ValueError:
                continue
            try:
                st = os.stat(os.path.join(directory, name))
            except OSError:
                continue
            entries.append((st.st_mtime, h, st.st_size))
        for _, h, size in sorted(entries):
            self._index[h] = size
            self._bytes += size

    # ------------------------------------------------------------------ state
    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.directory, f"{seq_hash:016x}.kvblk")

    def _tmp_path(self, final: str) -> str:
        """Staging path for the atomic write protocol: bytes land in
        ``<final>.tmp`` and are ``os.replace``d into place on success or
        ``os.remove``d on failure (dynalint DYN501 tracks this pair)."""
        return final + ".tmp"

    # Reads are deliberately LOCK-FREE: the main lock is held across file
    # I/O by executor threads, and the EVENT LOOP calls contains()/
    # block_nbytes() on hot paths (kv_manager.tier_lookup at eviction,
    # local_prefix_blocks at admission) — blocking the loop on a disk
    # write would stall every live stream.  Dict membership/get are
    # GIL-atomic; a stale answer is safe (a just-evicted hash reads as
    # present → the later validated get() misses → recompute fallback).
    def __len__(self) -> int:
        return len(self._index)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._index

    def block_nbytes(self, seq_hash: int) -> Optional[int]:
        """On-disk size of one block (index lookup, no I/O) — lets the
        promotion path budget the copy BEFORE reading any file."""
        return self._index.get(seq_hash)

    def drain_transitions(self) -> List[Tuple[str, int]]:
        with self._tlock:
            out, self._transitions = self._transitions, []
            return out

    # -------------------------------------------------------------------- put
    def put(self, seq_hash: int, block, checksum: Optional[int] = None) -> bool:
        """Demote one host-tier block to disk.  Returns False (and the
        caller emits Removed instead of a disk tier-tag) when the block
        cannot be taken: multi-host shard dicts, or larger than the whole
        budget.

        ``checksum`` is the block's offload-time integrity stamp
        (engine/integrity.py).  When provided it is VERIFIED against the
        payload before anything touches disk: a mismatch means the bytes
        rotted in host RAM after the stamp, and writing them would launder
        the corruption into a structurally-valid file other requests (and
        restarts) would trust."""
        from .integrity import bytes_checksum

        if not isinstance(block, np.ndarray):
            self.rejected_blocks += 1
            return False
        payload = np.ascontiguousarray(block).tobytes()
        payload_crc = bytes_checksum(payload)
        if checksum is not None and int(checksum) != payload_crc:
            from ..llm.metrics import kv_integrity_metrics

            kv_integrity_metrics.corrupt_total["host"] += 1
            self.corrupt_blocks += 1
            self.rejected_blocks += 1
            logger.warning(
                "refusing to demote block %#x: payload fails its offload "
                "checksum (host-RAM corruption)", seq_hash,
            )
            return False
        header = json.dumps(
            {
                "dtype": str(block.dtype),
                "shape": list(block.shape),
                "checksum": payload_crc,
            }
        ).encode()
        blob = _MAGIC + _HLEN.pack(len(header)) + header + payload
        nbytes = len(blob)
        with self._lock:
            if nbytes > self.capacity_bytes:
                self.rejected_blocks += 1
                return False
            if seq_hash in self._index:
                self._index.move_to_end(seq_hash)
                return True
            while self._bytes + nbytes > self.capacity_bytes and self._index:
                old, old_bytes = self._index.popitem(last=False)  # LRU
                self._bytes -= old_bytes
                self.evicted_blocks += 1
                demoted = False
                if self.on_evict is not None:
                    try:
                        # The file still exists here: the hook parses and
                        # re-verifies it before taking ownership of a copy.
                        demoted = bool(self.on_evict(old, self._path(old)))
                    except Exception:
                        # Demotion is an optimization; a failing object
                        # tier must never break the disk eviction path.
                        logger.exception(
                            "disk-tier demotion failed for %#x", old
                        )
                if demoted:
                    self.demoted_blocks += 1
                with self._tlock:
                    self._transitions.append(
                        ("demote" if demoted else "drop", old)
                    )
                try:
                    os.remove(self._path(old))
                except OSError:
                    pass
            path = self._path(seq_hash)
            tmp = self._tmp_path(path)
            try:
                with open(tmp, "wb") as f:
                    f.write(blob)
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
                os.replace(tmp, path)  # atomic: readers never see a torn file
            except OSError:
                logger.exception("disk KV tier write failed for %#x", seq_hash)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                self.rejected_blocks += 1
                return False
            self._index[seq_hash] = nbytes
            self._bytes += nbytes
            self.stored_blocks += 1
            return True

    # -------------------------------------------------------------------- get
    def get(
        self,
        seq_hash: int,
        expected_shape: Optional[Tuple[int, ...]] = None,
        expected_dtype=None,
    ) -> Optional[np.ndarray]:
        """Read + VALIDATE one block; see ``read`` (this wrapper drops the
        integrity detail for callers that only care hit/miss)."""
        return self.read(seq_hash, expected_shape, expected_dtype)[0]

    def read(
        self,
        seq_hash: int,
        expected_shape: Optional[Tuple[int, ...]] = None,
        expected_dtype=None,
    ) -> Tuple[Optional[np.ndarray], Optional[int], bool]:
        """Read + VALIDATE one block (the inject_blocks contract: a block
        that fails validation is a miss, never a crash or a wrong scatter).
        Returns ``(array, carried_checksum, corrupt)``: the checksum rides
        to the host tier on promotion so the stamp survives the round
        trip; ``corrupt`` distinguishes a failed verification from a plain
        miss so the engine can quarantine the chain.  A corrupt file is
        deleted (it cannot miss forever) and its loss RECORDED so the
        router stops advertising the prefix."""
        from ..runtime.faultinject import faults

        with self._lock:
            if seq_hash not in self._index:
                return None, None, False
            path = self._path(seq_hash)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                self._drop_locked(seq_hash)
                with self._tlock:
                    self._transitions.append(("drop", seq_hash))
                return None, None, False
            if (
                faults.enabled
                and len(blob) > len(_MAGIC) + _HLEN.size
                and faults.should("kv_corrupt", "disk")
            ):
                # Chaos hook: flip one payload byte AFTER the OS read —
                # models media rot the structural checks cannot see.
                from .integrity import flip_blob_byte

                (hlen,) = _HLEN.unpack_from(blob, len(_MAGIC))
                blob = flip_blob_byte(blob, len(_MAGIC) + _HLEN.size + hlen)
            parsed = self._parse(blob, expected_shape, expected_dtype)
            if parsed is None:
                self.corrupt_blocks += 1
                self._drop_locked(seq_hash)
                with self._tlock:
                    self._transitions.append(("drop", seq_hash))
                try:
                    os.remove(path)
                except OSError:
                    pass
                return None, None, True
            arr, checksum = parsed
            self._index.move_to_end(seq_hash)  # touch
            return arr, checksum, False

    def drop(self, seq_hash: int) -> bool:
        """Remove one block (corruption quarantine of chained
        descendants); records the loss for the engine's event flush."""
        with self._lock:
            if seq_hash not in self._index:
                return False
            self._drop_locked(seq_hash)
            try:
                os.remove(self._path(seq_hash))
            except OSError:
                pass
        with self._tlock:
            self._transitions.append(("drop", seq_hash))
        return True

    def _parse(
        self, blob: bytes, expected_shape, expected_dtype
    ) -> Optional[Tuple[np.ndarray, Optional[int]]]:
        from .integrity import bytes_checksum

        if not blob.startswith(_MAGIC) or len(blob) < len(_MAGIC) + _HLEN.size:
            return None
        off = len(_MAGIC)
        (hlen,) = _HLEN.unpack_from(blob, off)
        off += _HLEN.size
        if len(blob) < off + hlen:
            return None
        try:
            header = json.loads(blob[off : off + hlen])
            dt = _np_dtype(header["dtype"])
            shape = tuple(int(s) for s in header["shape"])
            checksum = header.get("checksum")
            checksum = None if checksum is None else int(checksum)
        except (ValueError, KeyError, TypeError):
            return None
        off += hlen
        if len(blob) - off != int(np.prod(shape)) * dt.itemsize:
            return None  # truncated/padded payload
        if expected_shape is not None and shape != tuple(expected_shape):
            return None
        if expected_dtype is not None and dt != np.dtype(expected_dtype):
            return None
        if checksum is not None and bytes_checksum(blob[off:]) != checksum:
            return None  # payload bit-rot: structural checks passed, CRC not
        return np.frombuffer(blob, dtype=dt, offset=off).reshape(shape), checksum

    def _drop_locked(self, seq_hash: int) -> None:
        nbytes = self._index.pop(seq_hash, None)
        if nbytes is not None:
            self._bytes -= nbytes
