"""Engine-side live-migration primitives: snapshot, freeze, cutover.

Llumnix-style (OSDI'24) live sequence migration needs three things from the
engine that preemption-style rescheduling does not:

- a **serializable decode-state snapshot** (``SequenceSnapshot``,
  llm/migration/snapshot.py): everything needed to continue the stream
  token-identically on another engine — fed tokens, the per-request sampler
  seed and rng-stream position (``orig_prompt_len``), stop conditions, and
  the speculative-decoding controller state;
- a **freeze** primitive for the brief final-delta window: the sequence
  keeps its KV blocks and output queue but stops being planned, so the
  source can export the last sealed blocks and the snapshot against a
  frontier that no in-flight dispatch is still advancing;
- a **cutover/rollback** pair: cutover emits one last stream item (the
  ``migrated`` splice marker the routed client consumes) and releases the
  sequence WITHOUT a finish_reason; rollback simply unfreezes — the source
  never stopped being authoritative, so a failed migration costs nothing
  but the copied bytes (which land as harmless prefix-cache fills on the
  target).

KV itself moves over the existing hash-addressed transfer plane
(engine/transfer.py): decode seals complete blocks as it goes, so the
sealed frontier of ``prompt + output`` is exportable with
``export_prompt_blocks`` at any time, and the unsealed tail (< block_size
tokens) is recomputed by the target as an ordinary partial prefix hit.
"""

from __future__ import annotations

import asyncio
import time
import logging
from typing import Any, Dict, List, Optional

from .pipeline import _FINISHED
from .scheduler import SequenceState

logger = logging.getLogger(__name__)


class MigrationMixin:
    """TpuEngine methods backing llm/migration's source-side protocol."""

    def find_sequence(self, request_id: str) -> Optional[SequenceState]:
        for seq in self.scheduler.running:
            if seq.request_id == request_id:
                return seq
        for seq in self.scheduler.waiting:
            if seq.request_id == request_id:
                return seq
        return None

    def live_request_ids(self) -> List[str]:
        """Requests a migrate-out drain would move (not finished/frozen)."""
        return [
            s.request_id
            for s in list(self.scheduler.running) + list(self.scheduler.waiting)
            if not s.finished and not s.frozen
        ]

    def sequence_tokens(self, request_id: str) -> Optional[List[int]]:
        """The full fed-token stream (prompt + output) at this instant —
        the hash-addressed identity the KV transfer plane exports by."""
        seq = self.find_sequence(request_id)
        if seq is None:
            return None
        return list(seq.prompt) + list(seq.output)

    def snapshot_sequence(self, request_id: str):
        """Serializable decode-state checkpoint (llm/migration/snapshot.py).

        Valid for resume only when taken on a QUIESCENT sequence (after
        ``freeze_sequence``); an unfrozen snapshot is still useful as a
        progress probe (phase-1 copy loops read the token frontier)."""
        from ..llm.migration.snapshot import SequenceSnapshot

        seq = self.find_sequence(request_id)
        if seq is None:
            return None
        ctx = self._contexts.get(request_id)
        deadline = getattr(ctx, "deadline", None) if ctx is not None else None
        return SequenceSnapshot(
            request_id=request_id,
            token_ids=list(seq.prompt) + list(seq.output),
            orig_prompt_len=seq.orig_prompt_len,
            sampling={
                # Resolved values (engine defaults applied) so the target
                # reproduces the sampler stream exactly even when its own
                # engine seed differs.
                "seed": int(seq.sampling_seed),
                "temperature": float(seq.sampling_temperature),
                "top_k": int(seq.sampling_top_k),
                "top_p": float(seq.sampling_top_p),
                "frequency_penalty": float(seq.freq_penalty),
                "presence_penalty": float(seq.pres_penalty),
                "logprobs": seq.logprobs,
                "spec_decode": seq.spec_enabled,
            },
            stop={
                "max_tokens": seq.max_new_tokens,
                "min_tokens": seq.min_new_tokens,
                "stop_token_ids": sorted(seq.stop_token_ids),
                "ignore_eos": bool(seq.ignore_eos),
            },
            spec={
                "k": seq.spec_k,
                "ewma": seq.spec_ewma,
                "bench_until": seq.spec_bench_until,
                "next_try": seq.spec_next_try,
                "miss": seq.spec_miss,
            },
            deadline_s=(
                max(deadline.remaining(), 0.0) if deadline is not None else None
            ),
            # Tenant identity (llm/tenancy): the adapter + KV salt travel
            # with the sequence; the grammar automaton ships serialized and
            # the target re-derives its state from the resumed tokens.
            adapter=seq.adapter,
            kv_salt=seq.kv_salt,
            tenant=seq.tenant or None,
            priority=seq.priority or None,
            grammar=seq.grammar.to_dict() if seq.grammar is not None else None,
            # Tracing continuity (runtime/tracing.py): only the CONTEXT
            # travels — the target opens its own spans under the same
            # trace_id; source-side anchors stay source-local.
            trace=seq.trace.ctx.to_dict() if seq.trace is not None else None,
        )

    async def freeze_sequence(
        self, request_id: str, timeout: float = 10.0
    ) -> Optional[SequenceState]:
        """Stop planning ``request_id`` and wait until no in-flight dispatch
        can still advance it (deferred fetches harvested; fused-pipeline
        membership released).  Under the continuous pipeline
        (docs/decode_pipeline.md) the frozen row is parked OUT of a live
        fused session at its write barrier — ``_pipeline_members`` drops
        the id a few chunks later while the session keeps fusing for
        everyone else, and any not-yet-harvested chunk tokens for the row
        are dropped (recomputed identically on resume: seeded sampler), so
        the snapshot frontier always equals the emitted stream.  Returns
        the quiescent SequenceState, or None if the sequence is
        gone/finished or quiescence didn't land in ``timeout`` (the flag
        is cleared again — the sequence keeps decoding)."""
        seq = self.find_sequence(request_id)
        if seq is None or seq.finished:
            return None
        seq.frozen = True
        self._wake.set()
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if seq.finished:
                # Finished (stop token landed from an in-flight chunk, or
                # the client cancelled) while we were freezing: nothing
                # left to migrate.
                seq.frozen = False
                return None
            if (
                not seq.awaiting_fetch
                and request_id not in self._pipeline_members
            ):
                # Quiescent: publish the sealed frontier so the final-delta
                # export sees every complete block.
                self._seal_completed_blocks(seq)
                return seq
            await asyncio.sleep(0.005)
        self.unfreeze_sequence(request_id)
        return None

    def unfreeze_sequence(self, request_id: str) -> None:
        """Rollback: the source resumes decoding exactly where it froze."""
        seq = self.find_sequence(request_id)
        if seq is not None:
            seq.frozen = False
        self._wake.set()

    def finish_migrated(
        self, request_id: str, item: Optional[Dict[str, Any]] = None
    ) -> None:
        """Cutover: emit ``item`` (the ``migrated`` splice marker) as the
        stream's last payload, end the stream WITHOUT a finish_reason, and
        release the sequence's slot and blocks.  The freed blocks keep
        their contents in the reuse pool, so an aborted client-side
        re-dispatch could still fall back to this worker with a prefix hit.
        """
        seq = self.find_sequence(request_id)
        if seq is not None:
            # A sequence migrated out mid-prefill leaves the mixed phase:
            # its chunk count must not carry into the cadence of whoever
            # prefills next (same invariant as preemption requeue).
            if seq.in_prefill:
                self._chunks_since_burst = 0
            seq.finished = True
            seq.frozen = False
            # Cutover bypasses pipeline._finish, so the adapter-slot ref
            # (llm/tenancy) must drop here too or a migrated-out LoRA
            # sequence pins its slot on the source forever.
            if (
                self._lora_registry is not None
                and seq.adapter is not None
                and not seq.adapter_released
            ):
                seq.adapter_released = True
                self._lora_registry.release(seq.adapter)
            self.scheduler.remove(seq)
        queue = self._queues.get(request_id)
        if queue is not None:
            if item is not None:
                queue.put_nowait(item)
            queue.put_nowait(_FINISHED)
        self._wake.set()
