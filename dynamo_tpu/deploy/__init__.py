"""Deploy layer (L7): CRD-shaped deployment spec → k8s manifests.

Reference counterpart: deploy/dynamo/operator (DynamoDeployment CRD +
controller), deploy/helm.  See deploy/k8s/crd.yaml and renderer.py."""

from .renderer import render, render_to_yaml, shell_preview  # noqa: F401
