"""DynamoTpuDeployment → k8s manifests: the operator's reconcile logic as a
pure function.

Reference counterpart: the k8s operator's child-resource generation
(deploy/dynamo/operator/*: a DynamoDeployment CR fans out into per-service
Deployments/Services with env wiring) and the helm chart's templates.  Here
the same mapping is a testable function — usable by an in-cluster controller
or from the CLI (``dynamo-tpu deploy render``) for GitOps-style flows.

Service roles map onto the CLI (cli.py):
  hub       → ``cli hub``                          (control plane)
  frontend  → ``cli http --hub … --router kv``     (OpenAI edge)
  worker    → ``cli run in=dyn://… out=tpu``        (aggregated engine)
  prefill   → worker with ``--disagg prefill``
  decode    → worker with ``--disagg decode``
  router    → standalone KV router (via frontend flag today)
  metrics   → ``cli metrics``

Multi-host workers (nnodes > 1) render one StatefulSet with nnodes pods;
rank/coordinator wiring comes from the pod ordinal + headless service —
matching the engine's --nnodes/--node-rank/--coordinator flags.
"""

from __future__ import annotations

import shlex
from typing import Any, Dict, List

HUB_PORT = 6650
HTTP_PORT = 8000
METRICS_PORT = 9091
STEP_PORT = 6651
COORD_PORT = 6652


def _meta(name: str, app: str, extra: Dict[str, str] = {}) -> Dict[str, Any]:
    return {
        "name": name,
        "labels": {"app.kubernetes.io/name": app,
                   "app.kubernetes.io/part-of": "dynamo-tpu", **extra},
    }


def _env_list(*groups) -> List[Dict[str, str]]:
    out: List[Dict[str, str]] = []
    for g in groups:
        out.extend(g or [])
    return out


def _engine_flags(engine: Dict[str, Any]) -> List[str]:
    flags = []
    for key, val in (engine or {}).items():
        flags.append(f"--{key.replace('_', '-')}")
        flags.append(str(val))
    return flags


def render(cr: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One DynamoTpuDeployment custom resource → child manifests."""
    name = cr["metadata"]["name"]
    namespace = cr["metadata"].get("namespace", "default")
    spec = cr["spec"]
    image = spec["image"]
    model = spec.get("model", "model")
    global_envs = spec.get("envs", [])
    out: List[Dict[str, Any]] = []
    hub_addr = f"{name}-hub.{namespace}.svc:{HUB_PORT}"

    services = spec.get("services") or {"hub": {"role": "hub"},
                                        "frontend": {"role": "frontend"},
                                        "worker": {"role": "worker"}}
    for svc_name, svc in services.items():
        role = svc.get("role", svc_name)
        full = f"{name}-{svc_name}"
        replicas = int(svc.get("replicas", 1))
        nnodes = int(svc.get("nnodes", 1))
        tpu = svc.get("tpu") or {}
        envs = _env_list(global_envs, svc.get("envs"))

        if role == "hub":
            cmd = ["python", "-m", "dynamo_tpu.cli", "hub",
                   "--port", str(HUB_PORT)]
            out.append(_deployment(full, namespace, image, cmd, replicas,
                                   envs, port=HUB_PORT))
            out.append(_service(full, namespace, HUB_PORT))
            continue
        if role == "frontend":
            cmd = ["python", "-m", "dynamo_tpu.cli", "http", "--hub", hub_addr,
                   "--port", str(HTTP_PORT), "--router",
                   str(svc.get("engine", {}).get("router", "kv"))]
            out.append(_deployment(full, namespace, image, cmd, replicas,
                                   envs, port=HTTP_PORT))
            out.append(_service(full, namespace, HTTP_PORT))
            ing = svc.get("ingress")
            if ing is not None:  # {} is an error (host required), not "off"
                # External exposure for the OpenAI edge (reference operator
                # renders ingress/virtual-service objects for its frontend;
                # dynamocomponent_controller.go ingress half).
                out.append(_ingress(full, namespace, HTTP_PORT, ing))
            continue
        if role == "metrics":
            cmd = ["python", "-m", "dynamo_tpu.cli", "metrics", "--hub",
                   hub_addr, "--port", str(METRICS_PORT)]
            out.append(_deployment(full, namespace, image, cmd, replicas,
                                   envs, port=METRICS_PORT))
            out.append(_service(full, namespace, METRICS_PORT))
            continue

        # engine roles: worker / prefill / decode
        endpoint = f"dyn://dynamo.TpuWorker.{svc_name}"
        cmd = ["python", "-m", "dynamo_tpu.cli", "run", f"in={endpoint}",
               "out=tpu", "--hub", hub_addr, "--model", model]
        if spec.get("checkpoint"):
            cmd += ["--checkpoint", spec["checkpoint"]]
        if role in ("prefill", "decode"):
            cmd += ["--disagg", role]
        cmd += _engine_flags(svc.get("engine"))
        if nnodes > 1:
            # Pod ordinal = node rank; rank 0's pod DNS is the coordinator.
            coord = f"{full}-0.{full}.{namespace}.svc:{COORD_PORT}"
            cmd += ["--nnodes", str(nnodes), "--coordinator", coord,
                    "--step-port", str(STEP_PORT),
                    "--node-rank", "$(POD_ORDINAL)"]
            envs = envs + [{
                "name": "POD_ORDINAL",
                "valueFrom": {"fieldRef": {
                    "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"
                }},
            }]
        out.append(_statefulset(full, namespace, image, cmd,
                                replicas=nnodes if nnodes > 1 else replicas,
                                envs=envs, tpu=tpu))
        out.append(_service(full, namespace, STEP_PORT, headless=True))
    return out


def _container(name: str, image: str, cmd: List[str], envs, tpu=None,
               port=None) -> Dict[str, Any]:
    c: Dict[str, Any] = {
        "name": name,
        "image": image,
        "command": cmd,
        "env": envs,
    }
    if port is not None:
        c["ports"] = [{"containerPort": port}]
    if tpu:
        chips = int(tpu.get("chips", 4))
        c["resources"] = {"limits": {"google.com/tpu": chips},
                          "requests": {"google.com/tpu": chips}}
    return c


def _deployment(name, namespace, image, cmd, replicas, envs, port=None):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {**_meta(name, name), "namespace": namespace},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
            "template": {
                "metadata": _meta(name, name),
                "spec": {"containers": [
                    _container(name, image, cmd, envs, port=port)
                ]},
            },
        },
    }


def _statefulset(name, namespace, image, cmd, replicas, envs, tpu):
    pod_spec: Dict[str, Any] = {
        "containers": [_container(name, image, cmd, envs, tpu=tpu)],
    }
    if tpu.get("accelerator"):
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": tpu["accelerator"],
        }
        if tpu.get("topology"):
            pod_spec["nodeSelector"][
                "cloud.google.com/gke-tpu-topology"
            ] = tpu["topology"]
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {**_meta(name, name), "namespace": namespace},
        "spec": {
            "serviceName": name,
            "replicas": replicas,
            "podManagementPolicy": "Parallel",  # all ranks start together
            "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
            "template": {
                "metadata": _meta(name, name),
                "spec": pod_spec,
            },
        },
    }


def _service(name, namespace, port, headless=False):
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {**_meta(name, name), "namespace": namespace},
        "spec": {
            "selector": {"app.kubernetes.io/name": name},
            "ports": [{"port": port, "targetPort": port}],
        },
    }
    if headless:
        svc["spec"]["clusterIP"] = "None"
    return svc


def _ingress(name, namespace, port, ing: Dict[str, Any]):
    """networking.k8s.io/v1 Ingress for a frontend Service.

    ``ing``: {host: str (required), className: str?, path: str?,
    tlsSecret: str?, annotations: {...}?}."""
    host = ing.get("host")
    if not host:
        raise ValueError("frontend ingress needs a 'host'")
    meta = {**_meta(name, name), "namespace": namespace}
    user_ann = dict(ing.get("annotations") or {})
    # Owned-keys marker: the drift check (_spec_equal) compares desired vs
    # observed by SUBSET, so REMOVING an annotation from the CR would
    # otherwise never re-apply (the smaller set still subsets the live
    # object).  Encoding the owned key list in an annotation makes a
    # removal change the marker value → drift → server-side apply, which
    # then drops the removed key (this fieldManager owns it).
    user_ann["dynamo.tpu.io/owned-annotations"] = ",".join(sorted(user_ann))
    meta["annotations"] = user_ann
    spec: Dict[str, Any] = {
        "rules": [
            {
                "host": host,
                "http": {
                    "paths": [
                        {
                            "path": ing.get("path", "/"),
                            "pathType": "Prefix",
                            "backend": {
                                "service": {
                                    "name": name,
                                    "port": {"number": port},
                                }
                            },
                        }
                    ]
                },
            }
        ]
    }
    if ing.get("className"):
        spec["ingressClassName"] = ing["className"]
    if ing.get("tlsSecret"):
        spec["tls"] = [{"hosts": [host], "secretName": ing["tlsSecret"]}]
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "Ingress",
        "metadata": meta,
        "spec": spec,
    }


def render_to_yaml(cr: Dict[str, Any]) -> str:
    import yaml

    docs = render(cr)
    return "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)


def shell_preview(cr: Dict[str, Any]) -> str:
    """The commands each service runs (docs / dry-run aid)."""
    lines = []
    for doc in render(cr):
        if doc["kind"] in ("Deployment", "StatefulSet"):
            c = doc["spec"]["template"]["spec"]["containers"][0]
            lines.append(f"# {doc['metadata']['name']}")
            lines.append(" ".join(shlex.quote(x) for x in c["command"]))
    return "\n".join(lines)
