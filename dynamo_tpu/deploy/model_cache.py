"""DynamoTpuModelCache controller: pre-stage checkpoints via k8s Jobs.

Reference counterpart: the operator's second controller half —
``dynamonimrequest_controller.go`` (1965 LoC) builds the ARTIFACT a
deployment consumes (a container image baked from a NIM request) before
serving starts.  The TPU-native analog of "build the artifact" is
"stage the checkpoint": serving pods resolve models from DYN_MODEL_CACHE
(models/hub.py), so this controller renders a batch/v1 Job that runs
``python -m dynamo_tpu.cli prepare MODEL --cache <pvc mount>`` into a
shared PVC, and reports Pending/Running/Ready/Failed from the Job's
status — cold-start downloads move out of the serving path exactly the
way image builds do in the reference.

CR shape (deploy/k8s/modelcache-crd.yaml):

  apiVersion: dynamo.tpu.io/v1alpha1
  kind: DynamoTpuModelCache
  spec:
    model: deepseek-ai/DeepSeek-R1-Distill-Llama-8B   # alias/repo/path
    revision: main          # optional
    image: dynamo-tpu:latest
    pvc: model-cache        # PVC mounted at /models in the fetch Job
    path: /models           # optional mount path

Job names embed a short hash of (model, revision, image): editing the CR
spawns a fresh Job and the stale one is swept as an orphan — Jobs are
effectively immutable, so "update" is replace-by-name.
"""

from __future__ import annotations

import copy
import hashlib
import logging
from typing import Any, Dict, Optional

from .controller import MANAGER_LABEL, OWNER_LABEL, Reconciler

logger = logging.getLogger(__name__)

CACHE_CR_PLURAL = "dynamotpumodelcaches"


def _spec_hash(spec: Dict[str, Any]) -> str:
    key = "|".join(
        str(spec.get(k, "")) for k in ("model", "revision", "image", "pvc", "path")
    )
    return hashlib.sha256(key.encode()).hexdigest()[:10]


def _job_name(cr_name: str, spec: Dict[str, Any]) -> str:
    """``<cr>-fetch-<hash>``, truncated from the CR-name side so the hash
    (the spec identity) survives both the 253-char object-name limit and
    the 63-char label-value limit."""
    return f"{cr_name[:46]}-fetch-{_spec_hash(spec)}"


def render_fetch_job(cr: Dict[str, Any]) -> Dict[str, Any]:
    """batch/v1 Job staging ``spec.model`` into the PVC."""
    name = cr["metadata"]["name"]
    spec = cr.get("spec") or {}
    for req in ("model", "image", "pvc"):
        if not spec.get(req):
            raise ValueError(f"DynamoTpuModelCache {name!r} needs spec.{req}")
    mount = spec.get("path") or "/models"
    cmd = ["python", "-m", "dynamo_tpu.cli", "prepare", spec["model"],
           "--cache", mount]
    if spec.get("revision"):
        cmd += ["--revision", str(spec["revision"])]
    job_name = _job_name(name, spec)
    labels = {
        "app.kubernetes.io/name": job_name,  # <=63 chars by construction
        OWNER_LABEL: name,
    }
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": job_name, "labels": labels,
                     "namespace": cr["metadata"].get("namespace", "default")},
        "spec": {
            "backoffLimit": 3,
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [
                        {
                            "name": "fetch",
                            "image": spec["image"],
                            "command": cmd,
                            "env": [{"name": "JAX_PLATFORMS", "value": "cpu"}],
                            "volumeMounts": [
                                {"name": "cache", "mountPath": mount}
                            ],
                        }
                    ],
                    "volumes": [
                        {
                            "name": "cache",
                            "persistentVolumeClaim": {"claimName": spec["pvc"]},
                        }
                    ],
                },
            },
        },
    }


class ModelCacheReconciler(Reconciler):
    """Drives DynamoTpuModelCache CRs: one fetch Job per spec revision.

    Subclasses Reconciler for the manager-scoped teardown / orphan-sweep
    machinery (one implementation of the scoping rules — the r4 advisory
    semantics must not diverge between the two controllers); only
    ``reconcile`` and the child kind differ."""

    CHILD_KINDS = ("Job",)

    async def reconcile(self, cr: Dict[str, Any]) -> Dict[str, Any]:
        name = cr["metadata"]["name"]
        job = copy.deepcopy(render_fetch_job(cr))
        job["metadata"]["labels"][MANAGER_LABEL] = self.manager
        job["spec"]["template"]["metadata"]["labels"][MANAGER_LABEL] = self.manager
        want_name = job["metadata"]["name"]

        observed: Dict[str, Dict[str, Any]] = {}
        for m in await self.kube.list("Job", label=(OWNER_LABEL, name)):
            labels = m["metadata"].get("labels") or {}
            if labels.get(MANAGER_LABEL) not in (None, self.manager):
                continue
            observed[m["metadata"]["name"]] = m

        if want_name not in observed:
            await self.kube.apply(job)
        # Jobs from superseded specs (different hash): delete.
        for jname, m in observed.items():
            if jname != want_name:
                await self.kube.delete("Job", jname)

        status = self._status(observed.get(want_name))
        await self.kube.update_status(
            dict(cr, kind="DynamoTpuModelCache"), status
        )
        return status

    def _status(self, job: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        if job is None:
            return {"phase": "Pending"}
        js = job.get("status") or {}
        if js.get("succeeded"):
            return {"phase": "Ready"}
        # The authoritative terminal signal is the Failed CONDITION (the
        # pod-failure count at exhaustion can be <= backoffLimit due to
        # counting races; a hardcoded count threshold can stick at
        # Pending forever).
        for cond in js.get("conditions") or []:
            if cond.get("type") == "Failed" and cond.get("status") == "True":
                return {"phase": "Failed", "failed": js.get("failed", 0)}
        if js.get("active"):
            return {"phase": "Running"}
        return {"phase": "Pending"}

    # teardown(), sweep_orphans(), run_pass() and the watch-driven run()
    # are INHERITED from Reconciler with CHILD_KINDS=("Job",) and
    # CR_KIND="DynamoTpuModelCache" — one implementation of the
    # manager-scoping and watch/resync machinery.
    CR_KIND = "DynamoTpuModelCache"
