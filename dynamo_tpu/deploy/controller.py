"""Operator controller: watches DynamoTpuDeployment CRs and reconciles the
cluster to `render(cr)`.

Reference counterpart: the Go operator's reconcile loop
(/root/reference/deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go:1-2169) — fetch CR, generate child
resources, create/update/delete to match, write status.  controller-runtime
gives the Go version its watch/cache machinery; here the same loop is an
asyncio poll-or-watch over a minimal cluster client protocol, so the whole
reconcile path is unit-testable against an in-memory fake (the reference
tests the same way with controller-runtime's fake client).

Split of responsibilities (mirrors the reference):
- deploy/renderer.py — PURE mapping CR → desired children;
- Reconciler (here)  — diffing desired vs observed, ownership, drift
  repair, status writing;
- KubeApi (here)     — the only piece that talks to a real API server.
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
from typing import Any, Dict, List, Optional, Tuple

from .renderer import render

logger = logging.getLogger(__name__)

GROUP = "dynamo.tpu.io"  # matches deploy/k8s/crd.yaml
OWNER_LABEL = f"{GROUP}/owner"
# Which control plane created a child ("operator" = the k8s CR controller,
# "api-store" = hub-CR REST store running with --kube).  The orphan sweep
# and teardown only ever touch children carrying their OWN manager value —
# without this, an operator sharing a namespace with an api-store would
# sweep away every api-store deployment within one poll (r4 advisory).
MANAGER_LABEL = f"{GROUP}/managed-by"
CR_PLURAL = "dynamotpudeployments"


def _kind_name(m: Dict[str, Any]) -> Tuple[str, str]:
    return m["kind"], m["metadata"]["name"]


def _subset(want: Any, have: Any) -> bool:
    """True when every field ``want`` sets matches ``have``.  The API
    server populates spec defaults the renderer omits (strategy,
    restartPolicy, dnsPolicy, ...), so EQUALITY against the observed
    object would re-apply every child on every poll forever; only the
    fields the controller actually owns may trigger an apply."""
    if isinstance(want, dict):
        if not isinstance(have, dict):
            return False
        return all(k in have and _subset(v, have[k]) for k, v in want.items())
    if isinstance(want, list):
        if not isinstance(have, list) or len(want) != len(have):
            return False
        return all(_subset(w, h) for w, h in zip(want, have))
    return want == have


def _spec_equal(desired: Dict[str, Any], observed: Dict[str, Any]) -> bool:
    """Drift check over the fields the controller owns (spec + labels +
    annotations — Ingress behavior is CONFIGURED via annotations, so a CR
    annotation edit must count as drift)."""
    return _subset(
        {
            "spec": desired.get("spec"),
            "labels": (desired.get("metadata") or {}).get("labels"),
            "annotations": (desired.get("metadata") or {}).get("annotations"),
        },
        {
            "spec": observed.get("spec"),
            "labels": (observed.get("metadata") or {}).get("labels"),
            "annotations": (observed.get("metadata") or {}).get("annotations"),
        },
    )


class FakeKube:
    """In-memory cluster for tests (the reference uses controller-runtime's
    fake client the same way).  Stores manifests by (kind, name); simulates
    readiness by echoing spec replicas into status when `auto_ready`."""

    def __init__(self, auto_ready: bool = True):
        self.objects: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.auto_ready = auto_ready
        self.applied: List[Tuple[str, str]] = []  # audit trail
        self.deleted: List[Tuple[str, str]] = []

    async def list(
        self, kind: str, label: Optional[Tuple[str, str]] = None
    ) -> List[Dict[str, Any]]:
        out = []
        for (k, _), m in self.objects.items():
            if k != kind:
                continue
            if label is not None:
                labels = (m.get("metadata") or {}).get("labels") or {}
                if labels.get(label[0]) != label[1]:
                    continue
            out.append(copy.deepcopy(m))
        return out

    async def apply(self, manifest: Dict[str, Any]) -> None:
        key = _kind_name(manifest)
        m = copy.deepcopy(manifest)
        if self.auto_ready and m["kind"] in ("Deployment", "StatefulSet"):
            reps = (m.get("spec") or {}).get("replicas", 1)
            m["status"] = {"readyReplicas": reps, "replicas": reps}
        prev = self.objects.get(key)
        if prev is not None and "status" in prev and "status" not in m:
            m["status"] = prev["status"]
        self.objects[key] = m
        self.applied.append(key)

    async def delete(self, kind: str, name: str) -> bool:
        self.deleted.append((kind, name))
        return self.objects.pop((kind, name), None) is not None

    async def update_status(self, cr: Dict[str, Any], status: Dict[str, Any]) -> None:
        kind = cr.get("kind") or "DynamoTpuDeployment"
        key = (kind, cr["metadata"]["name"])
        if key in self.objects:
            self.objects[key]["status"] = copy.deepcopy(status)


class KubeApi:
    """Minimal in-cluster API-server client (aiohttp).  Reads the standard
    serviceaccount token/CA; `apply` uses server-side apply so the loop is
    idempotent without resourceVersion bookkeeping."""

    SA = "/var/run/secrets/kubernetes.io/serviceaccount"

    _PATHS = {
        "Deployment": "/apis/apps/v1/namespaces/{ns}/deployments",
        "StatefulSet": "/apis/apps/v1/namespaces/{ns}/statefulsets",
        "Service": "/api/v1/namespaces/{ns}/services",
        "Ingress": "/apis/networking.k8s.io/v1/namespaces/{ns}/ingresses",
        "Job": "/apis/batch/v1/namespaces/{ns}/jobs",
        "DynamoTpuModelCache": (
            f"/apis/{GROUP}/v1alpha1/namespaces/{{ns}}/dynamotpumodelcaches"
        ),
        "DynamoTpuDeployment": (
            f"/apis/{GROUP}/v1alpha1/namespaces/{{ns}}/{CR_PLURAL}"
        ),
    }

    def __init__(self, namespace: str = "default", base: Optional[str] = None):
        import os

        self.namespace = namespace
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base = base or f"https://{host}:{port}"
        self._token: Optional[str] = None
        self._session = None

    async def _http(self):
        import os

        if self._session is None:
            import ssl

            import aiohttp

            ctx: Any = None
            ca = os.path.join(self.SA, "ca.crt")
            if os.path.exists(ca):
                ctx = ssl.create_default_context(cafile=ca)
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=ctx)
            )
        # Projected serviceaccount tokens are time-bound and the kubelet
        # refreshes the FILE — re-read per request, or a long-running
        # operator goes permanently 401 after ~1h.
        tokf = os.path.join(self.SA, "token")
        if os.path.exists(tokf):
            with open(tokf) as f:
                self._token = f.read().strip()
        return self._session

    def _headers(self, content_type: Optional[str] = None) -> Dict[str, str]:
        h = {}
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        if content_type:
            h["Content-Type"] = content_type
        return h

    def _path(self, kind: str, name: Optional[str] = None) -> str:
        p = self.base + self._PATHS[kind].format(ns=self.namespace)
        return f"{p}/{name}" if name else p

    async def list(self, kind, label=None):
        s = await self._http()
        params = {}
        if label is not None:
            params["labelSelector"] = f"{label[0]}={label[1]}"
        async with s.get(
            self._path(kind), params=params, headers=self._headers()
        ) as r:
            r.raise_for_status()
            return (await r.json()).get("items", [])

    async def apply(self, manifest):
        s = await self._http()
        kind, name = _kind_name(manifest)
        async with s.patch(
            self._path(kind, name),
            params={"fieldManager": "dynamo-tpu-operator", "force": "true"},
            data=json.dumps(manifest),
            headers=self._headers("application/apply-patch+yaml"),
        ) as r:
            r.raise_for_status()

    async def delete(self, kind, name) -> bool:
        s = await self._http()
        async with s.delete(
            self._path(kind, name),
            # Background propagation: a bare API delete of a Job ORPHANS
            # its pods (they keep running and writing); cascade everywhere
            # — it is the kubectl default for the other kinds anyway.
            params={"propagationPolicy": "Background"},
            headers=self._headers(),
        ) as r:
            return r.status < 300

    async def watch(self, kind: str):
        """Yield watch events for ``kind`` (k8s chunked-JSON watch stream).

        One LIST first captures resourceVersion so the watch starts from a
        consistent point; the stream then yields each event dict.  Server
        timeouts / 410 Gone end the generator — the caller's pump restarts
        it (Reconciler.run), and the periodic resync covers anything a
        restart gap missed."""
        s = await self._http()
        # rv-capture list: limit=1 — only metadata.resourceVersion matters
        # (k8s ends watches server-side every few minutes by design, so
        # this runs on every restart; never download the full collection).
        async with s.get(
            self._path(kind), params={"limit": "1"}, headers=self._headers()
        ) as r:
            r.raise_for_status()
            rv = ((await r.json()).get("metadata") or {}).get(
                "resourceVersion", ""
            )
        params = {"watch": "1", "allowWatchBookmarks": "true"}
        if rv:
            params["resourceVersion"] = rv
        async with s.get(
            self._path(kind), params=params, headers=self._headers(),
            timeout=None,
        ) as r:
            r.raise_for_status()
            # Chunk-based line splitting: aiohttp's line iterator caps at
            # 64 KiB and k8s objects (managedFields!) routinely exceed it
            # — a too-long line would kill the watch with ValueError.
            buf = b""
            async for chunk in r.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    event = json.loads(line)
                    if event.get("type") == "BOOKMARK":
                        continue
                    yield event

    async def update_status(self, cr, status):
        s = await self._http()
        name = cr["metadata"]["name"]
        kind = cr.get("kind") or "DynamoTpuDeployment"
        body = {
            "apiVersion": f"{GROUP}/v1alpha1",
            "kind": kind,
            "metadata": {"name": name},
            "status": status,
        }
        async with s.patch(
            self._path(kind, name) + "/status",
            params={"fieldManager": "dynamo-tpu-operator", "force": "true"},
            data=json.dumps(body),
            headers=self._headers("application/apply-patch+yaml"),
        ) as r:
            if r.status < 300:
                return
            sub_status = r.status
        attempted = "status subresource"
        if sub_status in (404, 405):
            # CRD registered without the status subresource: fall back to
            # patching status on the main resource (merge-patch).
            attempted = "subresource (HTTP %s) and merge-patch fallback" % sub_status
            async with s.patch(
                self._path(kind, name),
                data=json.dumps({"status": status}),
                headers=self._headers("application/merge-patch+json"),
            ) as r2:
                if r2.status < 300:
                    return
                sub_status = r2.status
        # A silently-dropped status write hides reconcile results from
        # kubectl — surface it (r4 weak #6: this was debug-logged).
        logger.warning(
            "status write failed for %s: HTTP %s via %s",
            name, sub_status, attempted,
        )

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None


class Reconciler:
    """Drives one CR (or all CRs) to its rendered desired state."""

    CHILD_KINDS = ("Deployment", "StatefulSet", "Service", "Ingress")

    def __init__(self, kube, manager: str = "operator"):
        self.kube = kube
        # Control-plane identity stamped on children (MANAGER_LABEL); sweep
        # and teardown are scoped to it.
        self.manager = manager

    def _mine(self, m: Dict[str, Any]) -> bool:
        labels = m["metadata"].get("labels") or {}
        return labels.get(MANAGER_LABEL) == self.manager

    async def reconcile(self, cr: Dict[str, Any]) -> Dict[str, Any]:
        """One reconcile pass for ``cr``; returns the status written."""
        name = cr["metadata"]["name"]
        desired = []
        for m in render(cr):
            m = copy.deepcopy(m)
            labels = m["metadata"].setdefault("labels", {})
            labels[OWNER_LABEL] = name
            labels[MANAGER_LABEL] = self.manager
            desired.append(m)
        desired_keys = {_kind_name(m) for m in desired}

        observed: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for kind in self.CHILD_KINDS:
            for m in await self.kube.list(kind, label=(OWNER_LABEL, name)):
                if self._mine(m):  # never adopt another plane's children
                    observed[_kind_name(m)] = m

        # Create missing / update drifted (covers spec drift AND manual
        # deletion — the apply re-creates).
        for m in desired:
            cur = observed.get(_kind_name(m))
            if cur is None or not _spec_equal(m, cur):
                await self.kube.apply(m)

        # Delete owned children no longer rendered (a service removed from
        # the CR takes its Deployment + Service with it).
        for key, _ in observed.items():
            if key not in desired_keys:
                await self.kube.delete(*key)

        status = await self._status(cr, desired)
        await self.kube.update_status(cr, status)
        return status

    async def teardown(self, name: str) -> int:
        """Delete every child THIS control plane owns for CR ``name``;
        returns count deleted.  Shared by the orphan sweep and the
        api-store's delete handler.  Children stamped by a DIFFERENT
        manager are left alone; unlabeled children (created before
        MANAGER_LABEL existed) are included — an explicit delete of this
        name must not leak pre-upgrade workloads.  (The background orphan
        sweep stays conservative and never touches unlabeled children;
        reconcile re-applies labels, so legacy children of live CRs adopt
        on the first pass.)"""
        count = 0
        for kind in self.CHILD_KINDS:
            for m in await self.kube.list(kind, label=(OWNER_LABEL, name)):
                mgr = (m["metadata"].get("labels") or {}).get(MANAGER_LABEL)
                if mgr is not None and mgr != self.manager:
                    continue
                await self.kube.delete(*_kind_name(m))
                count += 1
        return count

    async def _status(self, cr, desired) -> Dict[str, Any]:
        name = cr["metadata"]["name"]
        ready, total = 0, 0
        services = []
        observed = {}
        for kind in ("Deployment", "StatefulSet"):
            for m in await self.kube.list(kind, label=(OWNER_LABEL, name)):
                observed[_kind_name(m)] = m
        for m in desired:
            if m["kind"] not in ("Deployment", "StatefulSet"):
                continue
            total += 1
            cur = observed.get(_kind_name(m)) or {}
            want = (m.get("spec") or {}).get("replicas", 1)
            have = (cur.get("status") or {}).get("readyReplicas", 0)
            ok = have >= want
            ready += bool(ok)
            services.append(
                {"name": m["metadata"]["name"], "ready": have, "want": want}
            )
        return {
            "observedGeneration": cr["metadata"].get("generation", 0),
            "phase": "Ready" if ready == total else "Progressing",
            "readyServices": ready,
            "totalServices": total,
            "services": services,
        }

    CR_KIND = "DynamoTpuDeployment"

    async def run_pass(self) -> None:
        """One level-triggered pass: list CRs, reconcile each, sweep."""
        crs = await self.kube.list(self.CR_KIND)
        for cr in crs:
            try:
                await self.reconcile(cr)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "reconcile failed for %s", cr["metadata"]["name"]
                )
        await self.sweep_orphans({c["metadata"]["name"] for c in crs})

    async def run(self, poll_interval: float = 10.0) -> None:
        """Watch-triggered, level-driven loop (the controller-runtime
        shape): a pass runs immediately after any CR event, with
        ``poll_interval`` as the periodic resync (watches can silently go
        stale; the resync also drives child-drift repair, which CR events
        alone cannot see).  Clients without a watch (or when the watch
        errors — RBAC, old API server) degrade to pure polling."""
        watch = getattr(self.kube, "watch", None)
        wake = asyncio.Event()
        watcher: Optional[asyncio.Task] = None
        if watch is not None:

            async def pump() -> None:
                while True:
                    try:
                        async for _event in watch(self.CR_KIND):
                            wake.set()
                        # Clean end-of-stream (server-side watch timeout, or
                        # an intermediary that closes long responses): treat
                        # it as a resync point, NEVER a tight restart loop.
                        wake.set()
                        await asyncio.sleep(1.0)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — degrade to poll
                        logger.warning(
                            "%s watch unavailable (%s); relying on the "
                            "%.0fs resync", self.CR_KIND, e, poll_interval,
                        )
                        await asyncio.sleep(poll_interval)

            watcher = asyncio.ensure_future(pump())
        try:
            while True:
                # Clear BEFORE the pass: an event arriving mid-pass (which
                # the pass's own LIST may have missed) must trigger the
                # next pass, not wait out a full resync interval.
                wake.clear()
                try:
                    await self.run_pass()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception("controller pass failed")
                try:
                    await asyncio.wait_for(wake.wait(), poll_interval)
                except asyncio.TimeoutError:
                    pass  # periodic resync
        finally:
            if watcher is not None:
                watcher.cancel()

    async def sweep_orphans(self, live_names) -> int:
        """Tear down children whose owner CR is gone — scoped to children
        THIS manager created (MANAGER_LABEL); an api-store's deployments in
        the same namespace carry a different manager value and are never
        swept (r4 advisory).  Returns the number of children deleted."""
        orphaned = set()
        for kind in self.CHILD_KINDS:
            for m in await self.kube.list(
                kind, label=(MANAGER_LABEL, self.manager)
            ):
                owner = (m["metadata"].get("labels") or {}).get(OWNER_LABEL)
                if owner is not None and owner not in live_names:
                    orphaned.add(owner)
        count = 0
        for owner in orphaned:
            count += await self.teardown(owner)
        return count
