"""Deployment-management REST API (the reference's api-store).

Reference counterpart: /root/reference/deploy/dynamo/api-store/
ai_dynamo_store/api/* — a FastAPI CRUD surface over deployment records that
the operator consumes.  Here the records are DynamoTpuDeployment CR dicts
persisted in the hub KV (durable across hub restarts via its snapshot
layer), and the same Reconciler that serves the k8s controller can run
against this store's CRs — deployment management without a k8s control
plane, or as the source feeding one.

Routes (mirroring the reference's shape):
  POST   /api/v1/deployments          create (body = CR spec or full CR)
  GET    /api/v1/deployments          list
  GET    /api/v1/deployments/{name}   fetch (includes last status)
  DELETE /api/v1/deployments/{name}   delete
  GET    /api/v1/deployments/{name}/manifests   rendered children (preview)
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, Optional

from aiohttp import web

from ..labels import safe_key_component
from ..runtime.transports.shard import hub_key
from .renderer import render

logger = logging.getLogger(__name__)

PREFIX = "deployments/"


def deployment_key(name: str) -> str:
    """CR record key for one deployment name (shard-map routed: DYN401)."""
    return hub_key("deployments", name)


def _as_cr(name: str, body: Dict[str, Any]) -> Dict[str, Any]:
    """Accept either a full CR or a bare spec."""
    if "spec" in body:
        cr = dict(body)
        cr.setdefault("apiVersion", "dynamo.tpu.io/v1alpha1")
        cr.setdefault("kind", "DynamoTpuDeployment")
        cr.setdefault("metadata", {})["name"] = name
        return cr
    return {
        "apiVersion": "dynamo.tpu.io/v1alpha1",
        "kind": "DynamoTpuDeployment",
        "metadata": {"name": name},
        "spec": body,
    }


class ApiStore:
    """REST surface over hub-persisted deployment CRs.

    ``hub`` is anything with kv_put/kv_get/kv_get_prefix/kv_delete (the
    runtime hub client or InprocHub).  ``reconciler`` is optional: when
    given, create/delete trigger an immediate reconcile pass.
    """

    def __init__(
        self, hub, reconciler=None, host="127.0.0.1", port=7070, token=None
    ):
        self.hub = hub
        self.reconciler = reconciler
        self.host, self.port = host, port
        # Bearer-token gate (r4 advisory: with --kube this API can
        # create/delete k8s objects, so default to loopback + optional
        # token; None = unauthenticated, for loopback/dev use).
        self.token = token
        middlewares = [self._auth_middleware] if token else []
        self._runner: Optional[web.AppRunner] = None
        self.app = web.Application(middlewares=middlewares)
        self.app.router.add_post("/api/v1/deployments", self._create)
        self.app.router.add_get("/api/v1/deployments", self._list)
        self.app.router.add_get("/api/v1/deployments/{name}", self._get)
        self.app.router.add_delete("/api/v1/deployments/{name}", self._delete)
        self.app.router.add_get(
            "/api/v1/deployments/{name}/manifests", self._manifests
        )

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        import hmac

        # bytes compare: compare_digest raises TypeError on non-ASCII str
        # (a 500 where a 401 belongs).
        got = request.headers.get("Authorization", "").encode()
        want = f"Bearer {self.token}".encode()
        if not hmac.compare_digest(got, want):
            return web.json_response({"error": "unauthorized"}, status=401)
        return await handler(request)

    # ------------------------------------------------------------- handlers
    async def _create(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            return web.json_response({"error": "invalid JSON"}, status=400)
        name = (
            body.get("name")
            or (body.get("metadata") or {}).get("name")
        )
        if not name:
            return web.json_response(
                {"error": "missing deployment name"}, status=400
            )
        try:
            # Deployment names become hub-key components under PREFIX: a
            # name containing '/', whitespace or control chars could
            # escape the store's namespace and shadow another subsystem's
            # keys (dynalint DYN203) — reject at the edge, k8s-style.
            name = safe_key_component(name)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        body.pop("name", None)
        cr = _as_cr(name, body)
        try:
            render(cr)  # validate: reject specs the renderer can't map
        except asyncio.CancelledError:
            raise
        except Exception as e:
            return web.json_response(
                {"error": f"invalid spec: {e}"}, status=400
            )
        existed = await self.hub.kv_get(deployment_key(name)) is not None
        await self.hub.kv_put(deployment_key(name), cr)
        if self.reconciler is not None:
            try:
                status = await self.reconciler.reconcile(cr)
                cr = dict(cr, status=status)
                await self.hub.kv_put(deployment_key(name), cr)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("reconcile on create failed")
        return web.json_response(cr, status=200 if existed else 201)

    async def _list(self, request: web.Request) -> web.Response:
        items = await self.hub.kv_get_prefix(PREFIX)
        return web.json_response({"items": list(items.values())})

    async def _get(self, request: web.Request) -> web.Response:
        cr = await self.hub.kv_get(deployment_key(request.match_info["name"]))
        if cr is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(cr)

    async def _delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        cr = await self.hub.kv_get(deployment_key(name))
        if cr is None:
            return web.json_response({"error": "not found"}, status=404)
        await self.hub.kv_delete(deployment_key(name))
        if self.reconciler is not None:
            try:
                await self.reconciler.teardown(name)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("teardown on delete failed")
        return web.json_response({"deleted": name})

    async def _manifests(self, request: web.Request) -> web.Response:
        cr = await self.hub.kv_get(deployment_key(request.match_info["name"]))
        if cr is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"manifests": render(cr)})

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "ApiStore":
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
            break
        logger.info("api-store on http://%s:%s", self.host, self.port)
        return self

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
