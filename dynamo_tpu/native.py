"""ctypes bridge to the native C++ runtime components (native/*.cc).

Loads ``native/build/libdyn_native.so``, auto-building it with g++ on first
use (the toolchain is guaranteed in the image; pybind11 is not, hence
ctypes — reference counterpart: the PyO3 bindings crate + C API,
lib/bindings/{python,c}).  Everything here degrades gracefully: if the
library can't build/load, callers fall back to pure Python (set
``DYN_NATIVE=0`` to force that).

Surface:
- ``hash_blocks(tokens, block_size, parent_hash)`` — chained block hashing
  (native fast path for dynamo_tpu.tokens; bit-identical to xxhash path).
- ``KvEventShim`` — drain side of the C ABI event ring
  (dyn_kv_publish_stored/removed from any engine → KvCacheEvent objects).
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
# DYN_NATIVE_LIB overrides the library (e.g. the `make sanitize` ASan build).
_SO_PATH = os.environ.get(
    "DYN_NATIVE_LIB",
    os.path.join(_NATIVE_DIR, "build", "libdyn_native.so"),
)

_lib = None
_lib_lock = threading.Lock()
_load_failed = False
_build_thread: Optional[threading.Thread] = None


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as exc:
        logger.warning("native build failed (falling back to python): %s", exc)
        return False


def _build_and_load() -> None:
    global _lib, _load_failed
    if not os.path.exists(_SO_PATH):
        if "DYN_NATIVE_LIB" in os.environ:
            # An explicit override must never silently fall back to the
            # pure-Python path (e.g. a sanitizer run that tests nothing) —
            # and auto-build only knows the default target.
            raise FileNotFoundError(
                f"DYN_NATIVE_LIB={_SO_PATH} does not exist; build it first "
                "(e.g. `make -C native sanitize`)"
            )
        if not _build():
            _load_failed = True
            return
    _load()


def get_lib(wait: bool = False) -> Optional[ctypes.CDLL]:
    """The loaded native library, or None if unavailable/disabled.

    The g++ build runs on a background thread: with ``wait=False`` (the hot
    path) callers get None — and fall back to pure Python — until the build
    lands, instead of stalling the event loop for the compile.
    """
    global _build_thread, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get("DYN_NATIVE", "1") == "0":
        _load_failed = True
        return None
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if _build_thread is None:
            _build_thread = threading.Thread(target=_build_and_load, daemon=True)
            _build_thread.start()
    if wait:
        _build_thread.join(timeout=150)
    return _lib


def _load() -> None:
    """Load + bind the shared library (runs on the build thread)."""
    global _lib, _load_failed
    with _lib_lock:
        if _lib is not None or _load_failed:
            return
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as exc:
            logger.warning("native load failed: %s", exc)
            _load_failed = True
            return
        lib.dyn_xxh64.restype = ctypes.c_uint64
        lib.dyn_xxh64.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.dyn_hash_blocks.restype = ctypes.c_uint64
        lib.dyn_hash_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.dyn_kv_init.restype = ctypes.c_int
        lib.dyn_kv_init.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.dyn_kv_publish_stored.restype = ctypes.c_int
        lib.dyn_kv_publish_stored.argtypes = [
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
        ]
        lib.dyn_kv_publish_removed.restype = ctypes.c_int
        lib.dyn_kv_publish_removed.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint32,
        ]
        lib.dyn_kv_publish_cleared.restype = ctypes.c_int
        lib.dyn_kv_drain.restype = ctypes.c_int64
        lib.dyn_kv_drain.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.dyn_kv_dropped.restype = ctypes.c_uint64
        _lib = lib


def available() -> bool:
    """True once the library is built+loaded (blocks for the build)."""
    return get_lib(wait=True) is not None


def hash_blocks(
    tokens, block_size: int, parent_hash: int = 0
) -> Optional[List[Tuple[int, int]]]:
    """Native chained hashing of complete blocks: [(local, seq), ...].

    Returns None when the native library is unavailable.
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(tokens)
    n_blocks = n // block_size
    if n_blocks == 0:
        return []
    arr = (ctypes.c_uint32 * n)(*tokens)
    out_local = (ctypes.c_uint64 * n_blocks)()
    out_seq = (ctypes.c_uint64 * n_blocks)()
    wrote = lib.dyn_hash_blocks(
        arr, n, block_size, parent_hash & 0xFFFFFFFFFFFFFFFF, out_local, out_seq
    )
    return [(out_local[i], out_seq[i]) for i in range(wrote)]


class KvEventShim:
    """Drain side of the C-ABI event ring (external engine integration)."""

    _HEADER = struct.Struct("<BQQI")

    def __init__(self, worker_id: int = 0, capacity: int = 65536):
        lib = get_lib(wait=True)
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        rc = lib.dyn_kv_init(worker_id, capacity)
        if rc != 0:
            raise RuntimeError(f"dyn_kv_init failed: {rc}")
        self._buf = ctypes.create_string_buffer(1 << 20)

    def drain(self) -> List["KvCacheEvent"]:
        from .llm.kv_router.protocols import (
            KvCacheEvent,
            KvCacheStoredBlockData,
        )

        n = self._lib.dyn_kv_drain(self._buf, len(self._buf))
        events: List[KvCacheEvent] = []
        data = self._buf.raw[:n]
        off = 0
        while off < len(data):
            etype, event_id, parent, count = self._HEADER.unpack_from(data, off)
            off += self._HEADER.size
            pairs = [
                struct.unpack_from("<QQ", data, off + 16 * i) for i in range(count)
            ]
            off += 16 * count
            if etype == 1:
                events.append(
                    KvCacheEvent.stored(
                        event_id,
                        parent if parent != 0 else None,
                        [KvCacheStoredBlockData(s, t) for s, t in pairs],
                    )
                )
            elif etype == 2:
                events.append(KvCacheEvent.removed(event_id, [s for s, _ in pairs]))
            else:
                events.append(KvCacheEvent(event_id, None))
        return events

    @property
    def dropped(self) -> int:
        return self._lib.dyn_kv_dropped()

    def close(self) -> None:
        self._lib.dyn_kv_shutdown()
