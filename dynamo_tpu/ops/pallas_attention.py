"""Pallas TPU decode kernel: paged attention for Sq=1 continuous batching.

The hot op of the decode loop (SURVEY §7 stage 4): each sequence reads its
own scattered KV pages.  The XLA reference path (ops/attention.py) gathers
``max_blocks`` pages per sequence through HBM into one dense tensor; this
kernel instead streams pages through VMEM with flash-style online softmax,
one (batch row, kv head, page) grid step at a time, with the page table as
scalar-prefetch so the DMA pipeline knows each page's address up front
(pallas_guide: PrefetchScalarGridSpec + double-buffering pattern).

Layout contract (shared with jax's built-in paged_attention, so both are
interchangeable backends behind ops.attention.decode_attention):
  q        [B, kv_heads, group, head_dim]
  k_pages  [kv_heads, num_pages, page_size, head_dim]
  lengths  i32[B]  (context length per row, 0 = padding row)
  page_tables i32[B, pages_per_seq]
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import on_tpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_tables_ref,  # i32[B, PPS]
    lengths_ref,  # i32[B]
    # blocks
    q_ref,  # [1, 1, G, hd]
    k_ref,  # [1, 1, ps, hd]
    v_ref,  # [1, 1, ps, hd]
    o_ref,  # [1, 1, G, hd]
    # scratch
    m_ref,  # f32[G, 128]
    l_ref,  # f32[G, 128]
    acc_ref,  # f32[G, hd]
    *,
    page_size: int,
    scale: float,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = lengths_ref[b]

    @pl.when(j * page_size < length)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [ps, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [G, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [G, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G, ps]
        l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, :1] = m_new

    @pl.when(j == n_pages - 1)
    def _finish():
        denom = jnp.where(l_ref[:, :1] == 0.0, 1.0, l_ref[:, :1])
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size",))
def paged_decode_attention(
    q: jnp.ndarray,  # [B, KV, G, hd]
    k_pages: jnp.ndarray,  # [KV, NP, ps, hd]
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,  # i32[B]
    page_tables: jnp.ndarray,  # i32[B, PPS]
    *,
    page_size: int,
) -> jnp.ndarray:
    """Returns [B, KV, G, hd] attention output (our custom kernel)."""
    B, KV, G, hd = q.shape
    pps = page_tables.shape[1]
    scale = hd**-0.5

    kernel = functools.partial(_decode_kernel, page_size=page_size, scale=scale)

    grid = (B, KV, pps)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, G, hd), lambda b, h, j, pt, ln: (b, h, 0, 0)),
                pl.BlockSpec(
                    (1, 1, page_size, hd), lambda b, h, j, pt, ln: (h, pt[b, j], 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, page_size, hd), lambda b, h, j, pt, ln: (h, pt[b, j], 0, 0)
                ),
            ],
            out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h, j, pt, ln: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=not on_tpu(),
    )(page_tables, lengths, q, k_pages, v_pages)
