"""Fused-dequant ragged paged DECODE attention — our own Pallas TPU kernel.

The stock ``jax.experimental.pallas.ops.tpu.ragged_paged_attention`` kernel
only CASTS quantized (int8/fp8) KV pages up to the query dtype and never
applies ``kv_scale`` in-kernel, so the model folds dequant algebraically
around the call (q pre-scaled, output post-scaled — models/llama.py) and
the decode step's dominant HBM stream still rides a generic mixed
prefill/decode kernel.  BENCH_r05 put full-model decode at 54.89% MFU with
a ~12 ms/step non-bandwidth residual; this kernel attacks exactly that
residual for the one shape the fused decode program dispatches — ONE query
token per row, identity row map (``ragged_decode_attention``):

1. **Fused dequant**: int8/fp8 KV pages are DMA'd quantized and scaled by
   ``kv_scale`` in VMEM right before the QK/AV dots — the KV stream is
   read from HBM ONCE at 1 byte/value and never materialized dequantized.
   The scale is an SMEM scalar operand, so per-layer TRACED calibration
   scales work natively (the stock kernel's k_scale/v_scale must be static
   floats, which is why dequant lived outside it).
2. **Split-KV grid** (Flash-Decoding, Dao et al. 2023): long KV chains
   split across grid programs, each producing an unnormalized partial
   (o, m, l); a log-sum-exp combine reduces the splits.  At decode's
   q_len=1 shapes one program per row leaves the chip idle — the split
   axis restores parallel work.
3. **Double-buffered page fetch**: pages DMA HBM→VMEM via
   ``make_async_copy`` two compute-blocks deep, so the (bandwidth-bound)
   page stream overlaps the QK/AV compute (PagedAttention page tables,
   vLLM SOSP 2023 — the repo's existing paged layout).

Contract: identical inputs/outputs to ``ragged_decode_attention``'s XLA
fallback (the bit-exactness oracle) — [S, H, D] out, zeros for rows past
``num_seqs``.  Interpret mode (CPU) runs the same kernel for tier-1 parity
gates; compiled mode is TPU-only.  Selection: DYN_DECODE_KERNEL /
EngineConfig.decode_kernel (ops/ragged_attention.py resolve_decode_kernel).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

NEG_INF = -1e30  # matches ops/ragged_attention.py (bit-compatible masking)

# ------------------------------------------------------------------ tuning
# Block-hint resolution order (every knob): explicit env var > tuned-table
# entry installed at engine init (tools/tune_decode.py) > built-in default.
# The table maps "model|b<batch>|ps<page_size>" -> {nq, nkv_mb, splits,
# ppcb, ...}; engine init installs its own geometry's entry so serving
# picks up sweeps without env plumbing.

_ACTIVE_HINTS: Optional[Dict[str, Any]] = None
_ACTIVE_KEY: Optional[str] = None


def default_table_path() -> str:
    return os.environ.get(
        "DYN_DECODE_TUNE_TABLE",
        os.path.expanduser("~/.cache/dynamo_tpu/decode_tune.json"),
    )


def hint_key(model: str, batch: int, page_size: int) -> str:
    """Tuned-table key for an engine geometry.  Batch is the decode
    dispatch's ROW count (cfg.max_batch — fused decode always dispatches
    full-width), page_size the KV block size."""
    return f"{model}|b{int(batch)}|ps{int(page_size)}"


def load_tuned_table(path: Optional[str] = None) -> Dict[str, Any]:
    p = path or default_table_path()
    try:
        with open(p) as f:
            t = json.load(f)
        return t if isinstance(t, dict) else {}
    except (OSError, ValueError):
        return {}


def install_tuned_hints(
    model: str, batch: int, page_size: int, path: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """Engine-init hook: load the tuned entry for this geometry (None +
    built-in defaults when no table/key matches).  Never raises — a
    corrupt table must not take a worker down.

    Entries recorded on a DIFFERENT backend are refused: a CPU
    interpret-mode sweep's "winners" are meaningless timings, and
    silently serving a TPU with them would be exactly the perf
    regression the tuner exists to prevent.  (Hand-written entries
    without a ``backend`` field install anywhere.)

    The installed entry is process-global, resolved at TRACE time
    (resolve_hint).  Last install wins — safe because every engine warms
    up (compiling all its programs) immediately after its own install,
    and the zero-new-compiles gate means no decode shape retraces later.
    Two engines CONSTRUCTED concurrently in one process with different
    geometries could cross hints; construct sequentially."""
    global _ACTIVE_HINTS, _ACTIVE_KEY
    key = hint_key(model, batch, page_size)
    entry = load_tuned_table(path).get(key)
    if isinstance(entry, dict):
        rec = entry.get("backend")
        here = jax.default_backend()
        if rec is not None and rec != here:
            logger.warning(
                "decode kernel: ignoring tuned hints for %s — recorded on "
                "%r, running on %r (re-sweep with tools/tune_decode.py)",
                key, rec, here,
            )
            entry = None
    _ACTIVE_HINTS = dict(entry) if isinstance(entry, dict) else None
    _ACTIVE_KEY = key
    if _ACTIVE_HINTS:
        logger.info("decode kernel: tuned hints for %s: %s", key, _ACTIVE_HINTS)
    return _ACTIVE_HINTS


def clear_tuned_hints() -> None:
    global _ACTIVE_HINTS, _ACTIVE_KEY
    _ACTIVE_HINTS = None
    _ACTIVE_KEY = None


def active_hints() -> Optional[Dict[str, Any]]:
    return _ACTIVE_HINTS


def resolve_hint(env_name: str, tuned_key: str, default: int) -> int:
    """env var > installed tuned entry > default (all ints)."""
    v = os.environ.get(env_name)
    if v is not None:
        return int(v)
    if _ACTIVE_HINTS is not None and tuned_key in _ACTIVE_HINTS:
        return int(_ACTIVE_HINTS[tuned_key])
    return default


def pages_per_vmem_budget(
    budget_bytes: int, page_size: int, kv2: int, head_dim: int, itemsize: int
) -> int:
    """Pages whose DOUBLE-BUFFERED scratch fits a VMEM byte budget — the
    one copy of the formula behind both the stock kernel's nkv hint
    (ragged_attention._decode_block_hints, itemsize 2: its VMEM working
    set is in the cast-up bf16 compute dtype regardless of page dtype)
    and the fused kernel's ppcb default (the PAGE dtype's width: pages
    land in scratch quantized, so int8 packs ~2x the bf16 block — the
    fused path's bandwidth win)."""
    return max(
        1, budget_bytes // max(1, 2 * page_size * kv2 * head_dim * itemsize)
    )


def _default_ppcb(page_size: int, kv2: int, head_dim: int, itemsize: int) -> int:
    """Fused-kernel pages per compute block from the DYN_DECODE_NKV_MB
    budget (default 4MB) at the page dtype's width."""
    budget = resolve_hint("DYN_DECODE_NKV_MB", "nkv_mb", 4) << 20
    return pages_per_vmem_budget(budget, page_size, kv2, head_dim, itemsize)


# ------------------------------------------------------------------ kernel


def _make_kernel(
    *,
    sm_scale: float,
    num_kv: int,
    group: int,
    head_dim: int,
    page_size: int,
    pages_per_seq: int,
    split_pages: int,
    ppcb: int,
):
    """Build the kernel body for a static geometry.

    Grid (S, J): program (s, j) computes row ``s``'s attention over KV
    split ``j`` (pages [j*split_pages, (j+1)*split_pages)) and writes an
    UNNORMALIZED partial (o, m, l) — combined host-side by LSE.
    """
    C = ppcb * page_size  # context positions per compute block

    def kernel(
        # scalar prefetch (SMEM)
        kv_lens_ref,  # [S] int32
        page_indices_ref,  # [S, PP] int32
        num_seqs_ref,  # [1] int32
        # operands
        q_ref,  # [1, H, D] VMEM (row s)
        pages_ref,  # [P, ps, 2KV, D] HBM/ANY — DMA'd manually
        scale_ref,  # [1, 1] f32 SMEM — kv_scale (traced OK)
        # outputs (VMEM blocks at (s, j))
        o_ref,  # [1, 1, H, D] f32 — unnormalized sum(p·V)
        m_ref,  # [1, 1, H, 1] f32 — split max
        l_ref,  # [1, 1, H, 1] f32 — split sum(exp)
        # scratch
        kv_buf,  # [2, ppcb, ps, 2KV, D] pages dtype
        sems,  # DMA semaphores (2,)
    ):
        s = pl.program_id(0)
        j = pl.program_id(1)
        kv_len = kv_lens_ref[s]
        base_page = j * split_pages
        # Pages this split actually covers (tail splits truncate; rows
        # shorter than the split's base contribute nothing).
        row_pages = pl.cdiv(kv_len, page_size)
        pages_here = jnp.clip(row_pages - base_page, 0, split_pages)
        # The split's coverage END, not just kv_len: the last compute
        # block of a split can reach past split_pages (ppcb granularity),
        # and without this cap those positions would be counted by BOTH
        # this split and the next — a double-count the LSE combine cannot
        # undo.
        split_end = jnp.minimum(kv_len, (base_page + split_pages) * page_size)
        active = (s < num_seqs_ref[0]) & (kv_len > 0) & (pages_here > 0)

        # Inactive programs still own their out blocks: neutral partials
        # (o=0, m=NEG_INF, l=0) vanish in the LSE combine.
        o_ref[0, 0] = jnp.zeros((num_kv * group, head_dim), jnp.float32)
        m_ref[0, 0] = jnp.full((num_kv * group, 1), NEG_INF, jnp.float32)
        l_ref[0, 0] = jnp.zeros((num_kv * group, 1), jnp.float32)

        def fetch(block, slot, start):
            # One DMA per page: page ids are arbitrary (PagedAttention
            # indirection), so the block's pages can't ride one stride.
            # wait() recreates the descriptor — standard Pallas pattern;
            # the semaphore accounts per-copy.
            for t in range(ppcb):
                idx = base_page + block * ppcb + t
                idx = jnp.clip(idx, 0, pages_per_seq - 1)
                pid = page_indices_ref[s, idx]
                dma = pltpu.make_async_copy(
                    pages_ref.at[pid], kv_buf.at[slot, t], sems.at[slot]
                )
                if start:
                    dma.start()
                else:
                    dma.wait()

        @pl.when(active)
        def _():
            nblocks = pl.cdiv(pages_here, ppcb)
            fetch(0, 0, start=True)
            scale = scale_ref[0, 0]

            def block_step(b, carry):
                slot = jax.lax.rem(b, 2)

                @pl.when(b + 1 < nblocks)
                def _():
                    fetch(b + 1, jax.lax.rem(b + 1, 2), start=True)

                fetch(b, slot, start=False)
                buf = kv_buf[slot].reshape(C, 2 * num_kv, head_dim)
                # Fused dequant: the ONLY f32 materialization of this KV
                # block is here in VMEM, one compute block at a time.
                kvf = buf.astype(jnp.float32) * scale
                pos = (base_page + b * ppcb) * page_size + (
                    jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
                )
                mask = pos < split_end  # [1, C]
                out = []
                for h in range(num_kv):
                    m_h, l_h, acc_h = carry[3 * h], carry[3 * h + 1], carry[3 * h + 2]
                    k_h = kvf[:, 2 * h, :]  # [C, D]
                    v_h = kvf[:, 2 * h + 1, :]
                    qf = (
                        q_ref[0, h * group : (h + 1) * group, :].astype(
                            jnp.float32
                        )
                        * sm_scale
                    )  # [G, D]
                    logits = jax.lax.dot_general(
                        qf,
                        k_h,
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )  # [G, C]
                    logits = jnp.where(mask, logits, NEG_INF)
                    m_new = jnp.maximum(
                        m_h, jnp.max(logits, axis=1, keepdims=True)
                    )  # [G, 1]
                    # Mask the exp explicitly: a fully-masked block has
                    # m_new == m_h and exp(NEG_INF - m) can round to a
                    # nonzero subnormal only through the mask, never here.
                    p = jnp.where(mask, jnp.exp(logits - m_new), 0.0)
                    alpha = jnp.exp(m_h - m_new)  # [G, 1]
                    l_new = alpha * l_h + jnp.sum(p, axis=1, keepdims=True)
                    acc_new = alpha * acc_h + jax.lax.dot_general(
                        p,
                        v_h,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )  # [G, D]
                    out.extend((m_new, l_new, acc_new))
                return tuple(out)

            init = []
            for _h in range(num_kv):
                init.extend(
                    (
                        jnp.full((group, 1), NEG_INF, jnp.float32),
                        jnp.zeros((group, 1), jnp.float32),
                        jnp.zeros((group, head_dim), jnp.float32),
                    )
                )
            final = jax.lax.fori_loop(0, nblocks, block_step, tuple(init))
            m_all = jnp.concatenate(
                [final[3 * h] for h in range(num_kv)], axis=0
            )  # [H, 1]
            l_all = jnp.concatenate(
                [final[3 * h + 1] for h in range(num_kv)], axis=0
            )
            o_all = jnp.concatenate(
                [final[3 * h + 2] for h in range(num_kv)], axis=0
            )  # [H, D]
            o_ref[0, 0] = o_all
            m_ref[0, 0] = m_all
            l_ref[0, 0] = l_all

    return kernel


def fused_decode_attention(
    q: jnp.ndarray,  # [S, num_heads, head_dim] — ONE query token per row
    pages: jnp.ndarray,  # [num_pages, page_size, 2*kv_heads, head_dim]
    kv_lens: jnp.ndarray,  # [S] int32 context length per row
    page_indices: jnp.ndarray,  # [S, pages_per_seq] int32
    num_seqs: jnp.ndarray,  # [1] int32 valid rows
    *,
    sm_scale: float,
    kv_scale=None,  # None | float | traced [] scalar — applied IN-KERNEL
    num_kv_splits: Optional[int] = None,
    pages_per_block: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Host wrapper: fused-dequant split-KV decode attention + LSE combine.

    Knobs (env > tuned table > default; tools/tune_decode.py sweeps them):
    - ``DYN_DECODE_SPLITS`` / splits: KV-split grid width (0 = auto:
      enough splits to cover pages_per_seq at one compute block each,
      capped at 8).
    - ``DYN_DECODE_FUSED_PPCB`` / ppcb: pages per compute block (default
      from the DYN_DECODE_NKV_MB VMEM budget at the PAGE dtype's width —
      int8 pages pack ~2x the bf16 block).
    """
    S, H, D = q.shape
    P, ps, KV2, _ = pages.shape
    KV = KV2 // 2
    G = H // KV
    PP = page_indices.shape[1]

    ppcb = pages_per_block or resolve_hint(
        "DYN_DECODE_FUSED_PPCB",
        "ppcb",
        _default_ppcb(ps, KV2, D, pages.dtype.itemsize),
    )
    ppcb = max(1, min(ppcb, PP))
    splits = num_kv_splits or resolve_hint("DYN_DECODE_SPLITS", "splits", 0)
    if splits <= 0:  # auto: one compute block per split, at most 8 splits
        splits = max(1, min(8, pl.cdiv(PP, ppcb)))
    splits = min(splits, pl.cdiv(PP, ppcb))
    split_pages = pl.cdiv(PP, splits)
    splits = pl.cdiv(PP, split_pages)  # drop now-empty tail splits

    if interpret is None:
        from .ragged_attention import on_tpu

        interpret = not on_tpu()

    kernel = _make_kernel(
        sm_scale=sm_scale,
        num_kv=KV,
        group=G,
        head_dim=D,
        page_size=ps,
        pages_per_seq=PP,
        split_pages=split_pages,
        ppcb=ppcb,
    )
    scale_arr = jnp.asarray(
        1.0 if kv_scale is None else kv_scale, jnp.float32
    ).reshape(1, 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, splits),
        in_specs=[
            pl.BlockSpec(
                (1, H, D), lambda s, j, *_: (s, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),  # pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.SMEM),  # kv_scale
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, H, D),
                lambda s, j, *_: (s, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, H, 1),
                lambda s, j, *_: (s, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, H, 1),
                lambda s, j, *_: (s, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, ppcb, ps, KV2, D), pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    o_part, m_part, l_part = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((S, splits, H, D), jnp.float32),
            jax.ShapeDtypeStruct((S, splits, H, 1), jnp.float32),
            jax.ShapeDtypeStruct((S, splits, H, 1), jnp.float32),
        ),
        compiler_params=pltpu.TPUCompilerParams(
            # Same headroom as the stock path: the default 16MB scoped
            # budget is a compiler default, not the hardware ceiling.
            vmem_limit_bytes=64 << 20,
        ),
        interpret=interpret,
    )(
        jnp.asarray(kv_lens, jnp.int32),
        jnp.asarray(page_indices, jnp.int32),
        jnp.asarray(num_seqs, jnp.int32),
        q,
        pages,
        scale_arr,
    )
    # Flash-Decoding LSE combine over the split axis.  All-masked rows
    # (padding / kv_len 0) have every m == NEG_INF and every l == 0:
    # alpha == 1 but o == 0, so out == 0 — matching the XLA oracle.
    m = m_part[..., 0]  # [S, J, H]
    l = l_part[..., 0]
    m_max = jnp.max(m, axis=1)  # [S, H]
    alpha = jnp.exp(m - m_max[:, None, :])  # [S, J, H]
    l_tot = jnp.sum(alpha * l, axis=1)  # [S, H]
    o_tot = jnp.sum(alpha[..., None] * o_part, axis=1)  # [S, H, D]
    out = o_tot / (l_tot[..., None] + 1e-30)
    return out.astype(q.dtype)
