"""TPU compute ops: paged-KV attention, RoPE, sampling, block copies.

Reference counterpart: the only kernel the reference owns is
lib/llm/src/kernels/block_copy.cu (KV offload copies); attention kernels live
inside vLLM.  Here the whole compute path is native: XLA-fused reference
implementations first, Pallas kernels for the hot paths.
"""

from .ragged_attention import (  # noqa: F401
    on_tpu,
    ragged_attention,
    write_kv_ragged,
)
from .rope import apply_rope, rope_frequencies  # noqa: F401
from .sampling import sample_tokens  # noqa: F401
