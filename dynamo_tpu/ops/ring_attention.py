"""Ring attention: causal self-attention sequence-parallel over a mesh axis.

The reference has NO long-context parallelism (SURVEY §5 — it offloads long
prefills to dedicated workers and chunks them); this is the TPU-native
capability the north-star configs need: shard a long prompt's tokens over
the ``sp`` mesh axis, keep Q resident per shard, and rotate K/V blocks
around the ring with ``lax.ppermute`` while accumulating an online softmax —
compute and memory per chip stay O(T/sp · T), K/V movement rides ICI
neighbor-to-neighbor (the Ring Attention construction of Liu et al. 2023,
built here from scratch on XLA collectives).

Layout contract: shard i of the ``sp`` axis owns the CONTIGUOUS token chunk
[i*C, (i+1)*C) of a length sp*C prompt (padding tokens at the tail of the
last shards are masked by ``valid_len``).  Causality falls out of chunk
indices: a shard attends fully to earlier chunks, causally within its own,
not at all to later ones — those ring rounds still run (uniform program per
shard) but are masked.

Use ``ring_attention`` inside shard_map (see tests/test_ring_attention.py)
or through ``parallel.mesh`` sp-aware forward paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_attend(q, k, v, q_pos, k_pos, valid_len, sm_scale):
    """Partial (unnormalized) attention of q against one K/V chunk.

    q: [C, KV, G, D] f32; k/v: [C, KV, D] f32.
    Returns (o_part [C, KV, G, D], m [C, KV, G], l [C, KV, G]) — the online
    softmax partials (running max, sum of exp) for this chunk.
    """
    scores = jnp.einsum("qkgd,lkd->kgql", q, k) * sm_scale  # [KV, G, C, C]
    mask = (k_pos[None, :] <= q_pos[:, None]) & (k_pos[None, :] < valid_len)
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [KV, G, C]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [KV, G, C]
    o = jnp.einsum("kgql,lkd->qkgd", p, v)  # [C, KV, G, D]
    # transpose m/l to [C, KV, G] to match o's leading token dim
    return o, m.transpose(2, 0, 1), l.transpose(2, 0, 1)


def ring_attention(
    q: jnp.ndarray,  # [C, H, D] this shard's queries (f32/bf16)
    k: jnp.ndarray,  # [C, KV, D] this shard's keys
    v: jnp.ndarray,  # [C, KV, D] this shard's values
    valid_len: jnp.ndarray,  # [] int32 — global prompt length (pre-padding)
    *,
    axis_name: str = "sp",
    sm_scale: float,
) -> jnp.ndarray:
    """Causal ring attention; call under shard_map with ``axis_name`` bound.

    Returns [C, H, D] attention outputs for this shard's tokens.
    """
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    C, H, D = q.shape
    KV = k.shape[1]
    G = H // KV

    qf = q.astype(jnp.float32).reshape(C, KV, G, D)
    q_pos = idx * C + jnp.arange(C, dtype=jnp.int32)

    o = jnp.zeros((C, KV, G, D), jnp.float32)
    m = jnp.full((C, KV, G), NEG_INF, jnp.float32)
    l = jnp.zeros((C, KV, G), jnp.float32)
    kv = (k.astype(jnp.float32), v.astype(jnp.float32))

    # sp is static (mesh shape), so the ring unrolls at trace time; each
    # round overlaps the neighbor ppermute with the chunk's compute.
    for r in range(sp):
        src = (idx - r) % sp  # whose chunk we hold this round
        k_pos = src * C + jnp.arange(C, dtype=jnp.int32)
        o_c, m_c, l_c = _chunk_attend(
            qf, kv[0], kv[1], q_pos, k_pos, valid_len, sm_scale
        )
        # online softmax merge
        m_new = jnp.maximum(m, m_c)
        # guard fully-masked chunks (m_c == NEG_INF): exp underflows to 0
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_c - m_new)
        o = o * alpha[..., None] + o_c * beta[..., None]
        l = l * alpha + l_c * beta
        m = m_new
        if r != sp - 1:
            perm = [(j, (j + 1) % sp) for j in range(sp)]
            kv = lax.ppermute(kv, axis_name, perm)

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(C, H, D).astype(q.dtype)


def ring_attention_sharded(q, k, v, valid_len, mesh, *, sm_scale):
    """Convenience wrapper: shard_map ``ring_attention`` over mesh axis "sp".

    q/k/v are GLOBAL [T, H|KV, D] arrays (T divisible by the sp size);
    tokens shard over "sp", heads stay local (combine with "tp" by sharding
    the head axis in the caller's specs)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    fn = shard_map(
        lambda q_, k_, v_, n_: ring_attention(
            q_, k_, v_, n_[0], sm_scale=sm_scale
        ),
        mesh=mesh,
        in_specs=(P("sp"), P("sp"), P("sp"), P()),
        out_specs=P("sp"),
        check_vma=False,
    )
    return fn(q, k, v, jnp.asarray([valid_len], jnp.int32))
