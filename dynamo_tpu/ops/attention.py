"""Paged-KV attention: the engine's core op.

The KV cache for each layer is a head-major slab of token slots
``[kv_heads, num_slots, head_dim]`` (num_slots = num_blocks * block_size) —
the TPU translation of the reference's slab-per-layer block storage
(lib/llm/src/kv/layer.rs:100-772).  Head-major order makes each head's pages
contiguous, which is what both the Pallas decode kernel and jax's built-in
paged_attention stream (the slab reshapes to pages
``[kv_heads, num_pages, page_size, head_dim]`` for free).  Sequences own
*blocks* of ``block_size`` consecutive slots; a block table maps each
sequence's logical block index to its physical block id.  Because attention
addresses whole blocks, any physical block order works — allocation never
moves data.

Two execution paths behind one contract:
- ``paged_attention`` — XLA reference: gather the sequence's slots, mask,
  flash-style softmax in f32.  Used for prefill (Sq = padded bucket) on all
  platforms and for decode on CPU.
- ``decode_attention`` — dispatcher for the Sq=1 decode hot path: custom
  Pallas kernel (ops/pallas_attention.py), jax's built-in paged_attention,
  or the XLA path, per engine config.

Static shapes everywhere: padded queries use slot -1 (dropped scatter),
padded context is masked by ``context_lens``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def on_tpu() -> bool:
    """True when default execution actually lands on a TPU — accounts for a
    jax_default_device override (tests pin CPU while a TPU plugin is still
    registered as the default backend)."""
    if jax.default_backend() != "tpu":
        return False
    dev = jax.config.jax_default_device
    return dev is None or getattr(dev, "platform", None) == "tpu"


def write_kv(
    k_cache: jnp.ndarray,  # [kv_heads, num_slots, head_dim]
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, Sq, kv_heads, head_dim]
    v_new: jnp.ndarray,
    slot_mapping: jnp.ndarray,  # [B, Sq] int32; -1 = padding (write dropped)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V rows into their cache slots (out-of-range = dropped)."""
    flat_slots = slot_mapping.reshape(-1)
    # Negative indices would wrap; remap them past the end so mode="drop"
    # discards padding writes instead of clobbering the last slots.
    flat_slots = jnp.where(flat_slots < 0, k_cache.shape[1], flat_slots)
    kv_heads, _, head_dim = k_cache.shape
    k_flat = k_new.transpose(2, 0, 1, 3).reshape(kv_heads, -1, head_dim)
    v_flat = v_new.transpose(2, 0, 1, 3).reshape(kv_heads, -1, head_dim)
    k_cache = k_cache.at[:, flat_slots].set(k_flat.astype(k_cache.dtype), mode="drop")
    v_cache = v_cache.at[:, flat_slots].set(v_flat.astype(v_cache.dtype), mode="drop")
    return k_cache, v_cache


def gather_context_slots(
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 physical block ids
    block_size: int,
) -> jnp.ndarray:
    """[B, max_blocks*block_size] physical slot index of each context position."""
    max_blocks = block_tables.shape[-1]
    ctx = jnp.arange(max_blocks * block_size, dtype=jnp.int32)
    return block_tables[:, ctx // block_size] * block_size + ctx % block_size


def paged_attention(
    q: jnp.ndarray,  # [B, Sq, heads, head_dim]
    k_cache: jnp.ndarray,  # [kv_heads, num_slots, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] total valid context tokens (incl. new)
    positions: jnp.ndarray,  # [B, Sq] global position of each query token
    block_size: int,
    scale: float | None = None,
) -> jnp.ndarray:
    """Causal attention of queries against their sequence's paged context
    (XLA gather path).

    Context position j (< context_lens[b]) is visible to query token i iff
    j <= positions[b, i].  New tokens' K/V must already be in the cache
    (write_kv runs first), so prefill attends to reused prefix + itself with
    the same gather.
    """
    B, Sq, H, D = q.shape
    KV = k_cache.shape[0]
    groups = H // KV
    if scale is None:
        scale = D**-0.5

    slots = gather_context_slots(block_tables, block_size)  # [B, L]
    L = slots.shape[-1]
    k = k_cache[:, slots]  # [KV, B, L, D]
    v = v_cache[:, slots]

    qf = q.astype(jnp.float32).reshape(B, Sq, KV, groups, D) * scale
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqkgd,kbld->bkgql", qf, kf)  # [B, KV, G, Sq, L]

    ctx = jnp.arange(L, dtype=jnp.int32)
    valid = ctx[None, :] < context_lens[:, None]  # [B, L]
    causal = ctx[None, None, :] <= positions[:, :, None]  # [B, Sq, L]
    mask = (valid[:, None, :] & causal)[:, None, None]  # [B, 1, 1, Sq, L]
    logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgql,kbld->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, Sq, H, D).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, 1, heads, head_dim]
    k_cache: jnp.ndarray,  # [kv_heads, num_slots, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B]
    block_size: int,
    impl: str = "xla",  # xla | pallas | jax
) -> jnp.ndarray:
    """Sq=1 hot path: dispatch to the configured kernel backend."""
    B, Sq, H, D = q.shape
    KV = k_cache.shape[0]
    G = H // KV

    if impl == "xla":
        positions = (context_lens - 1)[:, None]
        return paged_attention(
            q, k_cache, v_cache, block_tables, context_lens, positions, block_size
        )

    num_pages = k_cache.shape[1] // block_size
    k_pages = k_cache.reshape(KV, num_pages, block_size, D)
    v_pages = v_cache.reshape(KV, num_pages, block_size, D)

    if impl == "pallas":
        from .pallas_attention import paged_decode_attention

        out = paged_decode_attention(
            q.reshape(B, KV, G, D),
            k_pages,
            v_pages,
            context_lens,
            block_tables,
            page_size=block_size,
        )
        return out.reshape(B, Sq, H, D)

    if impl == "jax":
        from jax.experimental.pallas.ops.tpu.paged_attention import paged_attention as jax_paged

        # jax's kernel does not scale q internally — pre-scale by 1/sqrt(d).
        out = jax_paged(
            (q.reshape(B, H, D) * (D**-0.5)).astype(q.dtype),
            k_pages,
            v_pages,
            jnp.maximum(context_lens, 1),
            block_tables,
            pages_per_compute_block=min(8, block_tables.shape[1]),
        )
        return out.reshape(B, Sq, H, D)

    raise ValueError(f"unknown attention impl {impl!r}")
