"""Paged-KV attention: the engine's core op.

The KV cache for each layer is a flat slab of token slots
``[num_slots, kv_heads, head_dim]`` (num_slots = num_blocks * block_size) —
the TPU translation of the reference's slab-per-layer block storage
(lib/llm/src/kv/layer.rs:100-772).  Sequences own *blocks* of ``block_size``
consecutive slots; a block table maps each sequence's logical block index to
its physical block id.  Because attention gathers whole blocks, any physical
block order works — allocation never moves data.

``paged_attention`` here is the XLA reference implementation: gather the
sequence's slots, mask, flash-style softmax in f32.  It is used for both
prefill (Sq = padded prompt bucket) and decode (Sq = 1), which keeps a single
code path and a single set of compiled shapes per bucket.  A Pallas kernel
with block-wise streaming replaces the gather for large contexts (ops/pallas_attention.py).

Static shapes everywhere: padded queries use slot -1 (dropped scatter), padded
context is masked by ``context_lens``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def write_kv(
    k_cache: jnp.ndarray,  # [num_slots, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [B, Sq, kv_heads, head_dim]
    v_new: jnp.ndarray,
    slot_mapping: jnp.ndarray,  # [B, Sq] int32; -1 = padding (write dropped)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V rows into their cache slots (out-of-range = dropped)."""
    flat_slots = slot_mapping.reshape(-1)
    # Negative indices would wrap; remap them past the end so mode="drop"
    # discards padding writes instead of clobbering the last slots.
    flat_slots = jnp.where(flat_slots < 0, k_cache.shape[0], flat_slots)
    kv_heads, head_dim = k_cache.shape[-2:]
    k_flat = k_new.reshape(-1, kv_heads, head_dim).astype(k_cache.dtype)
    v_flat = v_new.reshape(-1, kv_heads, head_dim).astype(v_cache.dtype)
    k_cache = k_cache.at[flat_slots].set(k_flat, mode="drop")
    v_cache = v_cache.at[flat_slots].set(v_flat, mode="drop")
    return k_cache, v_cache


def gather_context_slots(
    block_tables: jnp.ndarray,  # [B, max_blocks] int32 physical block ids
    block_size: int,
) -> jnp.ndarray:
    """[B, max_blocks*block_size] physical slot index of each context position."""
    max_blocks = block_tables.shape[-1]
    ctx = jnp.arange(max_blocks * block_size, dtype=jnp.int32)
    return block_tables[:, ctx // block_size] * block_size + ctx % block_size


def paged_attention(
    q: jnp.ndarray,  # [B, Sq, heads, head_dim]
    k_cache: jnp.ndarray,  # [num_slots, kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] total valid context tokens (incl. new)
    positions: jnp.ndarray,  # [B, Sq] global position of each query token
    block_size: int,
    scale: float | None = None,
) -> jnp.ndarray:
    """Causal attention of queries against their sequence's paged context.

    Context position j (< context_lens[b]) is visible to query token i iff
    j <= positions[b, i].  New tokens' K/V must already be in the cache
    (write_kv runs first), so prefill attends to reused prefix + itself with
    the same gather.
    """
    B, Sq, H, D = q.shape
    KV = k_cache.shape[-2]
    groups = H // KV
    if scale is None:
        scale = D**-0.5

    slots = gather_context_slots(block_tables, block_size)  # [B, L]
    L = slots.shape[-1]
    k = k_cache[slots]  # [B, L, KV, D]
    v = v_cache[slots]

    qf = q.astype(jnp.float32).reshape(B, Sq, KV, groups, D) * scale
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqkgd,blkd->bkgql", qf, kf)  # [B, KV, G, Sq, L]

    ctx = jnp.arange(L, dtype=jnp.int32)
    valid = ctx[None, :] < context_lens[:, None]  # [B, L]
    causal = ctx[None, None, :] <= positions[:, :, None]  # [B, Sq, L]
    mask = (valid[:, None, :] & causal)[:, None, None]  # [B, 1, 1, Sq, L]
    logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgql,blkd->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(B, Sq, H, D).astype(q.dtype)
