"""Rotary position embeddings with Llama-3 frequency scaling.

Computed on the fly from integer positions (no host-precomputed cos/sin
tables): a gather from a [max_pos, hd] table would be HBM-bound, while
computing cos/sin in-register is VPU work that XLA fuses into the attention
prologue — the TPU-friendly trade.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    theta: float,
    scaling: Optional[Dict[str, Any]] = None,
) -> jnp.ndarray:
    """Inverse frequencies [head_dim//2], with optional llama3-style scaling."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponents)
    if scaling and scaling.get("rope_type", scaling.get("type")) == "llama3":
        factor = scaling["factor"]
        low = scaling.get("low_freq_factor", 1.0)
        high = scaling.get("high_freq_factor", 4.0)
        orig = scaling.get("original_max_position_embeddings", 8192)
        # Long wavelengths (low freqs) scaled down by `factor`; short kept;
        # the band between orig/low and orig/high blends linearly.
        wavelen = 2.0 * math.pi / inv_freq
        smooth = jnp.clip((orig / wavelen - low) / (high - low), 0.0, 1.0)
        blended = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen > orig / low,
            inv_freq / factor,
            jnp.where(wavelen < orig / high, inv_freq, blended),
        )
    return inv_freq


def apply_rope(
    x: jnp.ndarray,  # [..., seq, heads, head_dim]
    positions: jnp.ndarray,  # [..., seq] int32
    inv_freq: jnp.ndarray,  # [head_dim//2]
) -> jnp.ndarray:
    """Rotate pairs (x[2i], x[2i+1]) — interleaved convention folded to
    half-split (HF llama convention: first/second half pairing)."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., seq, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
