"""Unified ragged paged attention: mixed prefill + decode in ONE kernel call.

This is the engine's core op from round 2 on.  A step is a flat run of
tokens — any mix of prompt chunks (many tokens of one sequence) and decode
tokens (one token each) — described by ``cu_q_lens`` row boundaries.  One
compiled program per *token-count bucket* covers every batch composition,
which is what keeps XLA recompiles rare (the round-1 design had separate
prefill/decode programs per (batch, seq-len) bucket pair and still hit
cold shapes in production mixes).

Two implementations behind one contract:
- TPU: ``jax.experimental.pallas.ops.tpu.ragged_paged_attention`` — the
  vLLM-TPU kernel (multi-page async-copy DMA, heads-block grid, online
  softmax in VMEM).  This is the measured-fastest decode AND prefill path
  and never materialises O(T · window) logits in HBM.
- XLA fallback (CPU tests / virtual meshes): static-shape gather + masked
  softmax.  Memory O(T · window · kv_heads · head_dim) — fine for the tiny
  test shapes, deliberately not used on real hardware.

Cache layout per layer (kernel contract): ``[num_pages, page_size,
2 * kv_heads, head_dim]`` with K at even combined-head indices and V at odd.
Layout reference: the reference's block storage is also page-major slabs
(lib/llm/src/kv/layer.rs:100-772); the combined-KV interleave is the TPU
kernel's requirement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def on_tpu() -> bool:
    """True when default execution actually lands on a TPU — accounts for a
    jax_default_device override (tests pin CPU while a TPU plugin is still
    registered as the default backend)."""
    if jax.default_backend() != "tpu":
        return False
    dev = jax.config.jax_default_device
    return dev is None or getattr(dev, "platform", None) == "tpu"


def resolve_decode_kernel(value: str = "auto", attn_impl: str = "auto") -> str:
    """Resolve the decode attention kernel selector.

    Order: explicit config value > ``DYN_DECODE_KERNEL`` env > auto.
    - ``pallas_fused``: our fused-dequant split-KV kernel
      (ops/decode_attention.py) — compiled on TPU, interpret-mode on CPU
      (the tier-1 parity gates run exactly the device kernel logic).
    - ``stock``: the pre-existing path — the jax pallas
      ragged_paged_attention kernel on TPU, XLA gather fallback elsewhere.
    - ``xla``: force the XLA fallback everywhere (the bit-exactness
      oracle, even on TPU).
    ``auto`` picks pallas_fused on TPU and stock elsewhere, so default
    CPU behaviour (and every pre-existing test stream) is unchanged.

    ``attn_impl`` is the engine's attention backend: an operator forcing
    ``attn_impl="xla"`` (the oracle-numerics debugging contract) must not
    have ``auto`` route decode through the compiled fused kernel — auto
    resolves to ``stock`` there, which honours impl=xla end-to-end.  An
    EXPLICIT pallas_fused (config or env) still wins.
    """
    import os

    # Lazy: config.py is the canonical (dependency-free) home of the
    # kernel list — EngineConfig validation and the CLI choices share it.
    from ..engine.config import DECODE_KERNELS

    # ''/whitespace count as unset at both layers: a deployment template
    # rendering DYN_DECODE_KERNEL= (empty) must not fail worker boot.
    v = ((value or "auto").strip() or "auto").lower()
    if v == "auto":
        v = (
            os.environ.get("DYN_DECODE_KERNEL", "auto").strip() or "auto"
        ).lower()
    if v == "auto":
        v = "stock" if attn_impl == "xla" else (
            "pallas_fused" if on_tpu() else "stock"
        )
    if v not in DECODE_KERNELS:
        # Report the RESOLVED value: with config "auto" the offender is
        # usually a typo'd DYN_DECODE_KERNEL env var, not the config.
        raise ValueError(
            f"unknown decode kernel {v!r} (from config {value!r} / "
            f"DYN_DECODE_KERNEL; expected auto|{'|'.join(DECODE_KERNELS)})"
        )
    return v


def resolve_prefill_kernel(value: str = "auto", attn_impl: str = "auto") -> str:
    """Resolve the prefill attention kernel selector.

    Order: explicit config value > ``DYN_PREFILL_KERNEL`` env > auto.
    - ``pallas``: our chunked paged prefill kernel with in-kernel dequant
      and KV splits (ops/prefill_attention.py) — compiled on TPU,
      interpret-mode on CPU (the tier-1 parity gates run exactly the
      device kernel logic).
    - ``stock``: the pre-existing path — the jax pallas
      ragged_paged_attention kernel on TPU, XLA gather fallback elsewhere.
    - ``xla``: force the XLA fallback everywhere (the byte-identity
      oracle, even on TPU).
    ``auto`` picks pallas on TPU and stock elsewhere, so default CPU
    behaviour (and every pre-existing test stream) is unchanged.

    ``attn_impl`` mirrors resolve_decode_kernel: an operator forcing
    ``attn_impl="xla"`` must not have ``auto`` route prefill through the
    compiled kernel — auto resolves to ``stock`` there, which honours
    impl=xla end-to-end.  An EXPLICIT pallas (config or env) still wins.
    """
    import os

    from ..engine.config import PREFILL_KERNELS

    v = ((value or "auto").strip() or "auto").lower()
    if v == "auto":
        v = (
            os.environ.get("DYN_PREFILL_KERNEL", "auto").strip() or "auto"
        ).lower()
    if v == "auto":
        v = "stock" if attn_impl == "xla" else (
            "pallas" if on_tpu() else "stock"
        )
    if v not in PREFILL_KERNELS:
        # Report the RESOLVED value: with config "auto" the offender is
        # usually a typo'd DYN_PREFILL_KERNEL env var, not the config.
        raise ValueError(
            f"unknown prefill kernel {v!r} (from config {value!r} / "
            f"DYN_PREFILL_KERNEL; expected auto|{'|'.join(PREFILL_KERNELS)})"
        )
    return v


def quantize_for_cache(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Make already-scaled values representable in a quantized page dtype.

    int8: round-to-nearest + clip (astype truncates toward zero — biased —
    and wraps on overflow).  float8: clip to ±finfo.max (e4m3fn has NO inf,
    so casting past the max saturates to NaN and one NaN K row poisons
    every later attention read of the block).  Shared by the ragged write
    path and the engine's block-inject path so normal-prefill and
    injected/sp-prefilled blocks can never diverge numerically."""
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        x = jnp.clip(jnp.round(x.astype(jnp.float32)), info.min, info.max)
    elif dtype.itemsize == 1:
        fmax = float(jnp.finfo(dtype).max)
        x = jnp.clip(x.astype(jnp.float32), -fmax, fmax)
    return x.astype(dtype)


def write_kv_ragged(
    pages: jnp.ndarray,  # [num_pages, page_size, 2*kv_heads, head_dim]
    k_new: jnp.ndarray,  # [T, kv_heads, head_dim]
    v_new: jnp.ndarray,  # [T, kv_heads, head_dim]
    slot_mapping: jnp.ndarray,  # [T] int32 flat slot ids; -1 = padding (dropped)
    kv_scale=None,  # quantized cache: store value/scale (float OR traced scalar)
) -> jnp.ndarray:
    """Scatter new K/V rows into their cache slots (one combined scatter)."""
    P, ps, KV2, D = pages.shape
    T = k_new.shape[0]
    # Interleave to the combined layout: [T, KV, 2, D] -> [T, 2KV, D]
    # puts k_h at combined index 2h and v_h at 2h+1.
    comb = jnp.stack([k_new, v_new], axis=2).reshape(T, KV2, D)
    if kv_scale is not None:
        # kv_scale may be a per-layer traced scalar (the layer scan indexes
        # a [L] calibration vector), so no Python != 1.0 fast path here.
        comb = comb.astype(jnp.float32) / kv_scale
    comb = quantize_for_cache(comb, pages.dtype)
    slots = jnp.where(jnp.asarray(slot_mapping) < 0, P * ps, slot_mapping)
    flat = pages.reshape(P * ps, KV2, D)
    flat = flat.at[slots].set(comb, mode="drop")
    return flat.reshape(P, ps, KV2, D)


def _decode_block_hints(pages: jnp.ndarray, page_indices: jnp.ndarray):
    """Pallas block/grid hints for decode-shaped dispatches (every row one
    query token).  The kernel's default KV block spans all of pages_per_seq;
    at long context its double-buffered VMEM scratch exceeds the 16MB scoped
    limit, and decode steps measured 2x faster with explicit 16-query blocks
    + a ~4MB-budget KV block (18-layer chain at batch 256: 14.2 -> 7.9ms on
    v5e).  Tunable for hardware sweeps: DYN_DECODE_NQ query block,
    DYN_DECODE_NKV_MB KV block budget — each resolved env var > tuned-table
    entry installed at engine init (tools/tune_decode.py) > the defaults
    above, through the ONE precedence implementation (resolve_hint)."""
    from .decode_attention import pages_per_vmem_budget, resolve_hint

    ps, KV2, hd = pages.shape[1], pages.shape[2], pages.shape[3]
    budget = resolve_hint("DYN_DECODE_NKV_MB", "nkv_mb", 4) << 20
    # itemsize 2: the stock kernel's VMEM working set is in the cast-up
    # bf16 compute dtype regardless of the page dtype (see the helper).
    nkv = pages_per_vmem_budget(budget, ps, KV2, hd, 2)
    nkv = min(page_indices.shape[1], nkv)
    nq = resolve_hint("DYN_DECODE_NQ", "nq", 16)
    return nq, nkv


def ragged_decode_attention(
    q: jnp.ndarray,  # [S, num_heads, head_dim] — ONE query token per row
    pages: jnp.ndarray,  # [num_pages, page_size, 2*kv_heads, head_dim]
    kv_lens: jnp.ndarray,  # [S] int32 context length per row
    page_indices: jnp.ndarray,  # [S, pages_per_seq] int32
    num_seqs: jnp.ndarray,  # [1] int32 valid rows
    *,
    sm_scale: float,
    impl: str = "xla",  # "tpu" | "xla"
    kv_scale: float | None = None,
    kernel: str = "stock",  # "pallas_fused" | "stock" | "xla"
) -> jnp.ndarray:
    """Decode-specialized attention: every row is exactly ONE query token
    (the fused multi-step decode program's shape — engine/pipeline.py).

    The unified entry (``ragged_attention``) must handle arbitrary
    prefill/decode mixes, which costs it per-token ``cu_q_lens``
    bookkeeping: a searchsorted row lookup and tail-position arithmetic per
    query token.  Here row ``i``'s single query sits at context position
    ``kv_lens[i] - 1`` by construction, so the row map is the identity and
    the causal mask is just ``ctx < kv_len``.

    ``kernel`` selects the implementation (resolve_decode_kernel /
    DYN_DECODE_KERNEL):
    - "pallas_fused": our fused-dequant split-KV decode kernel
      (ops/decode_attention.py) — ``kv_scale`` (static OR traced) is
      applied IN-KERNEL, so quantized pages stream from HBM once at
      1 byte/value.  Interpret-mode on CPU, compiled on TPU.
    - "stock": the pre-existing routing — the jax pallas kernel with the
      decode-tuned block hints on ``impl == "tpu"``, XLA fallback
      otherwise.
    - "xla": force the XLA fallback (the bit-exactness oracle) — a direct
      [S, W] row gather, no searchsorted, no cu_q_lens — numerically
      identical to the unified fallback on decode shapes.
    """
    S, H, D = q.shape
    if kernel == "pallas_fused":
        from .decode_attention import fused_decode_attention

        try:
            return fused_decode_attention(
                q,
                pages,
                kv_lens,
                page_indices,
                num_seqs,
                sm_scale=sm_scale,
                kv_scale=kv_scale,
            )
        except Exception as e:  # trace-time rejection (see ragged_attention)
            # Only COMPILED toy shapes (sub-lane-width heads on a real
            # TPU) may fall back.  Interpret mode has no legitimate
            # rejection path, and a silent fallback there would leave
            # every decode_kernel reporting surface (bench JSON, CI churn
            # assertion, /metrics info gauge) claiming pallas_fused while
            # stock served — the attribution error BENCH_r06 exists to
            # avoid.  Real serving geometries stay loud everywhere.
            if pages.shape[3] >= 128 or not on_tpu():
                raise
            import logging

            logging.getLogger(__name__).warning(
                "fused decode kernel rejected toy shapes q=%s pages=%s "
                "(%s); using the stock path",
                q.shape, pages.shape, e,
            )
            kernel = "stock"
    if kernel == "xla":
        impl = "xla"
    elif kernel != "stock":
        raise ValueError(f"unknown decode kernel {kernel!r}")
    if impl == "tpu":
        from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
            ragged_paged_attention,
        )

        nq, nkv = _decode_block_hints(pages, page_indices)
        # One token per row: cumulative query lengths are the identity.
        cu = jnp.arange(S + 1, dtype=jnp.int32)
        # Unit scale for quantized pages without an explicit one — see the
        # matching comment in ragged_attention.
        unit = 1.0 if pages.dtype.itemsize == 1 and kv_scale is None else kv_scale
        try:
            return ragged_paged_attention(
                q,
                pages,
                kv_lens,
                page_indices,
                cu,
                num_seqs,
                sm_scale=sm_scale,
                num_queries_per_block=nq,
                num_kv_pages_per_block=nkv,
                vmem_limit_bytes=64 << 20,
                k_scale=unit,
                v_scale=unit,
            )
        except Exception as e:  # trace-time rejection (see ragged_attention)
            if pages.shape[3] >= 128:
                raise
            import logging

            logging.getLogger(__name__).warning(
                "pallas ragged kernel rejected toy decode shapes q=%s "
                "pages=%s (%s); using the XLA fallback",
                q.shape, pages.shape, e,
            )
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown ragged attention impl {impl!r}")

    kv_lens = jnp.asarray(kv_lens)
    page_indices = jnp.asarray(page_indices)
    num_seqs = jnp.asarray(num_seqs)

    ps = pages.shape[1]
    KV = pages.shape[2] // 2
    G = H // KV
    W = page_indices.shape[1] * ps

    ctx = jnp.arange(W, dtype=jnp.int32)
    # Row map is the identity: gather each row's context directly.
    slots = page_indices[:, ctx // ps] * ps + ctx % ps  # [S, W]
    kv = pages.reshape(-1, 2 * KV, D)[slots]  # [S, W, 2KV, D]
    k = kv[:, :, 0::2].astype(jnp.float32)  # [S, W, KV, D]
    v = kv[:, :, 1::2].astype(jnp.float32)
    # The != 1.0 fast path only for PYTHON floats: a traced per-layer
    # scale (the fused kernel's native contract, reachable here through
    # its toy-shape fallback) cannot be compared at trace time.
    if kv_scale is not None and (
        not isinstance(kv_scale, (int, float)) or kv_scale != 1.0
    ):
        k = k * kv_scale
        v = v * kv_scale

    valid = jnp.arange(S, dtype=jnp.int32) < num_seqs[0]
    qf = q.reshape(S, KV, G, D).astype(jnp.float32) * sm_scale
    logits = jnp.einsum("skgd,swkd->skgw", qf, k)  # [S, KV, G, W]
    mask = (ctx[None, :] < kv_lens[:, None]) & valid[:, None]  # [S, W]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m) * mask[:, None, None, :]
    out = jnp.einsum("skgw,swkd->skgd", p, v) / (
        jnp.sum(p, axis=-1, keepdims=True) + 1e-30
    )
    return out.reshape(S, H, D).astype(q.dtype)


def ragged_attention(
    q: jnp.ndarray,  # [T, num_heads, head_dim]
    pages: jnp.ndarray,  # [num_pages, page_size, 2*kv_heads, head_dim]
    kv_lens: jnp.ndarray,  # [S] int32 context length per sequence row
    page_indices: jnp.ndarray,  # [S, pages_per_seq] int32
    cu_q_lens: jnp.ndarray,  # [S+1] int32 cumulative query lengths
    num_seqs: jnp.ndarray,  # [1] int32 valid rows of the above
    *,
    sm_scale: float,
    impl: str = "xla",  # "tpu" | "xla"
    kv_scale: float | None = None,  # quantized cache: value = stored * scale
    decode: bool = False,  # static hint: every row is a 1-token decode row
    decode_kernel: str = "stock",  # decode-path kernel (resolve_decode_kernel)
    prefill_kernel: str = "stock",  # non-decode kernel (resolve_prefill_kernel)
) -> jnp.ndarray:
    """Causal attention of each token against its sequence's paged context.

    Row i's queries are the LAST (cu_q_lens[i+1]-cu_q_lens[i]) tokens of its
    kv_lens[i]-token context (their K/V must already be written — callers run
    write_kv_ragged first).  Tokens at or past cu_q_lens[num_seqs] are
    padding and produce zeros.

    ``kv_scale`` supports quantized (fp8/int8) page dtypes with one static
    per-tensor scale — the TPU kernel's native k_scale/v_scale contract;
    the write side stores value/scale (write_kv_ragged).

    ``decode=True`` routes to ``ragged_decode_attention``: the fused
    multi-step decode program's shape (one query token per row) skips the
    cu_q_lens generality entirely and always gets the decode-tuned pallas
    block hints.

    ``prefill_kernel`` selects the NON-decode implementation
    (resolve_prefill_kernel / DYN_PREFILL_KERNEL):
    - "pallas": our chunked paged prefill kernel
      (ops/prefill_attention.py) — ``kv_scale`` (static OR traced) is
      applied IN-KERNEL and the prior prefix streams straight from the
      paged blocks.  Interpret-mode on CPU, compiled on TPU.
    - "stock": the pre-existing routing below (jax pallas kernel on
      ``impl == "tpu"``, XLA fallback otherwise).
    - "xla": force the XLA fallback (the byte-identity oracle).
    """
    if decode:
        return ragged_decode_attention(
            q,
            pages,
            kv_lens,
            page_indices,
            num_seqs,
            sm_scale=sm_scale,
            impl=impl,
            kv_scale=kv_scale,
            kernel=decode_kernel,
        )
    if prefill_kernel == "pallas":
        from .prefill_attention import fused_prefill_attention

        try:
            return fused_prefill_attention(
                q,
                pages,
                kv_lens,
                page_indices,
                cu_q_lens,
                num_seqs,
                sm_scale=sm_scale,
                kv_scale=kv_scale,
            )
        except Exception as e:  # trace-time rejection (see below)
            # Same fallback policy as the fused decode kernel: only
            # COMPILED toy shapes (sub-lane-width heads on a real TPU) may
            # fall back.  Interpret mode has no legitimate rejection path —
            # a silent fallback there would leave every prefill_kernel
            # reporting surface (bench JSON, CI gate, /metrics info gauge)
            # claiming pallas while stock served.
            if pages.shape[3] >= 128 or not on_tpu():
                raise
            import logging

            logging.getLogger(__name__).warning(
                "fused prefill kernel rejected toy shapes q=%s pages=%s "
                "(%s); using the stock path",
                q.shape, pages.shape, e,
            )
            prefill_kernel = "stock"
    if prefill_kernel == "xla":
        impl = "xla"
    elif prefill_kernel != "stock":
        raise ValueError(f"unknown prefill kernel {prefill_kernel!r}")
    if impl == "tpu":
        from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
            ragged_paged_attention,
        )

        # Block sizing: the kernel replaces BOTH block params with its tuned
        # table whenever EITHER is None — a partial override is silently
        # discarded.  Prefill and mixed shapes run the kernel's tuned table
        # (59-83% MFU measured) under the raised vmem limit; decode shapes
        # never reach here (routed to ragged_decode_attention above, which
        # passes the measured-best decode hints).
        hd = pages.shape[3]
        nkv = nq = None
        # Quantized (1-byte) pages: real scaling is folded around this call
        # by the model (q pre-scaled, output post-scaled — models/llama.py),
        # but the kernel only CASTS fp8/int8 K/V up to q's dtype inside its
        # `if k_scale is not None` branch — so a unit scale must be passed
        # or raw quantized values feed the MXU dot and tracing rejects.
        unit = 1.0 if pages.dtype.itemsize == 1 and kv_scale is None else kv_scale
        try:
            return ragged_paged_attention(
                q,
                pages,
                kv_lens,
                page_indices,
                cu_q_lens,
                num_seqs,
                sm_scale=sm_scale,
                num_queries_per_block=nq,
                num_kv_pages_per_block=nkv,
                # The default 16MB scoped-vmem budget is a compiler default,
                # not the hardware ceiling; long-context shapes need headroom
                # (vLLM's TPU backend raises it the same way).
                vmem_limit_bytes=64 << 20,
                k_scale=unit,
                v_scale=unit,
            )
        except Exception as e:  # trace-time rejection
            # The kernel enforces its own contract during tracing.  Only
            # TOY geometries (sub-lane-width heads: tests/debug models) may
            # silently fall back to the XLA path — there its O(T·window)
            # materialization is small.  A rejection at a real serving
            # geometry (head_dim >= 128) is a kernel/JAX fault that must be
            # LOUD, not a silent 10x memory/latency downgrade.
            if hd >= 128:
                raise
            import logging

            logging.getLogger(__name__).warning(
                "pallas ragged kernel rejected toy shapes q=%s pages=%s "
                "(%s); using the XLA fallback",
                q.shape, pages.shape, e,
            )
            impl = "xla"
    if impl != "xla":
        raise ValueError(f"unknown ragged attention impl {impl!r}")

    # Coerce metadata to jnp: callers may hand numpy arrays outside jit,
    # and mixing numpy containers with traced indices fails inside scan.
    kv_lens = jnp.asarray(kv_lens)
    page_indices = jnp.asarray(page_indices)
    cu_q_lens = jnp.asarray(cu_q_lens)
    num_seqs = jnp.asarray(num_seqs)

    T, H, D = q.shape
    S, PP = page_indices.shape
    ps = pages.shape[1]
    KV = pages.shape[2] // 2
    G = H // KV
    W = PP * ps

    tok = jnp.arange(T, dtype=jnp.int32)
    # Sequence row of each token; padding tokens clamp to the last row and
    # are masked out below.
    seq = jnp.searchsorted(cu_q_lens[1:], tok, side="right").astype(jnp.int32)
    seq = jnp.minimum(seq, S - 1)
    valid = tok < cu_q_lens[num_seqs[0]]
    q_len = cu_q_lens[seq + 1] - cu_q_lens[seq]
    # Global context position of each query token (queries are the tail).
    qpos = kv_lens[seq] - q_len + (tok - cu_q_lens[seq])

    ctx = jnp.arange(W, dtype=jnp.int32)
    slots = page_indices[seq][:, ctx // ps] * ps + ctx % ps  # [T, W]
    kv = pages.reshape(-1, 2 * KV, D)[slots]  # [T, W, 2KV, D]
    k = kv[:, :, 0::2].astype(jnp.float32)  # [T, W, KV, D]
    v = kv[:, :, 1::2].astype(jnp.float32)
    if kv_scale is not None and kv_scale != 1.0:
        k = k * kv_scale
        v = v * kv_scale

    qf = q.reshape(T, KV, G, D).astype(jnp.float32) * sm_scale
    logits = jnp.einsum("tkgd,twkd->tkgw", qf, k)  # [T, KV, G, W]
    mask = (ctx[None, :] <= qpos[:, None]) & valid[:, None]  # [T, W]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m) * mask[:, None, None, :]
    out = jnp.einsum("tkgw,twkd->tkgd", p, v) / (
        jnp.sum(p, axis=-1, keepdims=True) + 1e-30
    )
    return out.reshape(T, H, D).astype(q.dtype)
