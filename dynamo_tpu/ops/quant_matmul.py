"""W8A8-dynamic int8 matmul: the MXU path for quantized weights.

``qdot`` is the single hot op behind weight quantization
(models/quant.py): dynamic symmetric per-row int8 activations x static
per-output-channel int8 weights, int32 accumulation on the MXU, f32
rescale.  XLA fuses the quantize (max/abs/round) into the surrounding
elementwise work and runs the dot on the native int8 systolic path —
measured 1.73x bf16 on decode-geometry chains and 1.87x on prefill
(tools/quant_microbench.py on v5e; near both the int8 HBM roofline and the
int8 MXU peak).

Reference counterpart: vLLM's fp8-dynamic execution of the baseline
checkpoint (per-token dynamic activation scales, per-channel weight
scales) — /root/reference/examples/llm/benchmarks/README.md's
``...-FP8-dynamic`` workload.  v5e's native low-precision MXU format is
int8, so that is the TPU-first mapping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic symmetric per-row int8: returns (x_q int8, row_scale f32
    [..., 1]).  Rows of zeros get scale 1e-9 and quantize to zeros."""
    xf = x.astype(jnp.float32)
    ax = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-9)
    xq = jnp.clip(jnp.round(xf / ax), -127, 127).astype(jnp.int8)
    return xq, ax


def qdot(x: jnp.ndarray, w_q: jnp.ndarray, scale: jnp.ndarray, out_dtype=None):
    """``x @ dequant(w_q)`` via native int8: x [..., K] float, w_q [K, N]
    int8, scale [N] f32 (per-output-channel).  int32 accumulation is exact
    for K <= ~130k (|acc| <= K * 127^2 < 2^31)."""
    xq, ax = quantize_rows(x)
    acc = jax.lax.dot_general(
        xq, w_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * ax * scale
    return out.astype(out_dtype or x.dtype)


def qdot_batched(x: jnp.ndarray, w_q: jnp.ndarray, scale: jnp.ndarray, out_dtype=None):
    """Batched variant for MoE experts: x [E, C, K] float, w_q [E, K, N]
    int8, scale [E, N] f32 → [E, C, N] (einsum "eck,ekn->ecn")."""
    xq, ax = quantize_rows(x)
    acc = jax.lax.dot_general(
        xq, w_q, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * ax * scale[:, None, :]
    return out.astype(out_dtype or x.dtype)


def expert_linear(x: jnp.ndarray, lp, name: str, out_dtype=None):
    """Per-expert ``einsum("ecd,edf->ecf", x, lp[name])`` dispatching on the
    quant scale leaf — the batched sibling of models.llama.linear, so the
    MoE and dense forwards share one quantization contract."""
    w = lp[name]
    s = lp.get(name + "_scale")
    if s is None:
        r = jnp.einsum("ecd,edf->ecf", x, w)
        return r.astype(out_dtype) if out_dtype is not None else r
    return qdot_batched(x, w, s, out_dtype=out_dtype)
