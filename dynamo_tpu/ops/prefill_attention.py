"""Chunked paged PREFILL attention — our own Pallas TPU kernel.

The decode hot path got its purpose-built kernel (ops/decode_attention.py,
ISSUE 13); prefill — the other half of every request and the dominant cost
at 128k-class context — still rode the stock mixed-generality kernel.
This is the prefill sibling, specialised for the shape the engine's chunk
scheduler dispatches (``ragged_attention`` non-decode path): each row's
queries are the LAST ``cu_q_lens[i+1]-cu_q_lens[i]`` tokens of its
``kv_lens[i]``-token context, whose K/V — the restored/pulled/tiered
prior prefix AND the in-flight chunk itself (written by
``write_kv_ragged`` just before the call) — already sit in paged cache
blocks:

1. **Paged prefix reads with fused dequant**: the prior prefix streams
   straight from the paged KV blocks via double-buffered ``make_async_copy``
   DMA — a restored or cross-worker-pulled prefix never needs a contiguous
   gather — and int8/fp8 pages are scaled by ``kv_scale`` in VMEM right
   before the dots.  The scale is an SMEM scalar operand, so per-layer
   TRACED calibration scales work natively.
2. **Causal chunk masking**: the chunk's own positions are covered by the
   same paged stream; the causal mask ``ctx <= qpos`` (with
   ``qpos = kv_len - q_len + t``) keeps intra-chunk attention exact.
3. **Flash-style online softmax + LSE combine** (the structure proven in
   the decode kernel): the KV axis optionally splits across grid programs,
   each writing an unnormalized partial (o, m, l) reduced host-side by
   log-sum-exp — long prior prefixes parallelize across the chip even when
   the chunk itself is narrow.

Ragged layout without dense padding: q stays in HBM (``memory_space=ANY``)
and each row-program DMAs its own q-blocks in at dynamic token offsets;
partials are DMA'd back out the same way.  A row's tail q-block can spill
past its token range into the NEXT row's region — safe because the TPU
grid runs sequentially in row-major order (rows ascending), so the next
row's own first-block write lands after and overwrites the spill; the last
row's spill goes to the wrapper's padding tail.  The row grid axis must
therefore never be marked ``parallel``.

Contract: identical inputs/outputs to ``ragged_attention``'s XLA fallback
(the byte-identity oracle) — [T, H, D] out, zeros for padding tokens at or
past ``cu_q_lens[num_seqs]``.  Interpret mode (CPU) runs the same kernel
for tier-1 parity gates; compiled mode is TPU-only.  Selection:
DYN_PREFILL_KERNEL / EngineConfig.prefill_kernel
(ops/ragged_attention.py resolve_prefill_kernel).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Shared hint machinery: the prefill knobs live in the SAME tuned table
# (tools/tune_decode.py sweeps both kernels' families into one entry per
# engine geometry) under their own keys, resolved env > table > default.
from .decode_attention import NEG_INF, pages_per_vmem_budget, resolve_hint


def _default_ppcb(page_size: int, kv2: int, head_dim: int, itemsize: int) -> int:
    """Pages per compute block from the DYN_PREFILL_NKV_MB budget (default
    4MB) at the PAGE dtype's width — quantized pages land in scratch
    quantized, so int8 packs ~2x the bf16 block."""
    budget = resolve_hint("DYN_PREFILL_NKV_MB", "prefill_nkv_mb", 4) << 20
    return pages_per_vmem_budget(budget, page_size, kv2, head_dim, itemsize)


def _make_kernel(
    *,
    sm_scale: float,
    num_kv: int,
    group: int,
    head_dim: int,
    page_size: int,
    pages_per_seq: int,
    split_pages: int,
    ppcb: int,
    q_block: int,
):
    """Build the kernel body for a static geometry.

    Grid (S, J): program (s, j) computes ALL of row ``s``'s query blocks
    against KV split ``j`` (pages [j*split_pages, (j+1)*split_pages)) and
    writes UNNORMALIZED partials (o, m, l) per token — combined host-side
    by LSE over the split axis.
    """
    C = ppcb * page_size  # context positions per compute block
    QB = q_block
    H = num_kv * group

    def kernel(
        # scalar prefetch (SMEM)
        kv_lens_ref,  # [S] int32
        page_indices_ref,  # [S, PP] int32
        cu_q_lens_ref,  # [S+1] int32
        num_seqs_ref,  # [1] int32
        # operands
        q_hbm_ref,  # [Tpad, H, D] HBM/ANY — DMA'd per q-block
        pages_ref,  # [P, ps, 2KV, D] HBM/ANY — DMA'd per compute block
        scale_ref,  # [1, 1] f32 SMEM — kv_scale (traced OK)
        # outputs (HBM/ANY — DMA'd per q-block)
        o_ref,  # [J, Tpad, H, D] f32 — unnormalized sum(p·V)
        m_ref,  # [J, Tpad, H, 1] f32 — split max
        l_ref,  # [J, Tpad, H, 1] f32 — split sum(exp)
        # scratch
        q_buf,  # [QB, H, D] q dtype
        kv_buf,  # [2, ppcb, ps, 2KV, D] pages dtype
        o_sc,  # [QB, H, D] f32
        m_sc,  # [QB, H, 1] f32
        l_sc,  # [QB, H, 1] f32
        kv_sems,  # DMA semaphores (2,) — double-buffered page stream
        io_sems,  # DMA semaphores (4,) — q in + o/m/l out
    ):
        s = pl.program_id(0)
        j = pl.program_id(1)
        kv_len = kv_lens_ref[s]
        q_start = cu_q_lens_ref[s]
        q_len = cu_q_lens_ref[s + 1] - q_start
        base_page = j * split_pages
        row_pages = pl.cdiv(kv_len, page_size)
        pages_here = jnp.clip(row_pages - base_page, 0, split_pages)
        # The split's coverage END (not just kv_len): the last compute
        # block can reach past split_pages (ppcb granularity) and those
        # positions would otherwise be counted by TWO splits — a
        # double-count the LSE combine cannot undo (same cap as decode).
        split_end = jnp.minimum(kv_len, (base_page + split_pages) * page_size)
        # Rows past num_seqs write nothing: their token region is padding
        # by the cu_q_lens contract and the wrapper masks it to zero.  An
        # ACTIVE row writes every split slab — an empty split (prefix
        # shorter than the split's base) runs zero compute blocks and
        # writes the neutral partial, which vanishes in the combine.
        active = (s < num_seqs_ref[0]) & (q_len > 0)

        def fetch(block, slot, start):
            # One DMA per page: page ids are arbitrary (PagedAttention
            # indirection), so a block's pages share no stride.  wait()
            # recreates the descriptor — standard Pallas pattern.
            for t in range(ppcb):
                idx = base_page + block * ppcb + t
                idx = jnp.clip(idx, 0, pages_per_seq - 1)
                pid = page_indices_ref[s, idx]
                dma = pltpu.make_async_copy(
                    pages_ref.at[pid], kv_buf.at[slot, t], kv_sems.at[slot]
                )
                if start:
                    dma.start()
                else:
                    dma.wait()

        @pl.when(active)
        def _():
            nqb = pl.cdiv(q_len, QB)
            nblocks = pl.cdiv(pages_here, ppcb)
            scale = scale_ref[0, 0]

            def qb_step(qb, carry_unused):
                tok0 = q_start + qb * QB
                # Fetch this q-block (tail blocks over-read into the next
                # row's tokens / the wrapper's zero pad — masked below).
                qdma = pltpu.make_async_copy(
                    q_hbm_ref.at[pl.ds(tok0, QB)], q_buf, io_sems.at[0]
                )
                qdma.start()
                qdma.wait()

                # Per-token causal coordinates, flattened per KV head to
                # [QB*G] rows: row r is token i = r // G of the block.
                ti = (
                    qb * QB
                    + jax.lax.broadcasted_iota(jnp.int32, (QB * group, 1), 0)
                    // group
                )  # in-row token index [QB*G, 1]
                qpos = kv_len - q_len + ti
                valid_q = ti < q_len

                def block_step(b, carry):
                    slot = jax.lax.rem(b, 2)

                    @pl.when(b + 1 < nblocks)
                    def _():
                        fetch(b + 1, jax.lax.rem(b + 1, 2), start=True)

                    fetch(b, slot, start=False)
                    buf = kv_buf[slot].reshape(C, 2 * num_kv, head_dim)
                    # Fused dequant: the ONLY f32 materialization of this
                    # KV block is here in VMEM, one compute block at a time.
                    kvf = buf.astype(jnp.float32) * scale
                    pos = (base_page + b * ppcb) * page_size + (
                        jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
                    )
                    # Causal + split-coverage + live-query mask [QB*G, C].
                    mask = (pos <= qpos) & (pos < split_end) & valid_q
                    out = []
                    for h in range(num_kv):
                        m_h = carry[3 * h]
                        l_h = carry[3 * h + 1]
                        acc_h = carry[3 * h + 2]
                        k_h = kvf[:, 2 * h, :]  # [C, D]
                        v_h = kvf[:, 2 * h + 1, :]
                        qf = (
                            q_buf[:, h * group : (h + 1) * group, :]
                            .reshape(QB * group, head_dim)
                            .astype(jnp.float32)
                            * sm_scale
                        )
                        logits = jax.lax.dot_general(
                            qf,
                            k_h,
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )  # [QB*G, C]
                        logits = jnp.where(mask, logits, NEG_INF)
                        m_new = jnp.maximum(
                            m_h, jnp.max(logits, axis=1, keepdims=True)
                        )
                        # Mask the exp explicitly: a fully-masked block has
                        # m_new == m_h and exp(NEG_INF - m) must stay an
                        # exact zero, never a subnormal.
                        p = jnp.where(mask, jnp.exp(logits - m_new), 0.0)
                        alpha = jnp.exp(m_h - m_new)
                        l_new = alpha * l_h + jnp.sum(p, axis=1, keepdims=True)
                        acc_new = alpha * acc_h + jax.lax.dot_general(
                            p,
                            v_h,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )  # [QB*G, D]
                        out.extend((m_new, l_new, acc_new))
                    return tuple(out)

                init = []
                for _h in range(num_kv):
                    init.extend(
                        (
                            jnp.full((QB * group, 1), NEG_INF, jnp.float32),
                            jnp.zeros((QB * group, 1), jnp.float32),
                            jnp.zeros((QB * group, head_dim), jnp.float32),
                        )
                    )

                @pl.when(nblocks > 0)
                def _():
                    fetch(0, 0, start=True)

                # An empty split runs zero trips: the init carry IS the
                # neutral partial (o=0, m=NEG_INF, l=0).
                final = jax.lax.fori_loop(0, nblocks, block_step, tuple(init))
                for h in range(num_kv):
                    m_sc[:, h * group : (h + 1) * group, :] = final[
                        3 * h
                    ].reshape(QB, group, 1)
                    l_sc[:, h * group : (h + 1) * group, :] = final[
                        3 * h + 1
                    ].reshape(QB, group, 1)
                    o_sc[:, h * group : (h + 1) * group, :] = final[
                        3 * h + 2
                    ].reshape(QB, group, head_dim)
                # Write the block's partials back at the token offset.  The
                # tail block spills up to QB-1 tokens into the next row's
                # region — overwritten by that row's own (later) program;
                # see the module docstring's sequential-grid invariant.
                writes = (
                    pltpu.make_async_copy(
                        o_sc, o_ref.at[j, pl.ds(tok0, QB)], io_sems.at[1]
                    ),
                    pltpu.make_async_copy(
                        m_sc, m_ref.at[j, pl.ds(tok0, QB)], io_sems.at[2]
                    ),
                    pltpu.make_async_copy(
                        l_sc, l_ref.at[j, pl.ds(tok0, QB)], io_sems.at[3]
                    ),
                )
                for w in writes:
                    w.start()
                for w in writes:
                    w.wait()
                return carry_unused

            jax.lax.fori_loop(0, nqb, qb_step, 0)

    return kernel


def fused_prefill_attention(
    q: jnp.ndarray,  # [T, num_heads, head_dim] — ragged token run
    pages: jnp.ndarray,  # [num_pages, page_size, 2*kv_heads, head_dim]
    kv_lens: jnp.ndarray,  # [S] int32 context length per row
    page_indices: jnp.ndarray,  # [S, pages_per_seq] int32
    cu_q_lens: jnp.ndarray,  # [S+1] int32 cumulative query lengths
    num_seqs: jnp.ndarray,  # [1] int32 valid rows
    *,
    sm_scale: float,
    kv_scale=None,  # None | float | traced [] scalar — applied IN-KERNEL
    q_block: Optional[int] = None,
    num_kv_splits: Optional[int] = None,
    pages_per_block: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Host wrapper: chunked paged prefill attention + LSE split combine.

    Knobs (env > tuned table > default; tools/tune_decode.py sweeps them):
    - ``DYN_PREFILL_QB`` / prefill_qb: query tokens per compute block.
    - ``DYN_PREFILL_SPLITS`` / prefill_splits: KV-split grid width
      (0 = auto: 1 — the q-block axis already parallelizes a chunk; raise
      it for long restored prefixes, where the KV stream dominates).
    - ``DYN_PREFILL_PPCB`` / prefill_ppcb: pages per compute block
      (default from the DYN_PREFILL_NKV_MB VMEM budget at the PAGE
      dtype's width).
    """
    T, H, D = q.shape
    P, ps, KV2, _ = pages.shape
    KV = KV2 // 2
    G = H // KV
    S, PP = page_indices.shape

    QB = q_block or resolve_hint("DYN_PREFILL_QB", "prefill_qb", 128)
    QB = max(1, min(QB, T))
    ppcb = pages_per_block or resolve_hint(
        "DYN_PREFILL_PPCB",
        "prefill_ppcb",
        _default_ppcb(ps, KV2, D, pages.dtype.itemsize),
    )
    ppcb = max(1, min(ppcb, PP))
    splits = num_kv_splits or resolve_hint(
        "DYN_PREFILL_SPLITS", "prefill_splits", 0
    )
    if splits <= 0:
        splits = 1
    splits = min(splits, pl.cdiv(PP, ppcb))
    split_pages = pl.cdiv(PP, splits)
    splits = pl.cdiv(PP, split_pages)  # drop now-empty tail splits

    if interpret is None:
        from .ragged_attention import on_tpu

        interpret = not on_tpu()

    kernel = _make_kernel(
        sm_scale=sm_scale,
        num_kv=KV,
        group=G,
        head_dim=D,
        page_size=ps,
        pages_per_seq=PP,
        split_pages=split_pages,
        ppcb=ppcb,
        q_block=QB,
    )
    scale_arr = jnp.asarray(
        1.0 if kv_scale is None else kv_scale, jnp.float32
    ).reshape(1, 1)
    # Pad the token axis by one q-block: tail q-block DMAs over-read past
    # the run, and the LAST row's tail write spills here instead of out of
    # bounds.  Sliced back off after the combine.
    Tpad = T + QB
    q_pad = jnp.concatenate(
        [q, jnp.zeros((QB, H, D), q.dtype)], axis=0
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, splits),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),  # pages stay in HBM
            pl.BlockSpec(memory_space=pltpu.SMEM),  # kv_scale
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),  # o partials
            pl.BlockSpec(memory_space=pltpu.ANY),  # m partials
            pl.BlockSpec(memory_space=pltpu.ANY),  # l partials
        ),
        scratch_shapes=[
            pltpu.VMEM((QB, H, D), q.dtype),
            pltpu.VMEM((2, ppcb, ps, KV2, D), pages.dtype),
            pltpu.VMEM((QB, H, D), jnp.float32),
            pltpu.VMEM((QB, H, 1), jnp.float32),
            pltpu.VMEM((QB, H, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    cu = jnp.asarray(cu_q_lens, jnp.int32)
    num = jnp.asarray(num_seqs, jnp.int32)
    o_part, m_part, l_part = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((splits, Tpad, H, D), jnp.float32),
            jax.ShapeDtypeStruct((splits, Tpad, H, 1), jnp.float32),
            jax.ShapeDtypeStruct((splits, Tpad, H, 1), jnp.float32),
        ),
        compiler_params=pltpu.TPUCompilerParams(
            # Same headroom as the decode kernel / stock path.
            vmem_limit_bytes=64 << 20,
        ),
        interpret=interpret,
    )(
        jnp.asarray(kv_lens, jnp.int32),
        jnp.asarray(page_indices, jnp.int32),
        cu,
        num,
        q_pad,
        pages,
        scale_arr,
    )
    # Flash-style LSE combine over the split axis.  Neutral partials
    # (o=0, m=NEG_INF, l=0) from empty splits vanish here.
    m = m_part[..., 0]  # [J, Tpad, H]
    l = l_part[..., 0]
    m_max = jnp.max(m, axis=0)  # [Tpad, H]
    alpha = jnp.exp(m - m_max[None])  # [J, Tpad, H]
    l_tot = jnp.sum(alpha * l, axis=0)
    o_tot = jnp.sum(alpha[..., None] * o_part, axis=0)  # [Tpad, H, D]
    out = (o_tot / (l_tot[..., None] + 1e-30))[:T]
    # Padding tokens (at/past cu_q_lens[num_seqs]) were never written by an
    # active row: zero them to match the XLA oracle's padding contract.
    valid = jnp.arange(T, dtype=jnp.int32) < cu[num[0]]
    out = jnp.where(valid[:, None, None], out, 0.0)
    return out.astype(q.dtype)
