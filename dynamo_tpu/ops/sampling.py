"""Batched on-device token sampling: greedy / temperature / top-k / top-p /
frequency+presence penalties / per-request seeds / logprobs.

All requests in a decode batch sample in one fused op with per-request
parameters as arrays — no host round-trip per request.  temperature == 0
means greedy regardless of the other knobs.

Reference semantics: lib/llm/src/protocols/common.rs SamplingOptions
(temperature/top_p/top_k/frequency_penalty/presence_penalty/seed) — the
reference hands these to vLLM's sampler; this is the TPU-native sampler.

Cost shape matters here: this runs inside every decode step, and a full-vocab
sort (bitonic on TPU) of [B, 128k] costs more than an entire memory-bound
decode layer.  So the filtered path uses ONE sort (top-k and top-p both read
the same descending-sorted copy), and runtime ``lax.cond`` branches skip the
sort / penalties / logprobs work entirely when no row needs them — HLO
conditionals execute only the taken branch on device.

Randomness: each row draws from ``fold_in(PRNGKey(seed), step)`` where
``step`` is the row's output-token index — a request's sampled tokens are
reproducible regardless of how it was batched or preempted.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
# Top-k logprobs returned when logprobs are requested.  20 is the OpenAI
# API's documented top_logprobs maximum (the edge rejects anything larger),
# so no valid request is ever silently clamped (ADVICE r3).
TOPK_LOGPROBS = 20


class SampleOut(NamedTuple):
    tokens: jnp.ndarray  # [B] int32
    logprob: jnp.ndarray  # [B] f32 — raw log p(sampled token)
    top_ids: jnp.ndarray  # [B, TOPK_LOGPROBS] int32
    top_logprobs: jnp.ndarray  # [B, TOPK_LOGPROBS] f32


class SamplingParams(NamedTuple):
    """Per-row sampling state for one device step (host-built).

    Trailing fields default to None so pre-tenancy constructors keep
    working; None leaves vanish from the jit treedef, so engines that never
    use grammar masks / LoRA compile the exact same programs as before.
    """

    seeds: object  # [B] uint32
    steps: object  # [B] int32 — output-token index (rng stream position)
    temperature: object  # [B] f32
    top_k: object  # [B] int32
    top_p: object  # [B] f32
    freq_penalty: object  # [B] f32
    pres_penalty: object  # [B] f32
    counts: object  # [B, V] int16 output-token histogram
    need_logprobs: object  # [] bool
    # Grammar-constrained decoding (llm/tenancy/grammar.py): packed
    # admissible-token bitmask per row ([B, ceil(V/32)] uint32; bit i of
    # word i//32 = token i admissible) + an any-rows-masked scalar that
    # cond-skips the unpack entirely on unconstrained steps.
    mask_words: object = None  # [B, W] uint32 | None
    any_mask: object = None  # [] bool | None
    # Batched multi-LoRA (llm/tenancy/lora.py): per-row resident adapter
    # slot (-1 = base model), consumed by the fused decode program's
    # RaggedBatch construction (models/llama.py adapter_slots).
    adapter_slots: object = None  # [B] int32 | None


def _filtered_logits(
    scaled: jnp.ndarray,  # [B, V] temperature-scaled logits
    top_k: jnp.ndarray,  # [B] int32; 0 → disabled
    top_p: jnp.ndarray,  # [B] f32; 1.0 → disabled
) -> jnp.ndarray:
    """Apply top-k then top-p masks using a single descending sort."""
    B, V = scaled.shape
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]

    # top-k: mask everything below the k-th largest logit.
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [B, 1]

    # The top-k-masked copy stays sorted: positions >= k become NEG_INF.
    idx = jnp.arange(V, dtype=jnp.int32)[None, :]
    sorted_masked = jnp.where(idx < k[:, None], sorted_desc, NEG_INF)

    # top-p: keep the smallest prefix of the sorted distribution with
    # cumulative probability >= top_p (the kept set always includes argmax).
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    cutoff_count = jnp.sum(cum - probs_sorted < top_p[:, None], axis=-1)  # [B]
    cutoff_count = jnp.clip(cutoff_count, 1, V)
    thresh = jnp.take_along_axis(
        sorted_masked, (cutoff_count - 1)[:, None], axis=-1
    )

    scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    return jnp.where(scaled >= thresh, scaled, NEG_INF)


def _row_keys(seeds: jnp.ndarray, steps: jnp.ndarray) -> jnp.ndarray:
    """[B] independent PRNG keys: fold_in(PRNGKey(seed), step)."""

    def one(seed, step):
        return jax.random.fold_in(jax.random.PRNGKey(seed), step)

    return jax.vmap(one)(seeds.astype(jnp.uint32), steps.astype(jnp.uint32))


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] f32
    seeds: jnp.ndarray,  # [B] uint32 per-request seed
    steps: jnp.ndarray,  # [B] int32 output-token index (rng stream position)
    temperature: jnp.ndarray,  # [B] f32; 0 → greedy
    top_k: jnp.ndarray,  # [B] int32; 0 → disabled
    top_p: jnp.ndarray,  # [B] f32; 1.0 → disabled
    freq_penalty: jnp.ndarray,  # [B] f32; 0 → disabled
    pres_penalty: jnp.ndarray,  # [B] f32; 0 → disabled
    counts: jnp.ndarray,  # [B, V] int16 output-token counts (penalties)
    need_logprobs: jnp.ndarray,  # [] bool — any row wants logprobs
    mask_words: Optional[jnp.ndarray] = None,  # [B, ceil(V/32)] uint32
    any_mask: Optional[jnp.ndarray] = None,  # [] bool — any row masked
) -> SampleOut:
    """Sample one token per row; optionally raw logprobs of the choice.

    ``mask_words`` (grammar-constrained decoding) is a packed per-row
    admissible-token bitmask: inadmissible logits drop to NEG_INF BEFORE
    temperature/top-k/top-p, so greedy and seeded sampling both draw from
    exactly the admissible distribution (per-(seed, step) determinism is
    untouched — same key, same step, masked logits).  Rows whose mask is
    all-ones are unconstrained; the whole unpack is cond-skipped when
    ``any_mask`` is false.  Reported logprobs stay the RAW model
    distribution (OpenAI semantics), pre-penalty and pre-mask.
    """
    B, V = logits.shape

    def penalized() -> jnp.ndarray:
        c = counts.astype(jnp.float32)
        return logits - freq_penalty[:, None] * c - pres_penalty[:, None] * (
            c > 0
        )

    any_pen = jnp.any((freq_penalty != 0.0) | (pres_penalty != 0.0))
    eff = lax.cond(any_pen, penalized, lambda: logits)

    if mask_words is not None and any_mask is not None:

        def masked() -> jnp.ndarray:
            # [B, W] uint32 → [B, W, 32] bits → [B, W*32] → [:, :V]
            shifts = jnp.arange(32, dtype=jnp.uint32)
            bits = (mask_words[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
            admissible = bits.reshape(B, -1)[:, :V] != 0
            return jnp.where(admissible, eff, NEG_INF)

        eff = lax.cond(jnp.asarray(any_mask, jnp.bool_), masked, lambda: eff)

    greedy = jnp.argmax(eff, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]

    def cat(scaled: jnp.ndarray) -> jnp.ndarray:
        # Key derivation lives INSIDE the sampling branches: on an
        # all-greedy step (the decode hot path for benchmark and batch
        # traffic) the outer lax.cond takes the greedy branch and the
        # per-row threefry fold_in work is skipped entirely — at batch 256
        # x decode_steps per fused dispatch that was real device work spent
        # deriving keys nothing consumed.
        keys = _row_keys(seeds, steps)
        return jax.vmap(
            lambda k, row: jax.random.categorical(k, row)
        )(keys, scaled).astype(jnp.int32)

    def sample_filtered() -> jnp.ndarray:
        sampled = cat(_filtered_logits(eff / temp, top_k, top_p))
        return jnp.where(temperature <= 0.0, greedy, sampled)

    def sample_plain() -> jnp.ndarray:
        sampled = cat(eff / temp)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    need_filter = jnp.any(
        (temperature > 0.0) & ((top_k > 0) | (top_p < 1.0))
    )
    tokens = lax.cond(
        jnp.any(temperature > 0.0),
        lambda: lax.cond(need_filter, sample_filtered, sample_plain),
        lambda: greedy,
    )

    def with_logprobs():
        # Raw model distribution (pre-penalty, pre-temperature) — the
        # OpenAI-reported quantity.
        k = min(TOPK_LOGPROBS, V)
        logp = jax.nn.log_softmax(logits, axis=-1)
        chosen = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
        top_lp, top_ids = lax.top_k(logp, k)
        pad = TOPK_LOGPROBS - k  # tiny test vocabs: stable output width
        if pad:
            top_lp = jnp.pad(top_lp, ((0, 0), (0, pad)), constant_values=NEG_INF)
            top_ids = jnp.pad(top_ids, ((0, 0), (0, pad)))
        return chosen, top_ids.astype(jnp.int32), top_lp

    def without_logprobs():
        return (
            jnp.zeros((B,), jnp.float32),
            jnp.zeros((B, TOPK_LOGPROBS), jnp.int32),
            jnp.zeros((B, TOPK_LOGPROBS), jnp.float32),
        )

    chosen, top_ids, top_lp = lax.cond(
        need_logprobs, with_logprobs, without_logprobs
    )
    return SampleOut(tokens, chosen, top_ids, top_lp)
