"""Batched on-device token sampling: greedy / temperature / top-k / top-p.

All requests in a decode batch sample in one fused op with per-request
parameters as arrays — no host round-trip per request.  temperature == 0
means greedy regardless of the other knobs.

Cost shape matters here: this runs inside every decode step, and a full-vocab
sort (bitonic on TPU) of [B, 128k] costs more than an entire memory-bound
decode layer.  So the filtered path uses ONE sort (top-k and top-p both read
the same descending-sorted copy), and runtime ``lax.cond`` branches skip the
sort entirely when no row needs filtering and skip sampling when every row is
greedy — HLO conditionals execute only the taken branch on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _filtered_logits(
    scaled: jnp.ndarray,  # [B, V] temperature-scaled logits
    top_k: jnp.ndarray,  # [B] int32; 0 → disabled
    top_p: jnp.ndarray,  # [B] f32; 1.0 → disabled
) -> jnp.ndarray:
    """Apply top-k then top-p masks using a single descending sort."""
    B, V = scaled.shape
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]

    # top-k: mask everything below the k-th largest logit.
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [B, 1]

    # The top-k-masked copy stays sorted: positions >= k become NEG_INF.
    idx = jnp.arange(V, dtype=jnp.int32)[None, :]
    sorted_masked = jnp.where(idx < k[:, None], sorted_desc, NEG_INF)

    # top-p: keep the smallest prefix of the sorted distribution with
    # cumulative probability >= top_p (the kept set always includes argmax).
    probs_sorted = jax.nn.softmax(sorted_masked, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    cutoff_count = jnp.sum(cum - probs_sorted < top_p[:, None], axis=-1)  # [B]
    cutoff_count = jnp.clip(cutoff_count, 1, V)
    thresh = jnp.take_along_axis(
        sorted_masked, (cutoff_count - 1)[:, None], axis=-1
    )

    scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    return jnp.where(scaled >= thresh, scaled, NEG_INF)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] f32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] f32; 0 → greedy
    top_k: jnp.ndarray,  # [B] int32; 0 → disabled
    top_p: jnp.ndarray,  # [B] f32; 1.0 → disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]

    def sample_filtered() -> jnp.ndarray:
        scaled = _filtered_logits(logits / temp, top_k, top_p)
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
        return jnp.where(temperature <= 0.0, greedy, sampled)

    def sample_plain() -> jnp.ndarray:
        sampled = jax.random.categorical(rng, logits / temp, axis=-1)
        return jnp.where(temperature <= 0.0, greedy, sampled.astype(jnp.int32))

    need_filter = jnp.any(
        (temperature > 0.0) & ((top_k > 0) | (top_p < 1.0))
    )
    return lax.cond(
        jnp.any(temperature > 0.0),
        lambda: lax.cond(need_filter, sample_filtered, sample_plain),
        lambda: greedy,
    )
