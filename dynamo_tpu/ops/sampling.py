"""Batched on-device token sampling: greedy / temperature / top-k / top-p.

All requests in a decode batch sample in one fused op with per-request
parameters as arrays — no host round-trip per request.  temperature == 0
means greedy regardless of the other knobs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] f32
    rng: jax.Array,
    temperature: jnp.ndarray,  # [B] f32; 0 → greedy
    top_k: jnp.ndarray,  # [B] int32; 0 → disabled
    top_p: jnp.ndarray,  # [B] f32; 1.0 → disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask everything below the k-th largest logit.
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V]
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [B, 1]
    scaled = jnp.where(scaled >= kth, scaled, NEG_INF)

    # top-p: keep the smallest prefix of the sorted distribution with
    # cumulative probability >= top_p (the kept set always includes argmax).
    probs_sorted = jax.nn.softmax(jnp.sort(scaled, axis=-1)[:, ::-1], axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    cutoff_count = jnp.sum(cum - probs_sorted < top_p[:, None], axis=-1)  # [B]
    cutoff_count = jnp.clip(cutoff_count, 1, V)
    thresh = jnp.take_along_axis(
        jnp.sort(scaled, axis=-1)[:, ::-1], (cutoff_count - 1)[:, None], axis=-1
    )
    scaled = jnp.where(scaled >= thresh, scaled, NEG_INF)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
