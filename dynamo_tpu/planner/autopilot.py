"""SLO autopilot: trace-informed policies layered above the DecisionEngine.

The ``DecisionEngine`` (planner/policy.py) answers one question — how many
replicas per pool — from aggregate pressure ratios.  The autopilot adds
four policies that act on the RICHER signal planes the fleet already
publishes (docs/autopilot.md has the catalog and the signal→action table):

1. **Prefix warming before scaling** (``prefix_warming``): a sagging
   ``fleet_prefix_hit_rate`` means TTFT/KV pressure is cold-prefix
   pressure, not compute pressure.  Issue a ``kv_prefetch`` directive
   (promote + persist the hottest chains) and HOLD decode scale-ups for a
   grace window — warming is cheaper than a replica, and scaling first
   both wastes the replica and delays the warm.
2. **Measured-latency routing** (``measured_routing``): replace the static
   ``DEFAULT_TIER_WEIGHTS`` cost table in the KV router with weights
   derived from EWMA-smoothed measured restore/pull percentiles
   (``SignalSnapshot.restore_pct``), emitted as a ``set_tier_weights``
   directive.  The static table remains the cold-start fallback.
3. **Trace-identified migration victims** (``victim_migration``): pick
   ``migrate_out`` candidates from SUSTAINED per-worker p95 outliers in
   the per-hop latency view, instead of coldest-id.
4. **Drift-triggered retune** (``drift_retune``): when the fused-decode
   host-gap fraction (``SignalSnapshot.host_gap``) drifts out of band for
   N windows, emit a ``tune_decode`` sweep recommendation on the planner
   state surface.

Every policy is hysteresis/cooldown-damped (the Llumnix discipline the
DecisionEngine already follows: confirm streaks before acting, then go
quiet) and PURE — all state is explicit counters/EWMAs, no clock, no I/O —
so the same snapshot sequence always yields the same decision sequence and
the sim harness (planner/sim.py ``autopilot_smoke``) replays it exactly.

``Autopilot`` wraps a ``DecisionEngine`` and exposes the same
``decide(snapshot) -> Decision`` surface, so ``Planner``/``run_sim`` drive
either interchangeably.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from .policy import Action, Decision, DecisionEngine, noop
from .signals import SignalSnapshot

# Policy names — the metrics label set and the state() keys.
PREFIX_WARMING = "prefix_warming"
MEASURED_ROUTING = "measured_routing"
VICTIM_MIGRATION = "victim_migration"
DRIFT_RETUNE = "drift_retune"

POLICIES = (PREFIX_WARMING, MEASURED_ROUTING, VICTIM_MIGRATION, DRIFT_RETUNE)

# The cold-start fallback the measured weights are shaped against
# (llm/kv_router/indexer.py) — imported lazily in consumers to keep the
# planner importable without the llm stack; mirrored here as the canonical
# SHAPE (relative tier ratios) measured scaling preserves.
_STATIC_SHAPE = {"hbm": 1.0, "host": 0.75, "disk": 0.45, "objstore": 0.25}


@dataclass(frozen=True)
class AutopilotConfig:
    """Per-policy thresholds + damping (Llumnix discipline: every policy
    confirms over a streak, then cools down — a flapping signal produces
    zero directives by construction)."""

    # -- prefix warming ---------------------------------------------------
    # Fleet hit rate below this is cold-prefix pressure.
    warm_hit_rate_floor: float = 0.5
    warm_confirm_ticks: int = 2
    warm_cooldown_ticks: int = 12
    # Hottest chains to promote+persist per directive.
    warm_top_chains: int = 8
    # Decode scale-ups are deferred for this many ticks after a warming
    # directive — the window in which warming should absorb the pressure.
    warm_grace_ticks: int = 6

    # -- measured-latency routing ----------------------------------------
    # EWMA smoothing for the measured percentiles.
    route_ewma_alpha: float = 0.3
    # Restore p95 (ms) at which the host tier's weight halves — the scale
    # that turns a latency into a restore-cost discount.
    route_halving_ms: float = 50.0
    # Re-emit only when some weight moved by more than this fraction
    # relative to the last emitted table (drift gate, not a timer).
    route_retune_frac: float = 0.25
    route_cooldown_ticks: int = 10

    # -- victim migration -------------------------------------------------
    # A worker is an outlier when its p95 exceeds ratio × fleet median.
    outlier_ratio: float = 2.0
    outlier_confirm_ticks: int = 3
    # Minimum samples behind a worker's percentile row to trust it.
    outlier_min_samples: int = 8
    migrate_cooldown_ticks: int = 20

    # -- drift retune -----------------------------------------------------
    # Acceptable fused-decode host-gap band; sustained drift outside it
    # (either direction) triggers the sweep recommendation.
    gap_band_lo: float = 0.10
    gap_band_hi: float = 0.60
    gap_confirm_ticks: int = 4
    retune_cooldown_ticks: int = 30

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AutopilotConfig":
        kw = {f: d[f] for f in cls.__dataclass_fields__ if f in d}
        return cls(**kw)


def kv_prefetch(top_n: int, persist: bool, reason: str = "") -> Action:
    return Action(
        "kv_prefetch",
        params={"top_n": top_n, "persist": persist},
        reason=reason,
    )


def set_tier_weights(weights: Dict[str, float], reason: str = "") -> Action:
    return Action(
        "set_tier_weights",
        params={"weights": {t: round(w, 4) for t, w in weights.items()}},
        reason=reason,
    )


def migrate_out(worker_id: int, reason: str = "", **extra: Any) -> Action:
    return Action(
        "migrate_out", worker_id=worker_id, params=dict(extra) or None,
        reason=reason,
    )


def tune_decode(sweep: Dict[str, Any], reason: str = "") -> Action:
    return Action("tune_decode", params={"sweep": sweep}, reason=reason)


class Autopilot:
    """Deterministic policy layer above (and around) a ``DecisionEngine``.

    ``decide(snapshot)`` runs the wrapped engine, post-filters its actions
    (the warming policy may defer decode scale-ups), evaluates the four
    autopilot policies in a FIXED order, and returns one merged
    ``Decision`` — so every existing consumer (``Planner.tick``,
    ``run_sim``, the dry-run transcript) works unchanged.

    ``worker_view`` feeds the victim-migration policy: a callable
    returning ``{worker_id: {"ttft_p95_ms": .., "itl_p95_ms": .., "n": ..}}``
    (production: ``SignalCollector.worker_slo_view``; sim/tests: a
    synthetic provider).  None disables that policy.
    """

    def __init__(
        self,
        engine: Optional[DecisionEngine] = None,
        config: Optional[AutopilotConfig] = None,
        worker_view: Optional[Callable[[], Dict[int, Dict[str, Any]]]] = None,
    ):
        self.engine = engine or DecisionEngine()
        self.config = config or AutopilotConfig()
        self.worker_view = worker_view
        # Per-policy damping state — explicit, replayable.
        self._streak: Dict[str, int] = {p: 0 for p in POLICIES}
        self._cooldown: Dict[str, int] = {p: 0 for p in POLICIES}
        # Warming grace window: >0 defers decode scale-ups.
        self._warm_grace = 0
        # Measured-routing EWMAs + the last emitted weight table.
        self._ewma: Dict[str, float] = {}
        self._last_weights: Optional[Dict[str, float]] = None
        # Victim migration per-worker outlier streaks.
        self._outlier_streak: Dict[int, int] = {}
        # Drift retune: EWMA'd gap.
        self._gap_ewma: Optional[float] = None

    # -- shared damping helpers -------------------------------------------

    def _tick_cooldowns(self) -> None:
        for p in POLICIES:
            if self._cooldown[p] > 0:
                self._cooldown[p] -= 1
        if self._warm_grace > 0:
            self._warm_grace -= 1

    def _fire(self, policy: str, cooldown: int) -> bool:
        """A policy's confirmed trigger: True when it may act (and arms
        the cooldown); False (counted) when it is cooling down."""
        from .pmetrics import autopilot_metrics

        if self._cooldown[policy] > 0:
            autopilot_metrics.record_cooldown_skip(policy)
            return False
        self._cooldown[policy] = cooldown
        self._streak[policy] = 0
        autopilot_metrics.record_decision(policy)
        return True

    # -- policy 1: prefix warming -----------------------------------------

    def _warming(self, snap: SignalSnapshot) -> Optional[Action]:
        cfg = self.config
        rate = snap.fleet_prefix_hit_rate
        if rate is None or rate >= cfg.warm_hit_rate_floor:
            self._streak[PREFIX_WARMING] = 0
            return None
        self._streak[PREFIX_WARMING] += 1
        if self._streak[PREFIX_WARMING] < cfg.warm_confirm_ticks:
            return None
        if not self._fire(PREFIX_WARMING, cfg.warm_cooldown_ticks):
            return None
        self._warm_grace = cfg.warm_grace_ticks
        return kv_prefetch(
            cfg.warm_top_chains,
            persist=True,
            reason=f"fleet prefix hit rate {rate:.2f} < "
            f"{cfg.warm_hit_rate_floor:.2f} for "
            f"{cfg.warm_confirm_ticks} ticks: warm before scaling",
        )

    # -- policy 2: measured-latency routing --------------------------------

    def _measured_weights(self) -> Dict[str, float]:
        """Shape-preserving measured table: the static relative tier
        ratios scaled by the measured restore cost.  ``hbm`` is pinned at
        1.0 (a live block is free); the host weight decays with measured
        restore p95 (halving at ``route_halving_ms``), and the colder
        tiers keep their static ratio to host."""
        cfg = self.config
        r = self._ewma.get("restore_p95_ms", 0.0)
        # H/(H+r): 1.0 at zero measured latency (the static table), half
        # at route_halving_ms — bounded, monotone, never negative.
        scale = cfg.route_halving_ms / (cfg.route_halving_ms + max(0.0, r))
        host = _STATIC_SHAPE["host"] * scale
        return {
            "hbm": 1.0,
            "host": host,
            "disk": _STATIC_SHAPE["disk"] * scale,
            "objstore": _STATIC_SHAPE["objstore"] * scale,
        }

    def _routing(self, snap: SignalSnapshot) -> Optional[Action]:
        cfg = self.config
        pct = snap.restore_pct
        if not pct:
            return None  # cold start: the static table stays authoritative
        for key in ("restore_p95_ms", "pull_p95_ms"):
            v = pct.get(key)
            if isinstance(v, (int, float)):
                prev = self._ewma.get(key)
                self._ewma[key] = (
                    float(v)
                    if prev is None
                    else prev + cfg.route_ewma_alpha * (float(v) - prev)
                )
        if "restore_p95_ms" not in self._ewma:
            return None
        weights = self._measured_weights()
        last = self._last_weights
        if last is not None:
            drift = max(
                abs(weights[t] - last.get(t, 0.0)) / max(1e-9, last.get(t, 1.0))
                for t in weights
            )
            if drift <= cfg.route_retune_frac:
                return None  # inside the drift gate: keep the live table
        if not self._fire(MEASURED_ROUTING, cfg.route_cooldown_ticks):
            return None
        self._last_weights = dict(weights)
        return set_tier_weights(
            weights,
            reason="measured restore p95 "
            f"{self._ewma['restore_p95_ms']:.1f}ms -> live tier weights "
            "(static table is cold-start fallback)",
        )

    # -- policy 3: trace-identified migration victims ----------------------

    def _victims(self, snap: SignalSnapshot) -> Optional[Action]:
        cfg = self.config
        if self.worker_view is None:
            return None
        view = self.worker_view() or {}
        rows = {
            wid: row
            for wid, row in view.items()
            if isinstance(row.get("itl_p95_ms"), (int, float))
            and row.get("n", 0) >= cfg.outlier_min_samples
        }
        if len(rows) < 2:
            self._outlier_streak.clear()
            return None
        p95s = sorted(row["itl_p95_ms"] for row in rows.values())
        median = p95s[len(p95s) // 2]
        if median <= 0:
            return None
        outliers = {
            wid
            for wid, row in rows.items()
            if row["itl_p95_ms"] > cfg.outlier_ratio * median
        }
        # advance per-worker streaks; non-outliers (and vanished workers)
        # reset so a transient spike never accumulates across gaps
        for wid in list(self._outlier_streak):
            if wid not in outliers:
                del self._outlier_streak[wid]
        for wid in outliers:
            self._outlier_streak[wid] = self._outlier_streak.get(wid, 0) + 1
        sustained = [
            wid
            for wid, n in self._outlier_streak.items()
            if n >= cfg.outlier_confirm_ticks
        ]
        if not sustained:
            return None
        # worst sustained outlier; ties to lowest id (determinism)
        victim = max(sustained, key=lambda w: (rows[w]["itl_p95_ms"], -w))
        if not self._fire(VICTIM_MIGRATION, cfg.migrate_cooldown_ticks):
            return None
        self._outlier_streak.pop(victim, None)
        return migrate_out(
            victim,
            p95_ms=round(float(rows[victim]["itl_p95_ms"]), 3),
            fleet_median_ms=round(float(median), 3),
            reason=f"worker {victim} itl p95 "
            f"{rows[victim]['itl_p95_ms']:.0f}ms > {cfg.outlier_ratio}x "
            f"fleet median {median:.0f}ms for "
            f"{cfg.outlier_confirm_ticks} ticks",
        )

    # -- policy 4: drift-triggered retune ----------------------------------

    def _retune(self, snap: SignalSnapshot) -> Optional[Action]:
        cfg = self.config
        gap = snap.host_gap
        if gap is None:
            return None
        self._gap_ewma = (
            float(gap)
            if self._gap_ewma is None
            else self._gap_ewma
            + cfg.route_ewma_alpha * (float(gap) - self._gap_ewma)
        )
        g = self._gap_ewma
        if cfg.gap_band_lo <= g <= cfg.gap_band_hi:
            self._streak[DRIFT_RETUNE] = 0
            return None
        self._streak[DRIFT_RETUNE] += 1
        if self._streak[DRIFT_RETUNE] < cfg.gap_confirm_ticks:
            return None
        if not self._fire(DRIFT_RETUNE, cfg.retune_cooldown_ticks):
            return None
        host_bound = g > cfg.gap_band_hi
        # The sweep recommendation: which knobs to re-sweep and in which
        # direction — a tune_decode-style surface for the operator (or a
        # future closed-loop tuner), not an actuation.
        sweep = {
            "knob": "decode_burst" if host_bound else "prefill_chunk",
            "direction": "up" if host_bound else "down",
            "host_gap": round(g, 4),
            "band": [cfg.gap_band_lo, cfg.gap_band_hi],
        }
        return tune_decode(
            sweep,
            reason=f"host gap {g:.2f} outside "
            f"[{cfg.gap_band_lo:.2f}, {cfg.gap_band_hi:.2f}] for "
            f"{cfg.gap_confirm_ticks} windows: recommend "
            f"{sweep['knob']} sweep ({sweep['direction']})",
        )

    # -- the merged decision ----------------------------------------------

    def decide(self, snap: SignalSnapshot) -> Decision:
        from .pmetrics import autopilot_metrics

        self._tick_cooldowns()
        base = self.engine.decide(snap)
        # Post-filter: while a warming directive is in flight, decode
        # scale-UPS are deferred — warming is the cheaper remedy for
        # cold-prefix pressure, and the grace window is how the policy
        # proves it (scale-downs and prefill actions pass through).
        actions: List[Action] = []
        for a in base.actions:
            if (
                self._warm_grace > 0
                and a.kind == "scale_decode"
                and a.delta > 0
            ):
                autopilot_metrics.record_suppression(PREFIX_WARMING)
                actions.append(
                    noop(
                        "deferred: prefix warming in flight "
                        f"({self._warm_grace} ticks left)"
                    )
                )
                continue
            actions.append(a)
        # Policies in FIXED order (determinism), each self-damped.
        for policy_fn in (
            self._warming, self._routing, self._victims, self._retune
        ):
            action = policy_fn(snap)
            if action is not None:
                actions.append(action)
        # Collapse redundant noops when real actions exist.
        real = [a for a in actions if a.kind != "noop"]
        if real:
            actions = real
        else:
            actions = actions[:1] or [noop("in-band")]
        signals = dict(base.signals)
        if snap.fleet_prefix_hit_rate is not None:
            signals["fleet_prefix_hit_rate"] = round(
                snap.fleet_prefix_hit_rate, 4
            )
        if snap.host_gap is not None:
            signals["host_gap"] = round(snap.host_gap, 4)
        return Decision(
            tick=base.tick,
            actions=actions,
            pressures=base.pressures,
            signals=signals,
        )

    # -- introspection -----------------------------------------------------

    def state(self) -> Dict[str, Any]:
        """The planner /state surface's ``autopilot`` section — including
        the latest tune_decode-style recommendation inputs."""
        from .pmetrics import autopilot_metrics

        return {
            "engine": self.engine.state(),
            "streaks": dict(self._streak),
            "cooldowns": dict(self._cooldown),
            "warm_grace": self._warm_grace,
            "ewma": {k: round(v, 3) for k, v in self._ewma.items()},
            "gap_ewma": (
                round(self._gap_ewma, 4) if self._gap_ewma is not None else None
            ),
            "live_tier_weights": (
                dict(self._last_weights) if self._last_weights else None
            ),
            "metrics": autopilot_metrics.state(),
        }


__all__ = [
    "Autopilot",
    "AutopilotConfig",
    "DRIFT_RETUNE",
    "MEASURED_ROUTING",
    "POLICIES",
    "PREFIX_WARMING",
    "VICTIM_MIGRATION",
    "kv_prefetch",
    "migrate_out",
    "set_tier_weights",
    "tune_decode",
]
