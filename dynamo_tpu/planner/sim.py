"""Deterministic discrete-time simulator for planner policies.

A queueing model of a disaggregated fleet — a prefill pool (token
throughput per worker, FIFO) feeding a decode pool (slot-shared token
rate, KV occupancy) — driven by seedable arrival traces, ticked in lock
step with a ``DecisionEngine``.  No wall clock, no TPU, no asyncio: a
policy change is unit-testable in milliseconds, and the tier-1 smoke
(``python -m dynamo_tpu.planner sim --smoke``) proves the closed loop
(spike → scale-up → SLO restored → scale-down, zero flip-flops) on every
CI run.

Trace format (shared with ``benchmarks/loadgen.py --trace``): JSONL, one
arrival per line — ``{"t": seconds, "isl": prompt_tokens, "osl":
output_tokens}`` — so a bench trace replays in the simulator and a sim
trace drives a real deployment.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .policy import DECODE, PREFILL, Decision, DecisionEngine
from .signals import PoolStats, SignalSnapshot
from .signals import percentile as _pct

TRACE_SHAPES = ("poisson", "burst", "ramp")


@dataclass(frozen=True)
class Arrival:
    t: float
    isl: int = 3000
    osl: int = 150
    # Multi-tenant replay (llm/tenancy): route this request to a LoRA
    # adapter (the OpenAI ``model`` field) and/or constrain it with a JSON
    # schema (``response_format``).  Optional — single-tenant traces and
    # pre-tenancy consumers never see the keys.
    adapter: Optional[str] = None
    schema: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"t": round(self.t, 6), "isl": self.isl, "osl": self.osl}
        if self.adapter is not None:
            out["adapter"] = self.adapter
        if self.schema is not None:
            out["schema"] = self.schema
        return out


def gen_trace(
    shape: str,
    *,
    rate: float,
    duration_s: float,
    seed: int = 0,
    isl: int = 3000,
    osl: int = 150,
    spike_mult: float = 3.0,
    spike_start_s: Optional[float] = None,
    spike_end_s: Optional[float] = None,
) -> List[Arrival]:
    """Seedable arrival traces.

    - ``poisson``: constant-rate Poisson process (exp inter-arrivals).
    - ``burst``:   Poisson at ``rate``, but ``spike_mult``× inside
                   [spike_start, spike_end) (defaults: middle third) —
                   the planner acceptance scenario.
    - ``ramp``:    rate climbs linearly from ``rate`` to
                   ``spike_mult * rate`` across the trace.
    """
    if shape not in TRACE_SHAPES:
        raise ValueError(f"unknown trace shape {shape!r} (want {TRACE_SHAPES})")
    rng = random.Random(seed)
    lo = duration_s / 3.0 if spike_start_s is None else spike_start_s
    hi = 2.0 * duration_s / 3.0 if spike_end_s is None else spike_end_s
    out: List[Arrival] = []
    t = 0.0
    while True:
        if shape == "poisson":
            r = rate
        elif shape == "burst":
            r = rate * spike_mult if lo <= t < hi else rate
        else:  # ramp
            r = rate * (1.0 + (spike_mult - 1.0) * min(1.0, t / duration_s))
        t += rng.expovariate(r)
        if t >= duration_s:
            return out
        out.append(Arrival(t=t, isl=isl, osl=osl))


def write_trace(path: str, arrivals: Iterable[Arrival]) -> int:
    n = 0
    with open(path, "w") as f:
        for a in arrivals:
            f.write(json.dumps(a.to_dict()) + "\n")
            n += 1
    return n


def read_trace(path: str) -> List[Arrival]:
    out: List[Arrival] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            out.append(
                Arrival(
                    t=float(d["t"]),
                    isl=int(d.get("isl", 3000)),
                    osl=int(d.get("osl", 150)),
                    adapter=d.get("adapter"),
                    schema=d.get("schema"),
                )
            )
    out.sort(key=lambda a: a.t)
    return out


# ------------------------------------------------------------------ model


@dataclass(frozen=True)
class SimConfig:
    tick_s: float = 1.0
    # Capacity model (deliberately round numbers: the policy only sees
    # ratios, and tests assert behaviour, not absolute throughput).
    prefill_tokens_per_s: float = 6000.0  # per prefill worker
    decode_slots: int = 8  # per decode worker
    decode_tok_s_per_slot: float = 40.0
    kv_tokens_per_worker: int = 120_000
    # Scale actions take this many ticks to become capacity (pod spin-up);
    # flips are faster (the worker already holds weights).
    provision_ticks: int = 3
    flip_ticks: int = 1
    # Percentile window over recent TTFT/ITL samples.
    window_ticks: int = 10
    n_prefill: int = 1
    n_decode: int = 1
    # ---- optional prefix-population model (None disables — the default
    # keeps every pre-autopilot scenario byte-identical).  When enabled,
    # the fleet prefix-cache hit rate is a first-class state variable:
    # prefill work per request is isl x (1 - hit at admission), and each
    # decoding request's KV residency shrinks by its hit (the shared hot
    # base is counted once as ``hot_prefix_tokens``).  A hot-prefix SURGE
    # (a new population arriving at ``surge_start_s``) drops the hit rate
    # to ``surge_hit_rate``; it then recovers by ``natural_ramp_per_tick``
    # (caches refill from misses) — or by ``warm_ramp_per_tick`` once a
    # ``kv_prefetch`` warming directive lands (after ``warm_lag_ticks``).
    base_hit_rate: Optional[float] = None
    surge_hit_rate: float = 0.1
    surge_start_s: Optional[float] = None
    natural_ramp_per_tick: float = 0.01
    warm_ramp_per_tick: float = 0.15
    warm_lag_ticks: int = 2
    hot_prefix_tokens: int = 6000


@dataclass
class _Req:
    arrival: float
    isl: int
    osl: int
    prefill_left: float = 0.0
    decoded: int = 0
    ttft_s: Optional[float] = None
    # Prefix-cache hit fraction at admission (prefix model only): scales
    # both the prefill work and the request's private KV residency.
    hit: float = 0.0

    def __post_init__(self):
        self.prefill_left = float(self.isl) * (1.0 - self.hit)


class SimCluster:
    """The fleet + workload state machine; ``step()`` advances one tick."""

    def __init__(self, trace: List[Arrival], cfg: SimConfig):
        self.cfg = cfg
        self.trace = sorted(trace, key=lambda a: a.t)
        self._next_arrival = 0
        self.now = 0.0
        self.n_prefill = cfg.n_prefill
        self.n_decode = cfg.n_decode
        self.prefill_q: List[_Req] = []  # FIFO, head in service
        self.decoding: List[_Req] = []
        self.done: List[_Req] = []
        # (effective_at_tick, pool, delta)
        self._pending_scale: List[Tuple[int, str, int]] = []
        self.tick = 0
        # rolling (tick, value) samples for windowed percentiles
        self._ttft_samples: List[Tuple[int, float]] = []
        self._itl_samples: List[Tuple[int, float]] = []
        self._last_itl_ms = 0.0
        # prefix model state (inert when base_hit_rate is None)
        self.hit_rate: Optional[float] = cfg.base_hit_rate
        self._surged = False
        self._warm_at: Optional[int] = None  # tick a warming directive lands

    # -- capacity mutation (what actuation means in the sim) ---------------

    def schedule_scale(self, pool: str, target: int, *, flip: bool = False) -> None:
        cur = self.n_prefill if pool == PREFILL else self.n_decode
        pending = sum(
            d for _, p, d in self._pending_scale if p == pool
        )
        delta = target - (cur + pending)
        if delta == 0:
            return
        lag = self.cfg.flip_ticks if flip else self.cfg.provision_ticks
        self._pending_scale.append((self.tick + lag, pool, delta))

    def apply_actions(self, decision: Decision) -> None:
        for action in decision.actions:
            if action.kind in ("scale_prefill", "scale_decode"):
                self.schedule_scale(action.pool, action.target)
            elif action.kind == "kv_prefetch" and self.hit_rate is not None:
                # Warming directive: the promoted chains start landing
                # after a short lag, then the hit rate ramps fast.
                if self._warm_at is None:
                    self._warm_at = self.tick + self.cfg.warm_lag_ticks
            elif action.kind == "flip_role":
                donor = DECODE if action.pool == PREFILL else PREFILL
                donor_n = self.n_prefill if donor == PREFILL else self.n_decode
                recv_n = self.n_prefill if action.pool == PREFILL else self.n_decode
                self.schedule_scale(donor, donor_n - 1, flip=True)
                self.schedule_scale(action.pool, recv_n + 1, flip=True)

    def _apply_pending(self) -> None:
        due = [e for e in self._pending_scale if e[0] <= self.tick]
        self._pending_scale = [e for e in self._pending_scale if e[0] > self.tick]
        for _, pool, delta in due:
            if pool == PREFILL:
                self.n_prefill = max(0, self.n_prefill + delta)
            else:
                self.n_decode = max(0, self.n_decode + delta)

    # -- one tick ----------------------------------------------------------

    def step(self) -> None:
        cfg = self.cfg
        self.tick += 1
        self.now += cfg.tick_s
        self._apply_pending()
        # prefix-population dynamics (inert without the model)
        if self.hit_rate is not None:
            if (
                not self._surged
                and cfg.surge_start_s is not None
                and self.now >= cfg.surge_start_s
            ):
                # a NEW hot-prefix population arrives: caches run cold
                self.hit_rate = cfg.surge_hit_rate
                self._surged = True
            elif self.hit_rate < (cfg.base_hit_rate or 0.0):
                warmed = self._warm_at is not None and self.tick >= self._warm_at
                ramp = (
                    cfg.warm_ramp_per_tick
                    if warmed
                    else cfg.natural_ramp_per_tick
                )
                self.hit_rate = min(cfg.base_hit_rate, self.hit_rate + ramp)
        # arrivals up to now
        while (
            self._next_arrival < len(self.trace)
            and self.trace[self._next_arrival].t <= self.now
        ):
            a = self.trace[self._next_arrival]
            self.prefill_q.append(
                _Req(a.t, a.isl, a.osl, hit=self.hit_rate or 0.0)
            )
            self._next_arrival += 1
        # prefill: pooled token throughput, FIFO
        budget = self.n_prefill * cfg.prefill_tokens_per_s * cfg.tick_s
        budget0 = budget
        while self.prefill_q and budget > 0:
            head = self.prefill_q[0]
            use = min(budget, head.prefill_left)
            head.prefill_left -= use
            budget -= use
            if head.prefill_left <= 1e-9:
                self.prefill_q.pop(0)
                head.ttft_s = self.now - head.arrival
                self._ttft_samples.append((self.tick, head.ttft_s))
                self.decoding.append(head)
        # busy worker-equivalents this tick (the pool's true utilization —
        # feeds the policy's scale-down guard)
        per_worker = cfg.prefill_tokens_per_s * cfg.tick_s
        self._prefill_busy = (budget0 - budget) / per_worker if per_worker else 0.0
        # decode: total capacity shared across active sequences; per-seq
        # rate caps at the per-slot rate (underload ≠ faster than hardware)
        if self.decoding:
            total = self.n_decode * cfg.decode_slots * cfg.decode_tok_s_per_slot
            per_seq = min(
                cfg.decode_tok_s_per_slot,
                total / len(self.decoding) if total > 0 else 0.0,
            )
            self._last_itl_ms = 1000.0 / per_seq if per_seq > 0 else float("inf")
            if per_seq > 0:
                self._itl_samples.append((self.tick, self._last_itl_ms))
            made = int(per_seq * cfg.tick_s)
            still: List[_Req] = []
            for req in self.decoding:
                req.decoded += made
                (self.done if req.decoded >= req.osl else still).append(req)
            self.decoding = still
        # trim sample windows
        floor = self.tick - cfg.window_ticks
        self._ttft_samples = [s for s in self._ttft_samples if s[0] > floor]
        self._itl_samples = [s for s in self._itl_samples if s[0] > floor]

    # -- signal view -------------------------------------------------------

    def snapshot(self) -> SignalSnapshot:
        cfg = self.cfg
        kv_cap = max(1, self.n_decode * cfg.kv_tokens_per_worker)
        if self.hit_rate is None:
            kv_used = sum(r.isl + r.decoded for r in self.decoding)
        else:
            # Prefix model: each request's PRIVATE residency is the part
            # it computed itself; the shared hot base is counted once.
            kv_used = cfg.hot_prefix_tokens + sum(
                r.isl * (1.0 - r.hit) + r.decoded for r in self.decoding
            )
        slots = self.n_decode * cfg.decode_slots
        ttfts = [v for _, v in self._ttft_samples]
        itls = [v for _, v in self._itl_samples]
        # Slot counts are scaled ×1000 so fractional busy-worker
        # utilization survives PoolStats' integer fields.
        busy = getattr(self, "_prefill_busy", 0.0)
        prefill_pool = PoolStats(
            workers=tuple(range(self.n_prefill)),
            queue_depth=len(self.prefill_q),
            active_slots=int(busy * 1000),
            total_slots=self.n_prefill * 1000,
            per_worker_load={w: 0.0 for w in range(self.n_prefill)},
        )
        decode_pool = PoolStats(
            workers=tuple(range(1000, 1000 + self.n_decode)),
            queue_depth=max(0, len(self.decoding) - slots),
            active_slots=min(len(self.decoding), slots),
            total_slots=slots,
            kv_usage=min(1.0, kv_used / kv_cap),
            per_worker_load={
                w: min(1.0, len(self.decoding) / max(1, slots))
                for w in range(1000, 1000 + self.n_decode)
            },
        )
        return SignalSnapshot(
            t=self.now,
            pools={PREFILL: prefill_pool, DECODE: decode_pool},
            ttft_p95_ms=_pct(ttfts, 0.95) * 1e3 if ttfts else None,
            ttft_p50_ms=_pct(ttfts, 0.5) * 1e3 if ttfts else None,
            itl_p95_ms=_pct(itls, 0.95) if itls else None,
            itl_p50_ms=_pct(itls, 0.5) if itls else None,
            prefill_queue_depth=len(self.prefill_q),
            fleet_prefix_hit_rate=(
                round(self.hit_rate, 4) if self.hit_rate is not None else None
            ),
        )


# ------------------------------------------------------------------ runner


@dataclass
class SimReport:
    ticks: List[Dict[str, Any]] = field(default_factory=list)
    decisions: List[Decision] = field(default_factory=list)
    actuation_calls: int = 0
    completed: int = 0

    def scale_actions(self, pool: Optional[str] = None) -> List[Any]:
        out = []
        for d in self.decisions:
            for a in d.actions:
                if a.kind in ("scale_prefill", "scale_decode") and (
                    pool is None or a.pool == pool
                ):
                    out.append(a)
        return out

    def flip_flops(self, within_ticks: int = 10) -> int:
        """Opposite-direction scale actions on the same pool closer than
        ``within_ticks`` apart — the oscillation the hysteresis band must
        eliminate."""
        last: Dict[str, Tuple[int, int]] = {}  # pool → (tick, direction)
        count = 0
        for d in self.decisions:
            for a in d.actions:
                if a.kind not in ("scale_prefill", "scale_decode"):
                    continue
                direction = 1 if a.delta > 0 else -1
                prev = last.get(a.pool)
                if (
                    prev is not None
                    and prev[1] != direction
                    and d.tick - prev[0] < within_ticks
                ):
                    count += 1
                last[a.pool] = (d.tick, direction)
        return count

    def decision_dicts(self) -> List[Dict[str, Any]]:
        return [d.to_dict() for d in self.decisions]


def run_sim(
    trace: List[Arrival],
    engine: DecisionEngine,
    cfg: Optional[SimConfig] = None,
    *,
    ticks: Optional[int] = None,
    dry_run: bool = False,
    on_actuate=None,
) -> SimReport:
    """Tick the cluster + policy loop to trace end (+ drain margin).

    Live mode counts an actuation (and calls ``on_actuate(decision)`` if
    given) for every non-noop decision AND applies it to the model.
    Dry-run applies the SAME actions to the model (the scenario under
    evaluation is identical) but never actuates — so a dry-run must
    reproduce the live decision stream exactly, with
    ``actuation_calls == 0``.
    """
    cfg = cfg or SimConfig()
    cluster = SimCluster(trace, cfg)
    report = SimReport()
    horizon = ticks
    if horizon is None:
        last_t = trace[-1].t if trace else 0.0
        horizon = int(last_t / cfg.tick_s) + 4 * cfg.window_ticks
    for _ in range(horizon):
        cluster.step()
        snap = cluster.snapshot()
        decision = engine.decide(snap)
        report.decisions.append(decision)
        if not decision.is_noop:
            if not dry_run:
                if on_actuate is not None:
                    on_actuate(decision)
                report.actuation_calls += 1
            cluster.apply_actions(decision)
        report.ticks.append(
            {
                "tick": cluster.tick,
                "t": round(cluster.now, 3),
                "n_prefill": cluster.n_prefill,
                "n_decode": cluster.n_decode,
                "prefill_queue": len(cluster.prefill_q),
                "decoding": len(cluster.decoding),
                "ttft_p95_ms": snap.ttft_p95_ms,
                "itl_p95_ms": snap.itl_p95_ms,
                "actions": [a.to_dict() for a in decision.actions],
            }
        )
    report.completed = len(cluster.done)
    return report


# ------------------------------------------------------------------ smoke


def smoke(verbose: bool = False) -> Tuple[bool, str]:
    """The acceptance scenario at smoke scale: a seeded 3× spike must
    scale prefill up within a bounded number of ticks, restore TTFT p95
    under the SLO, scale back down afterwards, with zero flip-flops, and
    dry-run must emit the identical decision stream with no actuation."""
    from .policy import PolicyConfig, SloTargets

    # Baseline 1.2 req/s × 2000 prompt tokens = 2400 tok/s: comfortably
    # inside one prefill worker — the spike (3×) is the only pressure
    # event, so any reversal in the decision stream is a genuine policy
    # oscillation, not a cold-start transient.
    trace = gen_trace("burst", rate=1.2, duration_s=120.0, seed=7, isl=2000, osl=60)
    slo = SloTargets(ttft_p95_ms=2500.0, itl_p95_ms=200.0)
    # queue_high_per_worker=8: baseline Poisson clumping (a few queued
    # requests) stays inside the hysteresis band; only the spike's
    # sustained queue growth breaches it.
    cfg = PolicyConfig(
        max_prefill=6, max_decode=6, confirm_down_ticks=8,
        queue_high_per_worker=8.0,
    )
    sim_cfg = SimConfig(n_prefill=1, n_decode=2)

    live = run_sim(trace, DecisionEngine(slo, cfg), sim_cfg)
    dry = run_sim(trace, DecisionEngine(slo, cfg), sim_cfg, dry_run=True)

    ups = [a for a in live.scale_actions(PREFILL) if a.delta > 0]
    downs = [a for a in live.scale_actions(PREFILL) if a.delta < 0]
    spike_tick = int(120.0 / 3.0)  # burst default: spike starts at t/3
    checks = [
        (bool(ups), "planner never scaled prefill up during the spike"),
        (
            bool(ups) and min(d.tick for d in live.decisions
                              for a in d.actions if a.kind == "scale_prefill"
                              and a.delta > 0) <= spike_tick + 20,
            "scale-up not within 20 ticks of spike onset",
        ),
        (bool(downs), "planner never scaled back down after the spike"),
        (live.flip_flops() == 0, "flip-flop decisions inside hysteresis band"),
        (
            _recovered(live, slo.ttft_p95_ms),
            "TTFT p95 not restored below SLO after scale-up",
        ),
        (
            live.decision_dicts() == dry.decision_dicts(),
            "dry-run decisions diverged from live decisions",
        ),
        (dry.actuation_calls == 0, "dry-run issued actuation calls"),
    ]
    failures = [msg for ok, msg in checks if not ok]
    if verbose or failures:
        tail = live.ticks[-1]
        summary = (
            f"sim smoke: {len(live.decisions)} ticks, completed="
            f"{live.completed}, scale_ups={len(ups)} scale_downs={len(downs)} "
            f"flip_flops={live.flip_flops()} final_pools="
            f"(p={tail['n_prefill']}, d={tail['n_decode']})"
        )
    else:
        summary = "sim smoke ok"
    if failures:
        return False, summary + "; FAILED: " + "; ".join(failures)
    return True, summary


def autopilot_smoke(verbose: bool = False) -> Tuple[bool, str]:
    """The autopilot acceptance scenario (docs/autopilot.md): a seeded
    hot-prefix SURGE (a new prefix population at t=40s runs the fleet's
    caches cold) must trigger the warming policy — which restores TTFT p95
    while spending at least one FEWER decode scale-up than the
    pressure-only control engine — with zero flip-flops, and the decision
    stream must be deterministic across replays and identical in dry-run."""
    from .autopilot import Autopilot, AutopilotConfig
    from .policy import PolicyConfig, SloTargets

    # Steady 4 req/s x 2000-token prompts at 80% prefix hit = 1600 tok/s
    # of real prefill (a quarter of one worker).  The surge quadruples the
    # effective prefill AND inflates per-request decode KV residency 4.5x
    # — the pressure-only control reads that as "decode pool too small"
    # and buys replicas; the autopilot warms the prefixes instead.
    trace = gen_trace(
        "poisson", rate=4.0, duration_s=120.0, seed=11, isl=2000, osl=60
    )
    slo = SloTargets(ttft_p95_ms=2500.0, itl_p95_ms=200.0)
    cfg = PolicyConfig(
        max_prefill=6, max_decode=6, confirm_down_ticks=8,
        queue_high_per_worker=8.0,
    )
    sim_cfg = SimConfig(
        n_prefill=1, n_decode=2, kv_tokens_per_worker=12_000,
        base_hit_rate=0.8, surge_start_s=40.0,
    )

    def pilot() -> Autopilot:
        return Autopilot(DecisionEngine(slo, cfg), AutopilotConfig())

    control = run_sim(trace, DecisionEngine(slo, cfg), sim_cfg)
    live = run_sim(trace, pilot(), sim_cfg)
    replay = run_sim(trace, pilot(), sim_cfg)
    dry = run_sim(trace, pilot(), sim_cfg, dry_run=True)

    control_ups = [a for a in control.scale_actions(DECODE) if a.delta > 0]
    live_ups = [a for a in live.scale_actions(DECODE) if a.delta > 0]
    warmed = any(
        a.kind == "kv_prefetch" for d in live.decisions for a in d.actions
    )
    # Last windows with traffic still in them — the trailing drain ticks
    # report no TTFT at all, so index by observation rather than by tick.
    observed = [
        r["ttft_p95_ms"] for r in live.ticks if r["ttft_p95_ms"] is not None
    ]
    tail = observed[-10:]
    checks = [
        (warmed, "autopilot never issued a warming directive"),
        (
            bool(control_ups),
            "control never scaled decode (scenario exerts no pressure)",
        ),
        (
            len(control_ups) >= len(live_ups) + 1,
            f"warming saved no decode scale-up "
            f"(control={len(control_ups)}, autopilot={len(live_ups)})",
        ),
        (live.flip_flops() == 0, "flip-flop decisions under the autopilot"),
        (
            bool(tail) and max(tail) < slo.ttft_p95_ms,
            "TTFT p95 not restored under SLO after the surge",
        ),
        (
            live.decision_dicts() == replay.decision_dicts(),
            "decision stream diverged across seeded replays",
        ),
        (
            live.decision_dicts() == dry.decision_dicts(),
            "dry-run decisions diverged from live decisions",
        ),
        (dry.actuation_calls == 0, "dry-run issued actuation calls"),
    ]
    failures = [msg for ok, msg in checks if not ok]
    summary = (
        f"autopilot smoke: decode_ups control={len(control_ups)} "
        f"autopilot={len(live_ups)}, warmed={warmed}, "
        f"flip_flops={live.flip_flops()}, completed={live.completed}"
    )
    if failures:
        return False, summary + "; FAILED: " + "; ".join(failures)
    return True, summary if verbose else "autopilot smoke ok"


def _recovered(report: SimReport, ttft_slo_ms: float) -> bool:
    """After the last prefill scale-up, TTFT p95 must come back under SLO."""
    up_ticks = [
        d.tick
        for d in report.decisions
        for a in d.actions
        if a.kind == "scale_prefill" and a.delta > 0
    ]
    if not up_ticks:
        return False
    after = [
        row
        for row in report.ticks
        if row["tick"] > max(up_ticks) and row["ttft_p95_ms"] is not None
    ]
    return bool(after) and min(row["ttft_p95_ms"] for row in after) < ttft_slo_ms
