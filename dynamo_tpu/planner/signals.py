"""Planner signal plane: windowed, per-pool views of the metrics topics.

``SignalCollector`` consumes the same namespace subjects as
``MetricsAggregatorService`` (llm/metrics_service.py) — per-worker
``ForwardPassMetrics`` on ``kv_metrics`` and router hit-rate events on
``kv-hit-rate`` — plus edge-reported TTFT/ITL percentiles published by the
HTTP frontend (``slo_metrics``), and maintains per-pool views with
staleness eviction: a worker that stops publishing (or whose discovery
registration disappears) drops out of the pool view instead of pinning the
planner's picture of the fleet forever.

``StalenessTracker`` is the shared eviction primitive — the metrics
aggregator reuses it so its ``/metrics`` rows stop leaking dead workers
(the pre-planner bug: ``_metrics`` rows outlived discovery forever).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..llm.kv_router.protocols import ForwardPassMetrics
from ..llm.kv_router.publisher import KV_METRICS_TOPIC, unpack_message
from ..llm.kv_router.scheduler import KV_HIT_RATE_SUBJECT
from ..runtime.component import INSTANCE_PREFIX, instance_prefix
from ..runtime.health import QUARANTINE_PREFIX, worker_latency

logger = logging.getLogger(__name__)

# Namespace subject the HTTP edge publishes rolling TTFT/ITL percentiles on
# (llm/metrics.py EdgeSloPublisher → planner).
SLO_METRICS_TOPIC = "slo_metrics"


def percentile(xs: List[float], p: float) -> float:
    """Nearest-rank percentile (shared by the sim and collectors)."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


class StalenessTracker:
    """Dict of key → value where every entry carries a last-update stamp
    and expires ``ttl_s`` after its last put (None = never).

    Iteration (`items()`/`values()`) evicts expired entries first, so a
    consumer that only ever reads still converges — no background task
    required.  The clock is injectable for deterministic tests.
    """

    def __init__(
        self,
        ttl_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ttl_s = ttl_s
        self._clock = clock
        self._data: Dict[Any, Tuple[Any, float]] = {}

    def put(self, key: Any, value: Any) -> None:
        self._data[key] = (value, self._clock())

    def get(self, key: Any, default: Any = None) -> Any:
        entry = self._data.get(key)
        if entry is None:
            return default
        if self.ttl_s is not None and self._clock() - entry[1] > self.ttl_s:
            self._data.pop(key, None)
            return default
        return entry[0]

    def pop(self, key: Any, default: Any = None) -> Any:
        entry = self._data.pop(key, None)
        return default if entry is None else entry[0]

    def age(self, key: Any) -> Optional[float]:
        entry = self._data.get(key)
        return None if entry is None else self._clock() - entry[1]

    def evict_stale(self) -> List[Any]:
        """Drop entries older than ttl; returns the evicted keys."""
        if self.ttl_s is None:
            return []
        now = self._clock()
        dead = [k for k, (_, t) in self._data.items() if now - t > self.ttl_s]
        for k in dead:
            del self._data[k]
        return dead

    def items(self) -> Iterator[Tuple[Any, Any]]:
        self.evict_stale()
        for k, (v, _) in list(self._data.items()):
            yield k, v

    def values(self) -> Iterator[Any]:
        for _, v in self.items():
            yield v

    def keys(self) -> Iterator[Any]:
        for k, _ in self.items():
            yield k

    def __contains__(self, key: Any) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def __len__(self) -> int:
        self.evict_stale()
        return len(self._data)


_MISSING = object()


# ---------------------------------------------------------------- snapshots


@dataclass
class PoolStats:
    """Aggregated view over one worker pool (prefill or decode)."""

    workers: Tuple[int, ...] = ()
    queue_depth: int = 0  # requests waiting at the workers
    active_slots: int = 0
    total_slots: int = 0
    kv_usage: float = 0.0  # mean KV cache usage fraction
    per_worker_load: Dict[int, float] = field(default_factory=dict)

    @property
    def size(self) -> int:
        return len(self.workers)

    def coldest_worker(self) -> Optional[int]:
        """Deterministic flip victim: lowest load, ties to lowest id."""
        if not self.workers:
            return None
        return min(
            self.workers,
            key=lambda w: (self.per_worker_load.get(w, 0.0), w),
        )


@dataclass
class SignalSnapshot:
    """One planner tick's input — everything the policy may read."""

    t: float = 0.0
    pools: Dict[str, PoolStats] = field(default_factory=dict)
    ttft_p95_ms: Optional[float] = None
    itl_p95_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    itl_p50_ms: Optional[float] = None
    prefill_queue_depth: int = 0
    hit_isl_blocks: int = 0
    hit_overlap_blocks: int = 0
    # Worst brownout rung any live edge reports (llm/qos.py ladder): >0
    # means latency/queue signals are already brownout-suppressed — a
    # scale-down policy must not read that suppression as idle capacity.
    edge_brownout_rung: int = 0
    # Mean engine prefix-cache hit rate across live edges' kv_tier
    # publications (docs/kv_tiering.md), or None when no edge publishes
    # tier gauges.  A sagging fleet hit rate with tiered capacity free is
    # the planner's cue to warm prefixes (kv_prefetch) before scaling.
    fleet_prefix_hit_rate: Optional[float] = None
    # Measured per-hop restore/pull percentiles (ms) from the colocated
    # engine's kv_tier windows, worst-merged across live edges — keys like
    # ``restore_p95_ms``/``pull_p95_ms``.  The autopilot's measured-latency
    # routing EWMAs these into live tier weights (docs/autopilot.md);
    # None until an edge has observed at least one restore.
    restore_pct: Optional[Dict[str, float]] = None
    # Fused-decode host-gap fraction (engine dispatch_summary
    # ``host_gap_frac``), worst-merged across edges: sustained drift out
    # of band is the autopilot's tune_decode trigger.  None when no edge
    # colocates an engine.
    host_gap: Optional[float] = None

    def pool(self, name: str) -> PoolStats:
        return self.pools.get(name) or PoolStats()

    def to_dict(self) -> Dict[str, Any]:
        """Wire form (dry-run transcripts, /state, replay fixtures).
        Optional signals are omitted when absent — consumers must d.get()
        them (the established omit-when-absent idiom)."""
        d: Dict[str, Any] = {
            "t": self.t,
            "pools": {
                name: {
                    "workers": list(p.workers),
                    "queue_depth": p.queue_depth,
                    "active_slots": p.active_slots,
                    "total_slots": p.total_slots,
                    "kv_usage": p.kv_usage,
                    "per_worker_load": {
                        str(w): v for w, v in p.per_worker_load.items()
                    },
                }
                for name, p in self.pools.items()
            },
            "prefill_queue_depth": self.prefill_queue_depth,
            "hit_isl_blocks": self.hit_isl_blocks,
            "hit_overlap_blocks": self.hit_overlap_blocks,
            "edge_brownout_rung": self.edge_brownout_rung,
        }
        if self.ttft_p95_ms is not None:
            d["ttft_p95_ms"] = self.ttft_p95_ms
        if self.itl_p95_ms is not None:
            d["itl_p95_ms"] = self.itl_p95_ms
        if self.ttft_p50_ms is not None:
            d["ttft_p50_ms"] = self.ttft_p50_ms
        if self.itl_p50_ms is not None:
            d["itl_p50_ms"] = self.itl_p50_ms
        if self.fleet_prefix_hit_rate is not None:
            d["fleet_prefix_hit_rate"] = self.fleet_prefix_hit_rate
        if self.restore_pct is not None:
            d["restore_pct"] = dict(self.restore_pct)
        if self.host_gap is not None:
            d["host_gap"] = self.host_gap
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SignalSnapshot":
        pools = {
            name: PoolStats(
                workers=tuple(p.get("workers", ())),
                queue_depth=int(p.get("queue_depth", 0)),
                active_slots=int(p.get("active_slots", 0)),
                total_slots=int(p.get("total_slots", 0)),
                kv_usage=float(p.get("kv_usage", 0.0)),
                per_worker_load={
                    int(w): float(v)
                    for w, v in (p.get("per_worker_load") or {}).items()
                },
            )
            for name, p in (d.get("pools") or {}).items()
        }
        return cls(
            t=float(d.get("t", 0.0)),
            pools=pools,
            ttft_p95_ms=d.get("ttft_p95_ms"),
            itl_p95_ms=d.get("itl_p95_ms"),
            ttft_p50_ms=d.get("ttft_p50_ms"),
            itl_p50_ms=d.get("itl_p50_ms"),
            prefill_queue_depth=int(d.get("prefill_queue_depth", 0)),
            hit_isl_blocks=int(d.get("hit_isl_blocks", 0)),
            hit_overlap_blocks=int(d.get("hit_overlap_blocks", 0)),
            edge_brownout_rung=int(d.get("edge_brownout_rung", 0)),
            fleet_prefix_hit_rate=d.get("fleet_prefix_hit_rate"),
            restore_pct=d.get("restore_pct"),
            host_gap=d.get("host_gap"),
        )


def pool_stats(per_worker: Dict[int, ForwardPassMetrics]) -> PoolStats:
    """Fold per-worker ForwardPassMetrics into one PoolStats."""
    loads = {
        w: (m.request_active_slots / m.request_total_slots)
        if m.request_total_slots
        else 0.0
        for w, m in per_worker.items()
    }
    usages = [m.gpu_cache_usage_perc for m in per_worker.values()]
    return PoolStats(
        workers=tuple(sorted(per_worker)),
        queue_depth=sum(m.num_requests_waiting for m in per_worker.values()),
        active_slots=sum(m.request_active_slots for m in per_worker.values()),
        total_slots=sum(m.request_total_slots for m in per_worker.values()),
        kv_usage=sum(usages) / len(usages) if usages else 0.0,
        per_worker_load=loads,
    )


def classify_instance(key: str, info: Any) -> Optional[Tuple[int, str]]:
    """``instances/{ns}/{comp}/{ep}/{worker_id}`` → (worker_id, pool).

    Pool = the registration's ``metadata.role`` when present, else the
    endpoint name when it names a disagg role, else ``decode`` (an
    aggregated worker serves both phases; the decode pool is the
    conservative bucket for its KV/slot signals).
    """
    parts = key.split("/")
    if len(parts) != 5 or parts[0] != INSTANCE_PREFIX:
        return None
    try:
        worker_id = int(parts[4])
    except ValueError:
        return None
    role = None
    if isinstance(info, dict):
        role = (info.get("metadata") or {}).get("role")
    if not role:
        ep = parts[3]
        role = ep if ep in ("prefill", "decode") else "decode"
    return worker_id, role


# ---------------------------------------------------------------- collector


class SignalCollector:
    """Consume metrics/hit-rate/SLO topics into per-pool windowed views.

    Construction wants the namespace-scoped ``component`` whose workers
    publish (same as MetricsAggregatorService).  ``snapshot()`` is cheap
    and side-effect free apart from staleness eviction and (optionally)
    one hub queue-depth probe.
    """

    def __init__(
        self,
        component,
        model: Optional[str] = None,
        stale_after_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.component = component
        self.model = model
        self._clock = clock
        # worker_id → ForwardPassMetrics, TTL-evicted (same tracker the
        # metrics aggregator uses).
        self._metrics = StalenessTracker(ttl_s=stale_after_s, clock=clock)
        # edge id → slo snapshot dict
        self._edges = StalenessTracker(ttl_s=stale_after_s, clock=clock)
        # worker_id → pool name, maintained from the discovery watch; no
        # TTL (instance-gone events delete rows — lease expiry IS the
        # liveness signal here, exactly like every other watcher).
        self._pool_of: Dict[int, str] = {}
        # Watchdog quarantine view (runtime/health.py): quarantined workers
        # are excluded from the pool stats so the planner never counts a
        # draining straggler as usable capacity.
        self._quarantined: set = set()
        self._hit_isl = 0
        self._hit_overlap = 0
        self._tasks: List[asyncio.Task] = []
        self._subs: List[Any] = []
        self._watcher = None
        self._q_watcher = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> "SignalCollector":
        loop = asyncio.get_running_loop()
        m_sub = await self.component.subscribe(KV_METRICS_TOPIC)
        h_sub = await self.component.subscribe(KV_HIT_RATE_SUBJECT)
        e_sub = await self.component.namespace.subscribe(SLO_METRICS_TOPIC)
        self._subs = [m_sub, h_sub, e_sub]
        ns = self.component.namespace.name
        hub = self.component.runtime.hub
        self._watcher = await hub.watch_prefix(instance_prefix(ns))
        self._q_watcher = await hub.watch_prefix(QUARANTINE_PREFIX)
        self._tasks = [
            loop.create_task(self._consume_metrics(m_sub)),
            loop.create_task(self._consume_hit_rate(h_sub)),
            loop.create_task(self._consume_edges(e_sub)),
            loop.create_task(self._consume_instances()),
            loop.create_task(self._consume_quarantine()),
        ]
        await self._watcher.synced.wait()
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
        for sub in self._subs:
            if hasattr(sub, "aclose"):
                await sub.aclose()
        self._subs = []
        for attr in ("_watcher", "_q_watcher"):
            w = getattr(self, attr)
            if w is not None:
                await w.aclose()
                setattr(self, attr, None)

    # -- consumers ---------------------------------------------------------

    async def _consume_metrics(self, sub) -> None:
        try:
            async for msg in sub:
                payload = unpack_message(msg)
                try:
                    self._metrics.put(
                        payload["worker_id"],
                        ForwardPassMetrics.from_dict(payload["metrics"]),
                    )
                except (KeyError, TypeError):
                    logger.warning("malformed kv_metrics payload: %r", payload)
        except asyncio.CancelledError:
            pass

    async def _consume_hit_rate(self, sub) -> None:
        try:
            async for msg in sub:
                payload = unpack_message(msg)
                try:
                    self._hit_isl += payload["isl_blocks"]
                    self._hit_overlap += payload["overlap_blocks"]
                except (KeyError, TypeError):
                    pass
        except asyncio.CancelledError:
            pass

    async def _consume_edges(self, sub) -> None:
        try:
            async for msg in sub:
                payload = unpack_message(msg)
                if isinstance(payload, dict) and "edge_id" in payload:
                    self._edges.put(payload["edge_id"], payload)
        except asyncio.CancelledError:
            pass

    async def _watch_consume(self, attr: str, prefix: str, on_event, on_resync) -> None:
        """Shared watch-consume loop with hub-restart recovery: a dead
        watcher (e.g. ``HubSessionLost`` after a hub crash) is re-armed and
        the derived state fully resynced from a fresh snapshot — deletes
        missed during the outage must not leave phantom state (the same
        recovery shape as the routed Client's instance watch)."""
        hub = self.component.runtime.hub
        backoff = 0.1
        while True:
            try:
                async for event in getattr(self, attr):
                    backoff = 0.1
                    on_event(event)
                return  # closed cleanly (collector shutdown)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — re-arm below
                logger.warning(
                    "planner watch %r died; re-arming", prefix, exc_info=True
                )
            while True:
                try:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)
                    old = getattr(self, attr)
                    setattr(self, attr, None)
                    if old is not None:
                        try:
                            await old.aclose()
                        except asyncio.CancelledError:
                            raise
                        except Exception:  # noqa: BLE001 — dead watcher
                            pass
                    setattr(self, attr, await hub.watch_prefix(prefix))
                    on_resync(await hub.kv_get_prefix(prefix))
                    break
                except asyncio.CancelledError:
                    return
                except Exception:  # noqa: BLE001 — hub still down
                    logger.warning(
                        "planner watch %r re-arm failed; retrying", prefix
                    )

    # instance watch: pool membership
    def _apply_instance_event(self, event) -> None:
        parsed = classify_instance(event.key, event.value)
        if parsed is None:
            return
        worker_id, pool = parsed
        if event.type == "put":
            self._pool_of[worker_id] = pool
        else:  # lease expiry / deregistration: worker is GONE
            self._pool_of.pop(worker_id, None)
            self._metrics.pop(worker_id)

    def _resync_instances(self, snapshot: Dict[str, Any]) -> None:
        fresh: Dict[int, str] = {}
        for key, value in snapshot.items():
            parsed = classify_instance(key, value)
            if parsed is not None:
                fresh[parsed[0]] = parsed[1]
        for wid in set(self._pool_of) - set(fresh):
            self._metrics.pop(wid)
        self._pool_of = fresh

    async def _consume_instances(self) -> None:
        ns = self.component.namespace.name
        await self._watch_consume(
            "_watcher",
            instance_prefix(ns),
            self._apply_instance_event,
            self._resync_instances,
        )

    # quarantine watch: watchdog markers → pool-view exclusion
    def _apply_quarantine_event(self, event) -> None:
        try:
            wid = int(event.key[len(QUARANTINE_PREFIX):])
        except ValueError:
            return
        if event.type == "put":
            self._quarantined.add(wid)
        else:
            self._quarantined.discard(wid)

    def _resync_quarantine(self, snapshot: Dict[str, Any]) -> None:
        fresh = set()
        for key in snapshot:
            try:
                fresh.add(int(key[len(QUARANTINE_PREFIX):]))
            except ValueError:
                continue
        self._quarantined = fresh

    async def _consume_quarantine(self) -> None:
        await self._watch_consume(
            "_q_watcher",
            QUARANTINE_PREFIX,
            self._apply_quarantine_event,
            self._resync_quarantine,
        )

    # -- views -------------------------------------------------------------

    def evict_worker(self, worker_id: int) -> None:
        self._pool_of.pop(worker_id, None)
        self._metrics.pop(worker_id)

    def _edge_percentile(self, key: str) -> Optional[float]:
        """Merge the live edges' windows: worst (max) fresh percentile —
        the conservative read when several frontends report."""
        vals = [
            e[key]
            for e in self._edges.values()
            if isinstance(e.get(key), (int, float))
        ]
        return max(vals) if vals else None

    def _edge_mean(self, key: str) -> Optional[float]:
        """Mean of a fresh edge-published scalar (rates, not latencies —
        the representative read, unlike the worst-case percentile merge)."""
        vals = [
            e[key]
            for e in self._edges.values()
            if isinstance(e.get(key), (int, float))
        ]
        return sum(vals) / len(vals) if vals else None

    def worker_slo_view(self) -> Dict[int, Dict[str, Any]]:
        """Merged per-worker TTFT/ITL view from the live edges' slo_metrics
        publications (``workers`` key) — a planner-side HealthWatchdog's
        ``latency_source`` when it does not share a process with the
        routed client."""
        merged: Dict[int, Dict[str, Any]] = {}
        for edge in self._edges.values():
            for wid, row in (edge.get("workers") or {}).items():
                try:
                    wid = int(wid)
                except (TypeError, ValueError):
                    continue
                prev = merged.get(wid)
                if prev is None or row.get("n", 0) > prev.get("n", 0):
                    merged[wid] = row
        return merged

    async def snapshot(self) -> SignalSnapshot:
        by_pool: Dict[str, Dict[int, ForwardPassMetrics]] = {}
        for worker_id, m in self._metrics.items():
            if worker_id in self._quarantined:
                continue  # draining under watchdog quarantine: not capacity
            pool = self._pool_of.get(worker_id, "decode")
            by_pool.setdefault(pool, {})[worker_id] = m
        # Discovery-known workers that have not published metrics yet still
        # count toward pool SIZE (a just-scaled-up worker must not read as
        # "pool shrank" while it warms up).
        for worker_id, pool in self._pool_of.items():
            if worker_id in self._quarantined:
                continue
            by_pool.setdefault(pool, {}).setdefault(
                worker_id, ForwardPassMetrics()
            )
        queue_depth = 0
        if self.model is not None:
            from ..llm.disagg.prefill_queue import (  # lazy: llm imports planner
                prefill_queue_name,
            )
            try:
                queue_depth = await self.component.runtime.hub.q_len(
                    prefill_queue_name(self.model)
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — hub hiccup: signal degrades
                logger.warning("prefill queue depth probe failed")
        return SignalSnapshot(
            t=self._clock(),
            pools={p: pool_stats(w) for p, w in by_pool.items()},
            ttft_p95_ms=self._edge_percentile("ttft_p95_ms"),
            itl_p95_ms=self._edge_percentile("itl_p95_ms"),
            ttft_p50_ms=self._edge_percentile("ttft_p50_ms"),
            itl_p50_ms=self._edge_percentile("itl_p50_ms"),
            prefill_queue_depth=queue_depth,
            hit_isl_blocks=self._hit_isl,
            hit_overlap_blocks=self._hit_overlap,
            edge_brownout_rung=int(
                self._edge_percentile("brownout_rung") or 0
            ),
            fleet_prefix_hit_rate=self._edge_mean("prefix_hit_rate"),
            restore_pct=self._edge_restore_pct(),
            host_gap=self._edge_percentile("host_gap"),
        )

    def _edge_restore_pct(self) -> Optional[Dict[str, float]]:
        """Worst-merge (per key) the edges' measured restore/pull
        percentile dicts — the conservative read, matching the latency
        percentile merge above.  None until some edge publishes one."""
        merged: Dict[str, float] = {}
        for e in self._edges.values():
            pct = e.get("restore_pct")
            if not isinstance(pct, dict):
                continue
            for k, v in pct.items():
                if isinstance(v, (int, float)):
                    merged[k] = max(merged.get(k, float("-inf")), float(v))
        return merged or None


class EdgeSloPublisher:
    """HTTP-frontend side: periodically publish the edge's rolling
    TTFT/ITL percentiles (llm/metrics.py windows) on the namespace's
    ``slo_metrics`` subject — the planner's SLO input."""

    def __init__(
        self,
        namespace,
        metrics,
        edge_id: Optional[str] = None,
        interval: float = 2.0,
        qos=None,
    ):
        self.namespace = namespace
        self.metrics = metrics
        self.edge_id = edge_id or f"edge-{id(self):x}"
        self.interval = interval
        # Optional QosController (llm/qos.py): when the edge runs the
        # brownout ladder its current rung rides the publication, so the
        # planner can tell "latency is fine because the edge is already
        # degrading service" from "latency is fine" — scale-down decisions
        # should not read brownout-suppressed load as idle capacity.
        self.qos = qos
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "EdgeSloPublisher":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def publish_once(self) -> None:
        snap = self.metrics.edge_slo_snapshot()
        snap["edge_id"] = self.edge_id
        if self.qos is not None and self.qos.ladder is not None:
            snap["brownout_rung"] = self.qos.rung
        # Tiered-KV view (docs/kv_tiering.md): when an engine is colocated
        # (kv_tier_metrics source wired), the fleet's prefix-hit rate rides
        # the SLO publication so the planner can distinguish "TTFT is high
        # because prefixes run cold" from "TTFT is high because we're out
        # of compute".
        from ..llm.metrics import kv_tier_metrics

        tier = kv_tier_metrics.tier_summary()
        if tier:
            snap["prefix_hit_rate"] = float(tier.get("prefix_hit_rate", 0.0))
            snap["kv_tier"] = {
                t: dict(tier[t])
                for t in ("hbm", "host", "disk", "objstore")
                if t in tier
            }
        # Measured restore/pull percentiles + fused-decode host gap ride
        # the same publication (the autopilot's measured-latency routing
        # and tune_decode inputs) — omitted when nothing was measured, per
        # the wire idiom.
        restore_pct: Dict[str, float] = {}
        for name, window in (
            ("restore", kv_tier_metrics.restore_latency_ms),
            ("pull", kv_tier_metrics.pull_latency_ms),
        ):
            if len(window):
                restore_pct[f"{name}_p50_ms"] = round(window.percentile(0.5), 3)
                restore_pct[f"{name}_p95_ms"] = round(window.percentile(0.95), 3)
        if restore_pct:
            snap["restore_pct"] = restore_pct
        from ..llm.metrics import engine_dispatch_metrics

        gap = engine_dispatch_metrics.host_gap_frac()
        if gap is not None:
            snap["host_gap"] = gap
        # Per-worker TTFT/ITL p50s observed by this edge's routed clients
        # (runtime/health.py): the planner-side watchdog's straggler feed.
        workers = worker_latency.snapshot()
        if workers:
            snap["workers"] = {str(wid): row for wid, row in workers.items()}
        await self.namespace.publish(SLO_METRICS_TOPIC, snap)

    async def _run(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — transient hub hiccup: the
                # feed must survive it (a dead publisher silently disables
                # SLO-driven scaling for the life of the frontend).
                logger.warning("edge SLO publish failed; retrying", exc_info=True)
            try:
                await asyncio.sleep(self.interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
