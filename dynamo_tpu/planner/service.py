"""The Planner runtime component: collector → policy → actuator tick loop.

``Planner`` glues a ``SignalCollector`` to a ``DecisionEngine`` and an
``Actuator`` on a fixed tick interval, exposes its decisions/state on a
``/metrics`` + ``/state`` HTTP endpoint, and owns the ``--dry-run``
switch: in dry-run every decision is computed, logged, and counted
exactly as live — the actuator is simply never invoked.

Run it as a standalone component (``python -m dynamo_tpu.planner run
--hub …``), or embed it (the sdk service entry in
examples/llm/components.py boots one inside a worker graph).
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from aiohttp import web

from .actuate import Actuator
from .pmetrics import autopilot_metrics
from .pmetrics import metrics as planner_metrics
from .policy import Decision, DecisionEngine
from .signals import SignalCollector

logger = logging.getLogger(__name__)


class Planner:
    """Tick loop: snapshot → decide → (maybe) actuate.

    ``engine`` is anything with ``decide(snapshot) -> Decision`` and
    ``state() -> dict`` — a bare ``DecisionEngine`` or an ``Autopilot``
    (planner/autopilot.py) wrapping one."""

    def __init__(
        self,
        collector: SignalCollector,
        engine: DecisionEngine,
        actuator: Optional[Actuator] = None,
        interval_s: float = 2.0,
        dry_run: bool = False,
        history: int = 256,
    ):
        self.collector = collector
        self.engine = engine
        self.actuator = actuator
        self.interval_s = interval_s
        self.dry_run = dry_run
        self.decisions: List[Decision] = []
        self._history = history
        self._task: Optional[asyncio.Task] = None

    async def tick(self) -> Decision:
        snap = await self.collector.snapshot()
        decision = self.engine.decide(snap)
        self.decisions.append(decision)
        if len(self.decisions) > self._history:
            del self.decisions[: -self._history]
        planner_metrics.record_decision(decision)
        if decision.is_noop:
            return decision
        logger.info(
            "planner tick %d: %s (pressures %s)%s",
            decision.tick,
            [a.to_dict() for a in decision.actions],
            {k: round(v, 3) for k, v in decision.pressures.items()},
            " [dry-run: not actuated]" if self.dry_run else "",
        )
        if self.dry_run:
            planner_metrics.dry_run_suppressed_total += len(decision.actions)
        elif self.actuator is not None:
            try:
                await self.actuator.apply(decision)
                planner_metrics.actuations_total += 1
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — actuation failure must not kill the loop
                logger.exception("actuation failed for tick %d", decision.tick)
        return decision

    async def start(self) -> "Planner":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        try:
            while True:
                await self.tick()
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass
        except Exception:  # noqa: BLE001 — crash visible, loop ends
            logger.exception("planner loop crashed")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class PlannerHttp:
    """Planner decisions/state appended to a /metrics endpoint (plus a
    JSON /state view) — same exposition style as the metrics aggregator."""

    def __init__(self, planner: Planner, host: str = "0.0.0.0", port: int = 9092):
        self.planner = planner
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> "PlannerHttp":
        app = web.Application()
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/state", self._state)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # resolve port 0
            self.port = s.getsockname()[1]
            break
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=planner_metrics.render() + autopilot_metrics.render(),
            content_type="text/plain",
        )

    async def _state(self, request: web.Request) -> web.Response:
        state = planner_metrics.state()
        state["engine"] = self.planner.engine.state()
        state["dry_run"] = self.planner.dry_run
        return web.json_response(state)
