"""Planner observability: decisions/state as Prometheus text.

Module-level singleton in the style of ``runtime/resilience.py`` — any
``/metrics`` endpoint in the same process (HTTP edge, the planner's own
server) appends ``metrics.render()`` to its exposition output, so planner
decisions and pool targets are scrapeable wherever the planner runs.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ..labels import escape_label


class PlannerMetrics:
    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.ticks_total = 0
        self.decisions_total: Dict[str, int] = {}
        self.actuations_total = 0
        self.dry_run_suppressed_total = 0
        self.pool_targets: Dict[str, int] = {}
        self.pressures: Dict[str, float] = {}
        self.last_decision: Optional[Dict[str, Any]] = None

    def record_decision(self, decision) -> None:
        self.ticks_total += 1
        for action in decision.actions:
            self.decisions_total[action.kind] = (
                self.decisions_total.get(action.kind, 0) + 1
            )
            if action.kind in ("scale_prefill", "scale_decode"):
                self.pool_targets[action.pool] = action.target
        self.pressures = dict(decision.pressures)
        self.last_decision = decision.to_dict()

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_planner"
        lines = []

        def emit(name: str, help_: str, kind: str) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")

        emit("ticks_total", "Planner ticks evaluated", "counter")
        lines.append(f"{ns}_ticks_total {self.ticks_total}")
        emit("decisions_total", "Decisions by action kind", "counter")
        for kind, n in sorted(self.decisions_total.items()):
            lines.append(f'{ns}_decisions_total{{kind="{escape_label(kind)}"}} {n}')
        emit("actuations_total", "Actuator calls issued", "counter")
        lines.append(f"{ns}_actuations_total {self.actuations_total}")
        emit(
            "dry_run_suppressed_total",
            "Actions logged but not actuated (dry-run)",
            "counter",
        )
        lines.append(
            f"{ns}_dry_run_suppressed_total {self.dry_run_suppressed_total}"
        )
        emit("pool_target", "Most recent per-pool replica target", "gauge")
        for pool, target in sorted(self.pool_targets.items()):
            lines.append(f'{ns}_pool_target{{pool="{escape_label(pool)}"}} {target}')
        emit("pressure", "Per-pool pressure ratio (1.0 = at SLO)", "gauge")
        for pool, p in sorted(self.pressures.items()):
            lines.append(f'{ns}_pressure{{pool="{escape_label(pool)}"}} {p:.4f}')
        return "\n".join(lines) + "\n"

    def state(self) -> Dict[str, Any]:
        return {
            "ticks": self.ticks_total,
            "decisions": dict(self.decisions_total),
            "actuations": self.actuations_total,
            "pool_targets": dict(self.pool_targets),
            "pressures": dict(self.pressures),
            "last_decision": self.last_decision,
        }

    def state_json(self) -> str:
        return json.dumps(self.state())


metrics = PlannerMetrics()


class AutopilotMetrics:
    """Autopilot policy observability (planner/autopilot.py): per-policy
    decision/suppression/cooldown-skip counters — same module-singleton
    pattern as ``PlannerMetrics``, rendered as ``dynamo_tpu_autopilot_*``
    and appended to the planner's ``/metrics``."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        # policy name → count; policies register lazily on first event so
        # the label set stays exactly the autopilot's policy catalog.
        self.decisions_total: Dict[str, int] = {}
        self.suppressions_total: Dict[str, int] = {}
        self.cooldown_skips_total: Dict[str, int] = {}

    def record_decision(self, policy: str) -> None:
        self.decisions_total[policy] = self.decisions_total.get(policy, 0) + 1

    def record_suppression(self, policy: str) -> None:
        self.suppressions_total[policy] = (
            self.suppressions_total.get(policy, 0) + 1
        )

    def record_cooldown_skip(self, policy: str) -> None:
        self.cooldown_skips_total[policy] = (
            self.cooldown_skips_total.get(policy, 0) + 1
        )

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_autopilot"
        lines = []

        def emit(name: str, help_: str, values: Dict[str, int]) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            for policy, n in sorted(values.items()):
                lines.append(
                    f'{ns}_{name}{{policy="{escape_label(policy)}"}} {n}'
                )

        emit("decisions_total",
             "Autopilot actions emitted, by policy", self.decisions_total)
        emit("suppressions_total",
             "Engine actions deferred/suppressed by a policy (e.g. decode "
             "scale-up held during prefix warming)", self.suppressions_total)
        emit("cooldown_skips_total",
             "Confirmed policy triggers skipped because the policy was "
             "cooling down", self.cooldown_skips_total)
        return "\n".join(lines) + "\n"

    def state(self) -> Dict[str, Any]:
        return {
            "decisions": dict(self.decisions_total),
            "suppressions": dict(self.suppressions_total),
            "cooldown_skips": dict(self.cooldown_skips_total),
        }


autopilot_metrics = AutopilotMetrics()
