"""Hub-native supervisor: enacts ``planner/targets/*`` for non-kube fleets.

The planner's ``LocalActuator`` records desired per-pool replica counts in
the hub KV; on Kubernetes the CR reconciler drives pods to match, but a
bare-metal / dev-box deployment had nothing watching those keys (ROADMAP
leftover from PR 3).  ``Supervisor`` closes the loop: it watches
``planner/targets/{pool}``, keeps a ledger of the worker handles it owns
per pool, and calls pluggable ``spawn(pool)`` / ``stop(pool, handle,
drain)`` callables until the ledger matches the target.

Scale-down honours the actuator's ``drain`` hint ("migrate" by default):
``ProcessWorkerPool`` stops a worker with SIGTERM, and a cli worker's own
shutdown path (cli.py ``WorkerRoles.stop_decode``) migrates its live
sequences to a peer before exiting — so shrink cost is KV-transfer time,
not longest-sequence time.  Custom ``stop`` callables can instead drive
``llm.migration.request_migrate_out`` remotely before hard-killing.

Reconciliation is level-triggered (the watch only schedules a pass), so a
burst of target updates converges to the LAST value and a missed event is
repaired by the next poll resync.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Dict, List, Optional

from .actuate import TARGET_PREFIX

logger = logging.getLogger(__name__)

SpawnFn = Callable[[str], Awaitable[Any]]
StopFn = Callable[[str, Any, str], Awaitable[None]]


class Supervisor:
    def __init__(
        self,
        hub,
        spawn: SpawnFn,
        stop: StopFn,
        pools: Optional[List[str]] = None,
        resync_s: float = 5.0,
    ):
        self.hub = hub
        self._spawn = spawn
        self._stop = stop
        # None = supervise whatever pools appear under planner/targets/.
        self.pools = list(pools) if pools is not None else None
        self.resync_s = resync_s
        self.desired: Dict[str, int] = {}
        self.drain_hint: Dict[str, str] = {}
        self.handles: Dict[str, List[Any]] = {}
        self.spawned = 0
        self.stopped = 0
        self.crashed = 0
        self._dirty = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._watcher = None

    def owned(self, pool: str) -> int:
        return len(self.handles.get(pool, []))

    async def start(self) -> "Supervisor":
        self._watcher = await self.hub.watch_prefix(TARGET_PREFIX)
        self._task = asyncio.get_running_loop().create_task(self._run())
        await self._watcher.synced.wait()
        self._dirty.set()
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._watcher is not None:
            await self._watcher.aclose()
            self._watcher = None

    async def shutdown_workers(self) -> None:
        """Stop every owned worker (process exit path)."""
        for pool in list(self.handles):
            while self.handles[pool]:
                await self._stop_one(pool)

    # ------------------------------------------------------------- internals

    def _accept(self, pool: str, value: Any) -> None:
        if self.pools is not None and pool not in self.pools:
            return
        if not isinstance(value, dict):
            return
        try:
            self.desired[pool] = max(0, int(value.get("replicas", 0)))
        except (TypeError, ValueError):
            return
        self.drain_hint[pool] = str(value.get("drain", "migrate"))
        self._dirty.set()

    async def _run(self) -> None:
        try:
            consume = asyncio.ensure_future(self._consume_watch())
            while True:
                try:
                    await asyncio.wait_for(
                        self._dirty.wait(), timeout=self.resync_s
                    )
                except asyncio.TimeoutError:
                    # Periodic resync repairs missed/garbled watch events.
                    await self._resync()
                self._dirty.clear()
                try:
                    await self._reconcile()
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — keep supervising
                    logger.exception("supervisor reconcile failed")
        except asyncio.CancelledError:
            consume.cancel()
            raise

    async def _consume_watch(self) -> None:
        try:
            async for event in self._watcher:
                if event.type != "put":
                    continue
                pool = event.key[len(TARGET_PREFIX):]
                self._accept(pool, event.value)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — resync poll takes over
            logger.exception("supervisor target watch died; relying on resync")

    async def _resync(self) -> None:
        try:
            snapshot = await self.hub.kv_get_prefix(TARGET_PREFIX)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — hub unreachable; retry next tick
            return
        for key, value in snapshot.items():
            self._accept(key[len(TARGET_PREFIX):], value)

    @staticmethod
    def _handle_alive(handle) -> bool:
        """Liveness for process-like handles (Popen needs a poll() to
        refresh returncode); opaque handles count as alive."""
        poll = getattr(handle, "poll", None)
        if callable(poll):
            return poll() is None
        return getattr(handle, "returncode", None) is None

    async def _reconcile(self) -> None:
        for pool, want in sorted(self.desired.items()):
            handles = self.handles.setdefault(pool, [])
            # Crash repair: a worker that exited on its own (OOM, crash)
            # must not keep occupying a ledger slot, or the pool silently
            # runs below target forever.  The periodic resync tick drives
            # this even with no target changes.
            dead = [h for h in handles if not self._handle_alive(h)]
            if dead:
                handles[:] = [h for h in handles if self._handle_alive(h)]
                self.crashed += len(dead)
                logger.warning(
                    "supervisor: %d %s worker(s) died; respawning to %d",
                    len(dead), pool, want,
                )
            while len(handles) < want:
                handle = await self._spawn(pool)
                handles.append(handle)
                self.spawned += 1
                logger.info(
                    "supervisor: spawned %s worker (%d/%d)",
                    pool, len(handles), want,
                )
            while len(handles) > want:
                await self._stop_one(pool)

    async def _stop_one(self, pool: str) -> None:
        handle = self.handles[pool].pop()  # LIFO: newest worker goes first
        drain = self.drain_hint.get(pool, "migrate")
        try:
            await self._stop(pool, handle, drain)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a stuck worker must not wedge us
            logger.exception("supervisor: stop of a %s worker failed", pool)
        self.stopped += 1
        logger.info(
            "supervisor: stopped %s worker (%d left, drain=%s)",
            pool, len(self.handles[pool]), drain,
        )


class ProcessWorkerPool:
    """Subprocess adapters for the supervisor: one shell command template
    per pool (e.g. ``python -m dynamo_tpu.cli run in=dyn://d.w.g out=tpu
    --hub H:P --disagg decode``).  Stop sends SIGTERM and waits — cli
    workers migrate their live sequences out in their own shutdown path —
    then falls back to SIGKILL after ``term_grace_s``."""

    def __init__(self, cmd_templates: Dict[str, str], term_grace_s: float = 15.0):
        self.cmd_templates = dict(cmd_templates)
        self.term_grace_s = term_grace_s

    async def spawn(self, pool: str):
        cmd = self.cmd_templates.get(pool)
        if not cmd:
            raise ValueError(f"no spawn command configured for pool {pool!r}")
        proc = await asyncio.create_subprocess_shell(cmd)
        logger.info("spawned %s worker pid %s: %s", pool, proc.pid, cmd)
        return proc

    async def stop(self, pool: str, proc, drain: str) -> None:
        if proc.returncode is not None:
            return
        proc.terminate()  # worker's own shutdown drains (via migration)
        try:
            await asyncio.wait_for(proc.wait(), timeout=self.term_grace_s)
        except asyncio.TimeoutError:
            logger.warning(
                "%s worker pid %s ignored SIGTERM; killing", pool, proc.pid
            )
            proc.kill()
            await proc.wait()
