"""SLA-driven planner: autoscaling & prefill/decode rebalancing.

Four parts (docs/planner.md):
- signals  — ``SignalCollector``: windowed, per-pool views of the
             metrics/hit-rate/edge-SLO topics with staleness eviction.
- policy   — ``DecisionEngine``: pure, deterministic mapping from signal
             windows + SLO targets to scale/flip actions with hysteresis
             bands, cooldowns, and min/max bounds.
- actuate  — ``KubeActuator`` (CR replica patches through the existing
             reconciler path) and ``LocalActuator`` (+``RoleFlipWatcher``)
             for hub-native drain/role-flip; both behind ``--dry-run``.
- sim      — a deterministic discrete-time fleet simulator driven by
             seedable arrival traces; every policy is unit-testable and a
             sim smoke runs in tier-1 with no TPU.

Runnable: ``python -m dynamo_tpu.planner run --hub …`` / ``… sim``.
"""

from .actuate import KubeActuator, LocalActuator, RecordingActuator, RoleFlipWatcher
from .pmetrics import metrics as planner_metrics
from .policy import (
    Action,
    Decision,
    DecisionEngine,
    PolicyConfig,
    SloTargets,
)
from .service import Planner, PlannerHttp
from .signals import (
    EdgeSloPublisher,
    PoolStats,
    SignalCollector,
    SignalSnapshot,
    StalenessTracker,
)

__all__ = [
    "Action",
    "Decision",
    "DecisionEngine",
    "EdgeSloPublisher",
    "KubeActuator",
    "LocalActuator",
    "Planner",
    "PlannerHttp",
    "PolicyConfig",
    "PoolStats",
    "RecordingActuator",
    "RoleFlipWatcher",
    "SignalCollector",
    "SignalSnapshot",
    "SloTargets",
    "StalenessTracker",
    "planner_metrics",
]
