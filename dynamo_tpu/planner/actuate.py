"""Planner actuation: decisions → cluster mutations.

Two actuators behind one protocol:

- ``KubeActuator`` — patches ``DynamoTpuDeployment`` CR replica counts
  through the existing ``KubeApi``/``FakeKube`` surface; the reconciler
  (deploy/controller.py) then drives the fleet to the new count.  The
  planner never touches child Deployments/StatefulSets directly — the CR
  stays the single source of truth, exactly like a human running
  ``kubectl patch``.
- ``LocalActuator`` — for hub-native (non-k8s) deployments: records
  per-pool replica targets in the hub KV (``planner/targets/{pool}``, for
  a process supervisor to enact) and drives role flips by writing
  ``planner/roles/{worker_id}``; a ``RoleFlipWatcher`` running inside the
  worker process watches its own key, drains the current role, and
  switches.

``Planner`` (service.py) owns dry-run: with ``--dry-run`` decisions are
logged and counted but ``apply`` is never called — the decision stream is
byte-identical to a live run over the same signals (the acceptance
property the sim verifies).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Dict, List, Optional

from ..deploy.controller import GROUP
from ..runtime.transports.shard import hub_key, hub_prefix
from .policy import DECODE, PREFILL, Decision

logger = logging.getLogger(__name__)

ROLE_PREFIX = hub_prefix("planner", "roles")
TARGET_PREFIX = hub_prefix("planner", "targets")
DIRECTIVE_PREFIX = hub_prefix("planner", "directives")
CR_KIND = "DynamoTpuDeployment"


def target_key(pool: str) -> str:
    """Pool replica-target key (shard-map routed: DYN401)."""
    return hub_key("planner", "targets", pool)


def role_key(worker_id: int) -> str:
    """Per-worker role-flip key (shard-map routed: DYN401)."""
    return hub_key("planner", "roles", worker_id)


def directive_key(kind: str) -> str:
    """Autopilot directive slot, one per directive kind — last-writer-wins
    (the autopilot's per-policy cooldowns guarantee a consumer sees each
    directive for many ticks before it can be overwritten).  Shard-map
    routed: DYN401."""
    return hub_key("planner", "directives", kind)


class Actuator:
    """Protocol: apply one decision's actions to the world."""

    async def apply(self, decision: Decision) -> None:
        raise NotImplementedError


class RecordingActuator(Actuator):
    """Test/dry-run double: remembers every applied decision."""

    def __init__(self):
        self.applied: List[Decision] = []

    async def apply(self, decision: Decision) -> None:
        self.applied.append(decision)


# ------------------------------------------------------------------- kube


class KubeActuator(Actuator):
    """Patch CR ``spec.services[*].replicas`` via the KubeApi surface.

    ``service_names`` maps policy pool → CR service name (defaults to the
    renderer's conventional ``prefill``/``decode`` services).  A flip is
    expressed in k8s terms as a replica shuffle: −1 on the donor pool,
    +1 on the receiver — pods are cattle there; the hub-native drain/flip
    path is the LocalActuator's job.
    """

    def __init__(
        self,
        kube,
        cr_name: str,
        service_names: Optional[Dict[str, str]] = None,
    ):
        self.kube = kube
        self.cr_name = cr_name
        self.service_names = service_names or {
            PREFILL: "prefill",
            DECODE: "decode",
        }

    async def _get_cr(self) -> Optional[Dict[str, Any]]:
        for cr in await self.kube.list(CR_KIND):
            if cr["metadata"]["name"] == self.cr_name:
                return cr
        return None

    async def apply(self, decision: Decision) -> None:
        deltas: Dict[str, int] = {}
        targets: Dict[str, int] = {}
        for action in decision.actions:
            if action.kind in ("scale_prefill", "scale_decode"):
                targets[action.pool] = action.target
            elif action.kind == "flip_role":
                donor = DECODE if action.pool == PREFILL else PREFILL
                deltas[action.pool] = deltas.get(action.pool, 0) + 1
                deltas[donor] = deltas.get(donor, 0) - 1
        if not targets and not deltas:
            return
        cr = await self._get_cr()
        if cr is None:
            logger.warning("KubeActuator: CR %s not found", self.cr_name)
            return
        services = cr.setdefault("spec", {}).setdefault("services", {})
        changed = False
        for pool, target in targets.items():
            svc = self.service_names.get(pool, pool)
            if svc not in services:
                logger.warning(
                    "KubeActuator: CR %s has no service %r", self.cr_name, svc
                )
                continue
            if int(services[svc].get("replicas", 1)) != target:
                services[svc]["replicas"] = target
                changed = True
        for pool, delta in deltas.items():
            svc = self.service_names.get(pool, pool)
            if svc not in services:
                continue
            new = max(0, int(services[svc].get("replicas", 1)) + delta)
            services[svc]["replicas"] = new
            changed = True
        if not changed:
            return
        manifest = {
            "apiVersion": f"{GROUP}/v1alpha1",
            "kind": CR_KIND,
            "metadata": {"name": self.cr_name},
            "spec": cr["spec"],
        }
        # FakeKube stores whole manifests by (kind, name); KubeApi uses
        # server-side apply — both are idempotent under this patch shape.
        if cr["metadata"].get("namespace"):
            manifest["metadata"]["namespace"] = cr["metadata"]["namespace"]
        await self.kube.apply(manifest)
        logger.info(
            "KubeActuator: patched CR %s replicas (tick %d): %s",
            self.cr_name,
            decision.tick,
            {**targets, **{f"{k}{d:+d}": "" for k, d in deltas.items()}},
        )


# ------------------------------------------------------------------ local


class LocalActuator(Actuator):
    """Hub-native actuation: targets to KV, role flips to per-worker keys."""

    def __init__(self, hub):
        self.hub = hub

    async def apply(self, decision: Decision) -> None:
        for action in decision.actions:
            if action.kind in ("scale_prefill", "scale_decode"):
                await self.hub.kv_put(
                    target_key(action.pool),
                    {
                        "replicas": action.target,
                        "tick": decision.tick,
                        "reason": action.reason,
                        # Scale-down actuation hint for the supervisor
                        # (planner/supervisor.py): migrate live sequences
                        # off the victim before stopping it, so shrink cost
                        # is KV-transfer time, not sequence time
                        # (llm/migration; Llumnix).
                        "drain": "migrate",
                    },
                )
            elif action.kind == "flip_role":
                await self.hub.kv_put(
                    role_key(action.worker_id),
                    {
                        "role": action.pool,
                        "tick": decision.tick,
                        "reason": action.reason,
                    },
                )
            elif action.kind in (
                "kv_prefetch",
                "set_tier_weights",
                "migrate_out",
                "tune_decode",
            ):
                # Autopilot directives (planner/autopilot.py).  The
                # router's PlannerDirectiveWatcher enacts kv_prefetch and
                # set_tier_weights; migrate_out names a victim for the
                # supervisor/operator; tune_decode is a sweep
                # recommendation (also on the planner's /state surface).
                body: Dict[str, Any] = {
                    "kind": action.kind,
                    "tick": decision.tick,
                    "reason": action.reason,
                    "params": dict(action.params or {}),
                }
                if action.worker_id is not None:
                    body["worker_id"] = action.worker_id
                await self.hub.kv_put(directive_key(action.kind), body)


class RoleFlipWatcher:
    """Worker-side half of the flip protocol.

    Watches ``planner/roles/{worker_id}``; on a put naming a role other
    than the current one, runs the drain hook for the current role, then
    the switch hook for the new one, then acks by rewriting the key with
    ``acked: true`` (the planner and operators can observe completion).

    Hooks are plain async callables so the worker process decides what a
    flip means for it (cli.py wires decode→prefill: stop serving the
    decode endpoint, drain pending transfers, start a PrefillWorkerLoop).
    """

    def __init__(
        self,
        hub,
        worker_id: int,
        current_role: str,
        drain: Dict[str, Callable[[], Awaitable[None]]],
        switch: Dict[str, Callable[[], Awaitable[None]]],
    ):
        self.hub = hub
        self.worker_id = worker_id
        self.role = current_role
        self._drain = drain
        self._switch = switch
        self.flips = 0
        self._task: Optional[asyncio.Task] = None
        self._watcher = None

    @property
    def key(self) -> str:
        return role_key(self.worker_id)

    async def start(self) -> "RoleFlipWatcher":
        self._watcher = await self.hub.watch_prefix(self.key)
        self._task = asyncio.get_running_loop().create_task(self._run())
        await self._watcher.synced.wait()
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._watcher is not None:
            await self._watcher.aclose()
            self._watcher = None

    async def _run(self) -> None:
        try:
            async for event in self._watcher:
                if event.type != "put" or not isinstance(event.value, dict):
                    continue
                want = event.value.get("role")
                if not want or want == self.role or event.value.get("acked"):
                    continue
                await self._flip(want, event.value)
        except asyncio.CancelledError:
            pass

    async def _flip(self, want: str, request: Dict[str, Any]) -> None:
        old = self.role
        switch = self._switch.get(want)
        if switch is None:
            # No way to BECOME the requested role: refuse (no state
            # change, no ack) rather than lie about having flipped — the
            # planner keeps seeing the old role and can re-plan.
            logger.warning(
                "worker %d cannot flip %s→%s: no switch hook",
                self.worker_id, old, want,
            )
            return
        try:
            drain = self._drain.get(old)
            if drain is not None:
                await drain()
            await switch()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — failed flip must not kill worker
            logger.exception(
                "role flip %s→%s failed on worker %d", old, want, self.worker_id
            )
            return
        self.role = want
        self.flips += 1
        logger.info("worker %d flipped %s→%s", self.worker_id, old, want)
        try:
            await self.hub.kv_put(
                self.key, {**request, "acked": True, "from": old}
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — ack is best-effort
            logger.warning("role flip ack write failed")
