"""``python -m dynamo_tpu.planner`` — run the planner or its simulator.

  planner run --hub H:P [--namespace dynamo] [--component TpuWorker]
              [--model NAME] [--interval 2.0] [--dry-run] [--autopilot]
              [--kube CR_NAME [--k8s-namespace default]] [--port 9092]
  planner sim [--trace poisson|burst|ramp | --trace-file F.jsonl]
              [--rate 2.0] [--duration 120] [--seed 7] [--dry-run]
              [--out report.jsonl] [--smoke]
  planner supervise --hub H:P --spawn-decode CMD [--spawn-prefill CMD]
              [--resync 5.0]   # enact planner/targets/* without kube

SLO targets and policy bounds come from the layered config's ``planner``
section (runtime/config.py: ``DYN_PLANNER__TTFT_P95_MS=1500`` etc.),
overridable by the flags below.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys
from typing import Optional

from .policy import DecisionEngine, PolicyConfig, SloTargets
from .sim import (
    SimConfig,
    autopilot_smoke,
    gen_trace,
    read_trace,
    run_sim,
    smoke,
    write_trace,
)


def _engine_from_config(args) -> DecisionEngine:
    from ..runtime.config import RuntimeConfig

    section = dict(RuntimeConfig.from_layers().planner)
    for name in ("ttft_p95_ms", "itl_p95_ms", "kv_headroom"):
        val = getattr(args, f"slo_{name}", None)
        if val is not None:
            section[name] = val
    return DecisionEngine(
        SloTargets.from_dict(section), PolicyConfig.from_dict(section)
    )


async def _run(args) -> None:
    from ..runtime.component import DistributedRuntime
    from .actuate import KubeActuator, LocalActuator
    from .service import Planner, PlannerHttp
    from .signals import SignalCollector

    runtime = await DistributedRuntime.connect(args.hub)
    component = runtime.namespace(args.namespace).component(args.component)
    collector = await SignalCollector(
        component, model=args.model, stale_after_s=args.stale_after_s
    ).start()
    if args.kube:
        from ..deploy.controller import KubeApi

        actuator = KubeActuator(
            KubeApi(namespace=args.k8s_namespace), cr_name=args.kube
        )
    else:
        actuator = LocalActuator(runtime.hub)
    engine = _engine_from_config(args)
    if args.autopilot:
        from .autopilot import Autopilot

        engine = Autopilot(engine, worker_view=collector.worker_slo_view)
    planner = await Planner(
        collector,
        engine,
        actuator,
        interval_s=args.interval,
        dry_run=args.dry_run,
    ).start()
    http = await PlannerHttp(planner, host=args.host, port=args.port).start()
    print(
        f"planner on http://{args.host}:{http.port}/metrics "
        f"({'DRY-RUN' if args.dry_run else 'live'}, "
        f"{'kube:' + args.kube if args.kube else 'local'} actuation)",
        flush=True,
    )
    try:
        await _wait_for_signal()
    finally:
        await http.stop()
        await planner.stop()
        await collector.stop()
        if args.kube:
            await actuator.kube.close()
        await runtime.close()


async def _supervise(args) -> None:
    from ..runtime.transports.hub import HubClient
    from .supervisor import ProcessWorkerPool, Supervisor

    templates = {}
    if args.spawn_decode:
        templates["decode"] = args.spawn_decode
    if args.spawn_prefill:
        templates["prefill"] = args.spawn_prefill
    if not templates:
        raise SystemExit("supervise needs --spawn-decode and/or --spawn-prefill")
    pool = ProcessWorkerPool(templates, term_grace_s=args.term_grace_s)
    hub = await HubClient(args.hub).connect()
    sup = await Supervisor(
        hub, pool.spawn, pool.stop,
        pools=sorted(templates), resync_s=args.resync,
    ).start()
    print(
        f"supervisor enacting {sorted(templates)} targets from the hub "
        "(SIGTERM stops workers — they migrate sequences out themselves)",
        flush=True,
    )
    try:
        await _wait_for_signal()
    finally:
        await sup.stop()
        await sup.shutdown_workers()
        await hub.close()


async def _wait_for_signal() -> None:
    # SIGTERM must unwind through the finally blocks above — supervise's
    # shutdown_workers in particular; the default signal action would kill
    # the process with its worker subprocesses still running, and a
    # restarted supervisor's empty ledger would spawn a second fleet on
    # top of the orphans.
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    await stop.wait()


def _sim(args) -> int:
    if args.smoke:
        ok, summary = smoke(verbose=args.verbose)
        print(summary, flush=True)
        ap_ok, ap_summary = autopilot_smoke(verbose=args.verbose)
        print(ap_summary, flush=True)
        return 0 if ok and ap_ok else 1
    if args.trace_file:
        trace = read_trace(args.trace_file)
    else:
        trace = gen_trace(
            args.trace,
            rate=args.rate,
            duration_s=args.duration,
            seed=args.seed,
            isl=args.isl,
            osl=args.osl,
            spike_mult=args.spike_mult,
        )
    if args.trace_out:
        write_trace(args.trace_out, trace)
    engine = _engine_from_config(args)
    report = run_sim(
        trace,
        engine,
        SimConfig(n_prefill=args.n_prefill, n_decode=args.n_decode),
        dry_run=args.dry_run,
    )
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        for row in report.ticks:
            out.write(json.dumps(row) + "\n")
    finally:
        if args.out:
            out.close()
    print(
        f"sim: {len(report.ticks)} ticks, completed={report.completed}, "
        f"actuations={report.actuation_calls}, "
        f"flip_flops={report.flip_flops()}"
        + (" [dry-run]" if args.dry_run else ""),
        file=sys.stderr,
    )
    return 0


def _add_slo_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--slo-ttft-p95-ms", type=float, default=None,
                   dest="slo_ttft_p95_ms")
    p.add_argument("--slo-itl-p95-ms", type=float, default=None,
                   dest="slo_itl_p95_ms")
    p.add_argument("--slo-kv-headroom", type=float, default=None,
                   dest="slo_kv_headroom")
    p.add_argument("--dry-run", action="store_true", dest="dry_run",
                   help="compute + log decisions; never actuate")


def main(argv: Optional[list] = None) -> int:
    from ..runtime.logging_config import setup_logging

    setup_logging()
    parser = argparse.ArgumentParser(prog="dynamo-tpu-planner")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run the planner against a hub")
    p_run.add_argument("--hub", required=True)
    p_run.add_argument("--namespace", default="dynamo")
    p_run.add_argument("--component", default="TpuWorker")
    p_run.add_argument("--model", default=None,
                       help="model name (enables prefill queue-depth probe)")
    p_run.add_argument("--interval", type=float, default=2.0)
    p_run.add_argument("--stale-after-s", type=float, default=10.0,
                       dest="stale_after_s")
    p_run.add_argument("--kube", default=None, metavar="CR_NAME",
                       help="actuate by patching this DynamoTpuDeployment CR")
    p_run.add_argument("--k8s-namespace", default="default",
                       dest="k8s_namespace")
    p_run.add_argument("--host", default="0.0.0.0")
    p_run.add_argument("--port", type=int, default=9092)
    p_run.add_argument("--autopilot", action="store_true",
                       help="wrap the engine in the SLO autopilot "
                       "(warming / measured routing / victim / retune "
                       "policies; docs/autopilot.md)")
    _add_slo_flags(p_run)

    p_sim = sub.add_parser("sim", help="deterministic policy simulator")
    p_sim.add_argument("--trace", default="burst", choices=["poisson", "burst", "ramp"])
    p_sim.add_argument("--trace-file", default=None, dest="trace_file",
                       help="replay an arrival-trace JSONL (loadgen format)")
    p_sim.add_argument("--trace-out", default=None, dest="trace_out",
                       help="also write the generated trace here (JSONL)")
    p_sim.add_argument("--rate", type=float, default=2.0, help="req/s baseline")
    p_sim.add_argument("--duration", type=float, default=120.0)
    p_sim.add_argument("--seed", type=int, default=7)
    p_sim.add_argument("--isl", type=int, default=3000)
    p_sim.add_argument("--osl", type=int, default=150)
    p_sim.add_argument("--spike-mult", type=float, default=3.0, dest="spike_mult")
    p_sim.add_argument("--n-prefill", type=int, default=1, dest="n_prefill")
    p_sim.add_argument("--n-decode", type=int, default=2, dest="n_decode")
    p_sim.add_argument("--out", default=None, help="write per-tick JSONL here")
    p_sim.add_argument("--smoke", action="store_true",
                       help="run the CI acceptance scenario; exit 1 on failure")
    p_sim.add_argument("--verbose", action="store_true")
    _add_slo_flags(p_sim)

    p_sup = sub.add_parser(
        "supervise",
        help="hub-native supervisor: spawn/stop local workers to match "
        "planner/targets/* (non-kube deployments)",
    )
    p_sup.add_argument("--hub", required=True)
    p_sup.add_argument("--spawn-decode", default=None, dest="spawn_decode",
                       help="shell command that starts one decode worker")
    p_sup.add_argument("--spawn-prefill", default=None, dest="spawn_prefill",
                       help="shell command that starts one prefill worker")
    p_sup.add_argument("--resync", type=float, default=5.0,
                       help="periodic target-resync interval (s)")
    p_sup.add_argument("--term-grace-s", type=float, default=15.0,
                       dest="term_grace_s",
                       help="SIGTERM→SIGKILL grace for stopped workers")

    args = parser.parse_args(argv)
    if args.cmd == "sim":
        return _sim(args)
    try:
        asyncio.run(_supervise(args) if args.cmd == "supervise" else _run(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
