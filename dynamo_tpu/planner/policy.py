"""Planner policy: signal windows + SLO targets → scaling actions.

Reference semantics: the Dynamo Planner closes the loop between the metrics
plane and the worker fleet — watching queue depth and KV pressure and
rescaling the prefill vs decode pools.  The policy core here follows
DistServe (OSDI'24): goodput under TTFT/TPOT SLOs hinges on the
prefill:decode resource ratio tracking load, and Llumnix (OSDI'24):
reactive rescheduling needs hysteresis bands + cooldowns or the controller
oscillates.

``DecisionEngine`` is PURE and deterministic: it consumes a sequence of
``SignalSnapshot``s (planner/signals.py) and emits ``Decision``s.  All
state is explicit (breach streaks, cooldown counters), there is no clock
and no I/O — the same snapshot sequence always yields the same decision
sequence, which is what makes the sim harness (planner/sim.py) able to
unit-test every policy path with no TPU and no wall time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .signals import PoolStats, SignalSnapshot

PREFILL = "prefill"
DECODE = "decode"


@dataclass(frozen=True)
class SloTargets:
    """The operator's service-level objectives (config section ``planner``)."""

    ttft_p95_ms: float = 2000.0
    itl_p95_ms: float = 100.0
    # Fraction of decode-pool KV that must stay free; usage beyond
    # (1 - headroom) is scale-up pressure even when latency still holds
    # (KV exhaustion hits as preemption storms, after it is too late).
    kv_headroom: float = 0.15

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SloTargets":
        return cls(
            ttft_p95_ms=float(d.get("ttft_p95_ms", cls.ttft_p95_ms)),
            itl_p95_ms=float(d.get("itl_p95_ms", cls.itl_p95_ms)),
            kv_headroom=float(d.get("kv_headroom", cls.kv_headroom)),
        )


@dataclass(frozen=True)
class PolicyConfig:
    """Bounds + hysteresis shape (Llumnix: bands and cooldowns, not a
    bang-bang threshold)."""

    min_prefill: int = 1
    max_prefill: int = 8
    min_decode: int = 1
    max_decode: int = 8
    scale_step: int = 1
    # Hysteresis band around pressure 1.0 (= exactly at target): act only
    # above 1 + band_up / below 1 - band_down.  band_down is deliberately
    # wider — scaling down too eagerly is the classic oscillation driver.
    band_up: float = 0.15
    band_down: float = 0.40
    # Consecutive breaching ticks required before acting (debounce).
    confirm_up_ticks: int = 2
    confirm_down_ticks: int = 5
    # Ticks a pool stays quiet after any action on it.
    cooldown_ticks: int = 5
    # Prefill queue depth per prefill worker considered "at target".
    queue_high_per_worker: float = 4.0
    # Scale-down guard: latency signals are binary (SLO met / violated),
    # so a well-provisioned pool ALWAYS reads "cold" — shrinking on that
    # alone re-violates the SLO and oscillates.  A pool only shrinks when
    # the remaining workers would still sit under this utilization.
    down_util_guard: float = 0.85
    # Allow role flips when one pool is at its bound and the other is cold.
    flip_enabled: bool = True

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PolicyConfig":
        kw = {f: d[f] for f in cls.__dataclass_fields__ if f in d}
        return cls(**kw)


# ---------------------------------------------------------------- actions


@dataclass(frozen=True)
class Action:
    kind: str  # scale_prefill | scale_decode | flip_role | noop
    # ... plus the autopilot kinds (planner/autopilot.py): kv_prefetch |
    # set_tier_weights | migrate_out | tune_decode
    pool: str = ""
    delta: int = 0
    target: int = 0
    worker_id: Optional[int] = None  # flip_role / migrate_out
    reason: str = ""
    # Kind-specific payload for the autopilot kinds (warming top-N, the
    # measured tier-weight table, the retune sweep recommendation) —
    # omitted from the wire when absent, like every optional wire field.
    params: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"kind": self.kind, "reason": self.reason}
        if self.kind in ("scale_prefill", "scale_decode"):
            d.update(pool=self.pool, delta=self.delta, target=self.target)
        if self.kind == "flip_role":
            d.update(worker_id=self.worker_id, to_pool=self.pool)
        if self.kind == "migrate_out":
            d.update(worker_id=self.worker_id)
        if self.params is not None:
            d["params"] = dict(self.params)
        return d


def scale_prefill(delta: int, target: int, reason: str = "") -> Action:
    return Action("scale_prefill", PREFILL, delta, target, reason=reason)


def scale_decode(delta: int, target: int, reason: str = "") -> Action:
    return Action("scale_decode", DECODE, delta, target, reason=reason)


def flip_role(worker_id: int, to_pool: str, reason: str = "") -> Action:
    return Action("flip_role", to_pool, worker_id=worker_id, reason=reason)


def noop(reason: str = "") -> Action:
    return Action("noop", reason=reason)


@dataclass
class Decision:
    """One planner tick's output: the actions plus why (for /metrics,
    logs, and the dry-run transcript)."""

    tick: int
    actions: List[Action]
    pressures: Dict[str, float]
    signals: Dict[str, Any] = field(default_factory=dict)

    @property
    def is_noop(self) -> bool:
        return all(a.kind == "noop" for a in self.actions)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "tick": self.tick,
            "actions": [a.to_dict() for a in self.actions],
            "pressures": {k: round(v, 4) for k, v in self.pressures.items()},
            "signals": self.signals,
        }


# ---------------------------------------------------------------- engine


class DecisionEngine:
    """Maps signal windows + SLO targets to actions, with hysteresis.

    Per pool, pressure is a dimensionless ratio (1.0 = exactly at target):

      prefill:  max( ttft_p95 / slo.ttft_p95,
                     queue_depth / (queue_high_per_worker * n_prefill) )
      decode:   max( itl_p95 / slo.itl_p95,
                     kv_usage / (1 - slo.kv_headroom),
                     waiting / (queue_high_per_worker * n_decode) )

    An action fires only when pressure stays outside the hysteresis band
    for ``confirm_*_ticks`` consecutive ticks AND the pool's cooldown has
    expired; inside the band both streaks reset — a signal oscillating
    within the band produces zero actions by construction.
    """

    def __init__(
        self,
        slo: Optional[SloTargets] = None,
        config: Optional[PolicyConfig] = None,
    ):
        self.slo = slo or SloTargets()
        self.config = config or PolicyConfig()
        self.tick = 0
        self._up_streak: Dict[str, int] = {PREFILL: 0, DECODE: 0}
        self._down_streak: Dict[str, int] = {PREFILL: 0, DECODE: 0}
        self._cooldown: Dict[str, int] = {PREFILL: 0, DECODE: 0}

    # -- pressures ---------------------------------------------------------

    def prefill_pressure(self, snap: SignalSnapshot) -> float:
        pool = snap.pool(PREFILL)
        n = max(1, pool.size)
        ratios = [
            snap.prefill_queue_depth / (self.config.queue_high_per_worker * n)
        ]
        if snap.ttft_p95_ms is not None and self.slo.ttft_p95_ms > 0:
            ratios.append(snap.ttft_p95_ms / self.slo.ttft_p95_ms)
        return max(ratios)

    def decode_pressure(self, snap: SignalSnapshot) -> float:
        pool = snap.pool(DECODE)
        n = max(1, pool.size)
        ratios = [
            pool.kv_usage / max(1e-9, 1.0 - self.slo.kv_headroom),
            pool.queue_depth / (self.config.queue_high_per_worker * n),
        ]
        if snap.itl_p95_ms is not None and self.slo.itl_p95_ms > 0:
            ratios.append(snap.itl_p95_ms / self.slo.itl_p95_ms)
        return max(ratios)

    # -- decision ----------------------------------------------------------

    def decide(self, snap: SignalSnapshot) -> Decision:
        self.tick += 1
        cfg = self.config
        pressures = {
            PREFILL: self.prefill_pressure(snap),
            DECODE: self.decode_pressure(snap),
        }
        wants: Dict[str, int] = {}  # pool → +1 (up) / -1 (down) / 0
        for pool_name in (PREFILL, DECODE):
            if self._cooldown[pool_name] > 0:
                self._cooldown[pool_name] -= 1
            wants[pool_name] = self._update_streaks(
                pool_name, pressures[pool_name]
            )

        actions: List[Action] = []
        for pool_name in (PREFILL, DECODE):
            want = wants[pool_name]
            if want == 0:
                continue
            if self._cooldown[pool_name] > 0:
                continue  # confirmed breach, but the pool is in cooldown
            action = self._act(pool_name, want, snap, pressures)
            if action is not None:
                actions.append(action)
                # Any action (including a flip) quiets BOTH affected pools.
                self._cooldown[pool_name] = cfg.cooldown_ticks
                self._up_streak[pool_name] = 0
                self._down_streak[pool_name] = 0
                if action.kind == "flip_role":
                    other = DECODE if pool_name == PREFILL else PREFILL
                    self._cooldown[other] = cfg.cooldown_ticks
                    self._up_streak[other] = 0
                    self._down_streak[other] = 0

        if not actions:
            reason = "in-band" if max(pressures.values()) <= 1 + cfg.band_up \
                else "cooldown-or-unconfirmed"
            actions = [noop(reason)]
        return Decision(
            tick=self.tick,
            actions=actions,
            pressures=pressures,
            signals={
                "prefill_workers": snap.pool(PREFILL).size,
                "decode_workers": snap.pool(DECODE).size,
                "prefill_queue": snap.prefill_queue_depth,
                "ttft_p95_ms": snap.ttft_p95_ms,
                "itl_p95_ms": snap.itl_p95_ms,
                "kv_usage": round(snap.pool(DECODE).kv_usage, 4),
            },
        )

    def _update_streaks(self, pool: str, pressure: float) -> int:
        """Advance hysteresis streaks; returns the CONFIRMED direction."""
        cfg = self.config
        if pressure >= 1.0 + cfg.band_up:
            self._up_streak[pool] += 1
            self._down_streak[pool] = 0
        elif pressure <= 1.0 - cfg.band_down:
            self._down_streak[pool] += 1
            self._up_streak[pool] = 0
        else:  # inside the band: full reset — oscillation absorbed here
            self._up_streak[pool] = 0
            self._down_streak[pool] = 0
        if self._up_streak[pool] >= cfg.confirm_up_ticks:
            return +1
        if self._down_streak[pool] >= cfg.confirm_down_ticks:
            return -1
        return 0

    def _bounds(self, pool: str) -> Tuple[int, int]:
        cfg = self.config
        return (
            (cfg.min_prefill, cfg.max_prefill)
            if pool == PREFILL
            else (cfg.min_decode, cfg.max_decode)
        )

    def _act(
        self,
        pool: str,
        want: int,
        snap: SignalSnapshot,
        pressures: Dict[str, float],
    ) -> Optional[Action]:
        cfg = self.config
        lo, hi = self._bounds(pool)
        stats = snap.pool(pool)
        size = stats.size
        if want < 0 and size > lo and stats.total_slots > 0:
            util = stats.active_slots / stats.total_slots
            survivors = max(1, size - cfg.scale_step)
            if util * size / survivors > cfg.down_util_guard:
                return None  # remaining pool couldn't absorb current load
        target = max(lo, min(hi, size + want * cfg.scale_step))
        maker = scale_prefill if pool == PREFILL else scale_decode
        # The emitted action must AGREE with the confirmed direction: a
        # pool sitting above max (a flip pushed it there) with up-pressure
        # must not "clamp down" to the bound — that would shrink an
        # overloaded pool and oscillate forever against the next flip.
        if (want > 0 and target > size) or (want < 0 and target < size):
            return maker(
                target - size,
                target,
                reason=f"{pool} pressure {pressures[pool]:.2f} "
                f"{'above' if want > 0 else 'below'} band",
            )
        # At a bound.  Scale-up blocked at max: steal a worker from the
        # other pool when it is provably cold (DistServe ratio rebalance).
        if want > 0 and cfg.flip_enabled:
            other = DECODE if pool == PREFILL else PREFILL
            other_lo, _ = self._bounds(other)
            other_pool = snap.pool(other)
            if (
                other_pool.size > other_lo
                and pressures[other] <= 1.0 - cfg.band_down
                # Donor untouched this tick: a decision must never carry
                # both a scale action and a flip on the same pool (the
                # actuators would compound them differently).
                and self._cooldown[other] == 0
            ):
                victim = other_pool.coldest_worker()
                if victim is not None:
                    return flip_role(
                        victim,
                        pool,
                        reason=f"{pool} at max ({hi}) and {other} cold "
                        f"({pressures[other]:.2f})",
                    )
        return None

    # -- introspection -----------------------------------------------------

    def state(self) -> Dict[str, Any]:
        return {
            "tick": self.tick,
            "up_streak": dict(self._up_streak),
            "down_streak": dict(self._down_streak),
            "cooldown": dict(self._cooldown),
        }


__all__ = [
    "Action",
    "Decision",
    "DecisionEngine",
    "PolicyConfig",
    "PoolStats",
    "SloTargets",
    "flip_role",
    "noop",
    "scale_decode",
    "scale_prefill",
]
