"""Model architecture configs for the native JAX engine.

The reference ships no model code of its own — architecture is whatever the
wrapped engine (vLLM/sglang) loads from HF config.json; its
``ModelDeploymentCard`` (lib/llm/src/model_card/model.rs:15-201) carries only
serving metadata.  The TPU build executes models natively, so the architecture
config lives here, convertible from a HF ``config.json``.

Dense Llama-family (Llama 2/3, DeepSeek-R1-Distill-Llama) plus Mixtral-style
MoE fields.  All shapes chosen to map well onto the MXU: head_dim multiples of
128 where the checkpoints allow, bfloat16 activations by default.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 500000.0
    rope_scaling: Optional[Dict[str, Any]] = None
    rms_norm_eps: float = 1e-5
    max_position: int = 131072
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"  # activation/weight dtype (string: jax-free config)
    # MoE (Mixtral / DeepSeek-V2-style shared+routed experts; 0 experts = dense)
    num_experts: int = 0
    num_experts_per_token: int = 0
    num_shared_experts: int = 0
    moe_intermediate_size: int = 0
    eos_token_ids: tuple = ()
    # Qwen2-style attention: q/k/v projections carry biases.
    qkv_bias: bool = False

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def with_overrides(self, **kw) -> "ModelConfig":
        return replace(self, **kw)

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any], name: str = "") -> "ModelConfig":
        """Convert a HuggingFace ``config.json`` dict (llama/mixtral style)."""
        num_heads = cfg["num_attention_heads"]
        head_dim = cfg.get("head_dim") or cfg["hidden_size"] // num_heads
        # Qwen2 checkpoints carry q/k/v biases but don't always write an
        # explicit attention_bias flag.
        qkv_bias = bool(
            cfg.get("attention_bias", cfg.get("model_type") == "qwen2")
        )
        eos = cfg.get("eos_token_id", ())
        if isinstance(eos, int):
            eos = (eos,)
        return cls(
            name=name or cfg.get("_name_or_path", "hf-model"),
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=num_heads,
            num_kv_heads=cfg.get("num_key_value_heads", num_heads),
            head_dim=head_dim,
            intermediate_size=cfg["intermediate_size"],
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=cfg.get("rope_scaling"),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            num_experts=cfg.get("num_local_experts", 0),
            num_experts_per_token=cfg.get("num_experts_per_tok", 0),
            moe_intermediate_size=cfg.get("intermediate_size", 0)
            if cfg.get("num_local_experts")
            else 0,
            eos_token_ids=tuple(eos),
            qkv_bias=qkv_bias,
        )

    @classmethod
    def from_local_path(cls, path: str, name: str = "") -> "ModelConfig":
        with open(os.path.join(path, "config.json")) as f:
            return cls.from_hf_config(json.load(f), name=name or os.path.basename(path))


_REGISTRY: Dict[str, ModelConfig] = {}


def register_config(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_config(name: str) -> ModelConfig:
    if name in _REGISTRY:
        return _REGISTRY[name]
    if os.path.isdir(name):
        return ModelConfig.from_local_path(name)
    raise KeyError(f"unknown model config: {name!r}; known: {sorted(_REGISTRY)}")


# ---------------------------------------------------------------------------
# Presets.  llama-3.1-8b matches DeepSeek-R1-Distill-Llama-8B (the north-star
# model, BASELINE.md): same architecture, distilled weights.
# ---------------------------------------------------------------------------

register_config(
    ModelConfig(
        name="llama-3.1-8b",
        vocab_size=128256,
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14336,
        rope_theta=500000.0,
        eos_token_ids=(128001, 128008, 128009),
    )
)

register_config(
    ModelConfig(
        name="llama-3.1-70b",
        vocab_size=128256,
        hidden_size=8192,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=28672,
        rope_theta=500000.0,
        eos_token_ids=(128001, 128008, 128009),
    )
)

register_config(
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14336,
        rope_theta=1e6,
        num_experts=8,
        num_experts_per_token=2,
        moe_intermediate_size=14336,
        eos_token_ids=(2,),
    )
)

register_config(
    ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152064,
        hidden_size=3584,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        intermediate_size=18944,
        rope_theta=1e6,
        tie_word_embeddings=False,
        qkv_bias=True,
        eos_token_ids=(151643, 151645),
    )
)

# Tiny configs for CPU tests / CI — shapes still MXU-friendly multiples.
register_config(
    ModelConfig(
        name="debug-tiny",
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        rope_theta=10000.0,
        max_position=2048,
        eos_token_ids=(0,),
    )
)

register_config(
    ModelConfig(
        name="debug-tiny-moe",
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        rope_theta=10000.0,
        max_position=2048,
        num_experts=4,
        num_experts_per_token=2,
        moe_intermediate_size=128,
        eos_token_ids=(0,),
    )
)
