"""GGUF container support: parse model metadata, tensors, and the embedded
tokenizer from a single .gguf file.

Reference counterpart: lib/llm/src/gguf/{mod,content,metadata}.rs (~1,030
LoC) — the reference parses GGUF to extract the ModelDeploymentCard's config
and tokenizer when a user points at a .gguf checkpoint.  Semantics matched
here: same header/metadata/tensor-directory layout, same `general.*` /
`llama.*` / `tokenizer.ggml.*` keys.  The TPU build additionally loads the
WEIGHTS (the reference delegates that to vLLM): unquantized F32/F16/BF16
tensors map straight into the stacked params tree; quantized ggml types are
recognized and rejected with a clear error (dequant kernels are not ported —
bf16 is the MXU-native serving dtype).

Format (spec: ggml/docs/gguf.md):
  u32 magic "GGUF" | u32 version (2|3) | u64 n_tensors | u64 n_kv
  n_kv * (string key | u32 type | value)
  n_tensors * (string name | u32 n_dims | u64 dims[n] | u32 ggml_type | u64 offset)
  padding to `general.alignment` (default 32) | tensor data

A minimal writer is included (tests + exporting our params to GGUF).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, Dict, List, Optional, Sequence, Tuple

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = range(13)
_SCALARS = {
    _U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I", _I32: "<i",
    _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d",
}

# ggml tensor types (subset; the rest are quantized blocks)
GGML_F32, GGML_F16 = 0, 1
GGML_BF16 = 30
_QUANT_NAMES = {
    2: "Q4_0", 3: "Q4_1", 6: "Q5_0", 7: "Q5_1", 8: "Q8_0", 9: "Q8_1",
    10: "Q2_K", 11: "Q3_K", 12: "Q4_K", 13: "Q5_K", 14: "Q6_K", 15: "Q8_K",
}


def _np_dtype(ggml_type: int):
    import ml_dtypes

    if ggml_type == GGML_F32:
        return np.dtype(np.float32)
    if ggml_type == GGML_F16:
        return np.dtype(np.float16)
    if ggml_type == GGML_BF16:
        return np.dtype(ml_dtypes.bfloat16)
    name = _QUANT_NAMES.get(ggml_type, f"type {ggml_type}")
    raise ValueError(
        f"quantized GGUF tensor type {name} is not supported — export the "
        "checkpoint unquantized (F16/BF16); TPU serving runs bf16"
    )


@dataclass
class GGUFTensor:
    name: str
    shape: Tuple[int, ...]  # numpy order (outermost first)
    ggml_type: int
    offset: int  # relative to data section start


class GGUFFile:
    """Parsed GGUF: metadata dict + tensor directory + lazy tensor reads."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: Dict[str, Any] = {}
        self.tensors: Dict[str, GGUFTensor] = {}
        self._data_start = 0
        with open(path, "rb") as f:
            self._parse(f)

    # ------------------------------------------------------------- parsing
    def _read(self, f: BinaryIO, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, f.read(size))[0]

    def _read_str(self, f: BinaryIO) -> str:
        n = self._read(f, "<Q")
        return f.read(n).decode("utf-8")

    def _read_value(self, f: BinaryIO, vtype: int):
        if vtype in _SCALARS:
            return self._read(f, _SCALARS[vtype])
        if vtype == _BOOL:
            return bool(self._read(f, "<B"))
        if vtype == _STR:
            return self._read_str(f)
        if vtype == _ARR:
            etype = self._read(f, "<I")
            n = self._read(f, "<Q")
            return [self._read_value(f, etype) for _ in range(n)]
        raise ValueError(f"bad GGUF metadata type {vtype}")

    def _parse(self, f: BinaryIO) -> None:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{self.path}: not a GGUF file")
        version = self._read(f, "<I")
        if version not in (2, 3):
            raise ValueError(f"unsupported GGUF version {version}")
        n_tensors = self._read(f, "<Q")
        n_kv = self._read(f, "<Q")
        for _ in range(n_kv):
            key = self._read_str(f)
            vtype = self._read(f, "<I")
            self.metadata[key] = self._read_value(f, vtype)
        for _ in range(n_tensors):
            name = self._read_str(f)
            n_dims = self._read(f, "<I")
            # GGUF stores ne[] innermost-first; numpy wants outermost-first.
            ne = [self._read(f, "<Q") for _ in range(n_dims)]
            ggml_type = self._read(f, "<I")
            offset = self._read(f, "<Q")
            self.tensors[name] = GGUFTensor(
                name, tuple(reversed(ne)), ggml_type, offset
            )
        align = int(self.metadata.get("general.alignment", 32))
        pos = f.tell()
        self._data_start = (pos + align - 1) // align * align

    # -------------------------------------------------------------- tensors
    def tensor(self, name: str) -> np.ndarray:
        """Read one tensor (memory-mapped; unquantized types only)."""
        info = self.tensors[name]
        dt = _np_dtype(info.ggml_type)
        count = int(np.prod(info.shape)) if info.shape else 1
        mm = np.memmap(
            self.path,
            dtype=dt,
            mode="r",
            offset=self._data_start + info.offset,
            shape=(count,),
        )
        return np.asarray(mm).reshape(info.shape)

    # --------------------------------------------------------------- config
    def architecture(self) -> str:
        return str(self.metadata.get("general.architecture", "llama"))

    def to_model_config(self, name: str = "") -> "Any":
        """`llama.*` metadata → ModelConfig (reference: gguf/content.rs)."""
        from .config import ModelConfig

        arch = self.architecture()
        m = self.metadata

        def key(suffix: str, default=None):
            return m.get(f"{arch}.{suffix}", default)

        heads = int(key("attention.head_count"))
        hidden = int(key("embedding_length"))
        vocab = m.get(f"{arch}.vocab_size")
        if vocab is None:
            vocab = len(m.get("tokenizer.ggml.tokens", ())) or 32000
        eos = m.get("tokenizer.ggml.eos_token_id")
        return ModelConfig(
            name=name or str(m.get("general.name", "gguf-model")),
            vocab_size=int(vocab),
            hidden_size=hidden,
            num_layers=int(key("block_count")),
            num_heads=heads,
            num_kv_heads=int(key("attention.head_count_kv", heads)),
            head_dim=int(key("attention.key_length", hidden // heads)),
            intermediate_size=int(key("feed_forward_length")),
            rope_theta=float(key("rope.freq_base", 10000.0)),
            rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
            max_position=int(key("context_length", 4096)),
            eos_token_ids=(int(eos),) if eos is not None else (),
            # qwen2 GGUFs ship q/k/v biases (llama.cpp writes them for the
            # family); the loader errors if the config says bias but the
            # tensors are missing, so detection by architecture is safe.
            qkv_bias=arch == "qwen2",
        )

    # ------------------------------------------------------------ tokenizer
    def to_tokenizer(self):
        """Build a tokenizer from `tokenizer.ggml.*` metadata.

        `gpt2` model → byte-level BPE from tokens+merges; `llama` (SPM) →
        Unigram from tokens+scores.  Reference: gguf/mod.rs tokenizer
        extraction into their HF tokenizer."""
        from tokenizers import Tokenizer, decoders, pre_tokenizers
        from tokenizers.models import BPE, Unigram

        from ..llm.tokenizer import HFTokenizer

        m = self.metadata
        tokens: List[str] = m["tokenizer.ggml.tokens"]
        model = str(m.get("tokenizer.ggml.model", "gpt2"))
        if model == "gpt2":
            vocab = {t: i for i, t in enumerate(tokens)}
            merges = [
                tuple(s.split(" ", 1)) for s in m.get("tokenizer.ggml.merges", [])
            ]
            tok = Tokenizer(BPE(vocab, merges, ignore_merges=True))
            tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
            tok.decoder = decoders.ByteLevel()
        elif model == "llama":
            scores = m.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
            unk = int(m.get("tokenizer.ggml.unknown_token_id", 0))
            tok = Tokenizer(Unigram(list(zip(tokens, scores)), unk_id=unk))
            tok.decoder = decoders.Replace("▁", " ")
        else:
            raise ValueError(f"unsupported tokenizer.ggml.model {model!r}")
        return HFTokenizer(
            tokenizer=tok,
            bos_token_id=m.get("tokenizer.ggml.bos_token_id"),
            eos_token_id=m.get("tokenizer.ggml.eos_token_id"),
        )


# ----------------------------------------------------------------- loading
# GGUF tensor names (ggml llama.cpp convention) → our stacked params tree.
_GGUF_LAYER_MAP = {
    "attn_norm.weight": ("attn_norm", False),
    "attn_q.weight": ("wq", True),
    "attn_k.weight": ("wk", True),
    "attn_v.weight": ("wv", True),
    # Qwen2-style attention biases ([out] vectors, no transpose).
    "attn_q.bias": ("bq", False),
    "attn_k.bias": ("bk", False),
    "attn_v.bias": ("bv", False),
    "attn_output.weight": ("wo", True),
    "ffn_norm.weight": ("mlp_norm", False),
    "ffn_gate.weight": ("w_gate", True),
    "ffn_up.weight": ("w_up", True),
    "ffn_down.weight": ("w_down", True),
}


def load_params_gguf(config, path: str, dtype: Any = None) -> Dict[str, Any]:
    """Load an unquantized GGUF checkpoint into the params pytree (same
    structure as loader.load_params; transposes [out, in] → [in, out])."""
    import jax.numpy as jnp

    g = GGUFFile(path)
    dt = jnp.dtype(dtype or config.dtype)
    L = config.num_layers
    per_layer: Dict[str, List[Any]] = {}
    params: Dict[str, Any] = {"layers": {}}

    for name, info in g.tensors.items():
        if name == "token_embd.weight":
            params["embed"] = jnp.asarray(g.tensor(name), dt)
        elif name == "output_norm.weight":
            params["final_norm"] = jnp.asarray(g.tensor(name), dt)
        elif name == "output.weight":
            params["lm_head"] = jnp.asarray(g.tensor(name).T, dt)
        elif name.startswith("blk."):
            idx_str, sub = name[len("blk."):].split(".", 1)
            mapped = _GGUF_LAYER_MAP.get(sub)
            if mapped is None:
                continue
            ours, transpose = mapped
            t = g.tensor(name)
            slot = per_layer.setdefault(ours, [None] * L)
            slot[int(idx_str)] = t.T if transpose else t

    for ours, slabs in per_layer.items():
        missing = [i for i, s in enumerate(slabs) if s is None]
        if missing:
            raise ValueError(f"gguf missing {ours} for layers {missing}")
        params["layers"][ours] = jnp.asarray(np.stack(slabs), dt)
    if "embed" not in params:
        raise ValueError("gguf missing token_embd.weight")
    if "lm_head" not in params and not config.tie_word_embeddings:
        # llama.cpp only omits output.weight for TIED embeddings; an untied
        # checkpoint without it would silently fall back to embed.T in
        # forward and produce wrong logits (ADVICE r3).
        raise ValueError(
            "gguf missing output.weight but config is not tied "
            "(tie_word_embeddings=False)"
        )
    return params


# ------------------------------------------------------------------ writer
def _write_str(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)) + b)


def _value_type(v: Any) -> int:
    if isinstance(v, bool):
        return _BOOL
    if isinstance(v, int):
        return _U32 if 0 <= v < 2**32 else _I64
    if isinstance(v, float):
        return _F32
    if isinstance(v, str):
        return _STR
    raise ValueError(f"can't encode metadata value {v!r}")


def _write_value(f: BinaryIO, v: Any) -> None:
    if isinstance(v, bool):
        f.write(struct.pack("<I", _BOOL) + struct.pack("<B", int(v)))
    elif isinstance(v, int):
        t = _value_type(v)
        f.write(struct.pack("<I", t) + struct.pack(_SCALARS[t], v))
    elif isinstance(v, float):
        f.write(struct.pack("<I", _F32) + struct.pack("<f", v))
    elif isinstance(v, str):
        f.write(struct.pack("<I", _STR))
        _write_str(f, v)
    elif isinstance(v, (list, tuple)):
        f.write(struct.pack("<I", _ARR))
        if not v:
            f.write(struct.pack("<I", _STR) + struct.pack("<Q", 0))
            return
        et = _value_type(v[0])
        f.write(struct.pack("<I", et) + struct.pack("<Q", len(v)))
        for item in v:
            if et == _STR:
                _write_str(f, item)
            elif et == _BOOL:
                f.write(struct.pack("<B", int(item)))
            else:
                f.write(struct.pack(_SCALARS[et], item))
    else:
        raise ValueError(f"can't encode metadata value {v!r}")


def write_gguf(
    path: str,
    metadata: Dict[str, Any],
    tensors: Dict[str, np.ndarray],
    alignment: int = 32,
) -> None:
    """Minimal GGUF v3 writer (tests / exporting params)."""
    import ml_dtypes

    def gtype(a: np.ndarray) -> int:
        if a.dtype == np.float32:
            return GGML_F32
        if a.dtype == np.float16:
            return GGML_F16
        if a.dtype == ml_dtypes.bfloat16:
            return GGML_BF16
        raise ValueError(f"unsupported tensor dtype {a.dtype}")

    with open(path, "wb") as f:
        f.write(GGUF_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", len(tensors)))
        meta = dict(metadata)
        meta.setdefault("general.alignment", alignment)
        f.write(struct.pack("<Q", len(meta)))
        for k, v in meta.items():
            _write_str(f, k)
            _write_value(f, v)
        offset = 0
        for name, a in tensors.items():
            _write_str(f, name)
            ne = list(reversed(a.shape))
            f.write(struct.pack("<I", len(ne)))
            for d in ne:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<I", gtype(a)) + struct.pack("<Q", offset))
            offset += (a.nbytes + alignment - 1) // alignment * alignment
        pad = (-f.tell()) % alignment
        f.write(b"\x00" * pad)
        for a in tensors.values():
            f.write(np.ascontiguousarray(a).tobytes())
            f.write(b"\x00" * ((-a.nbytes) % alignment))
