"""Llama-family forward pass (dense + Mixtral-style MoE) with paged KV.

This replaces the reference's delegated engines (vLLM/sglang subprocesses —
SURVEY.md §2.8): the model is a pure function over a params pytree, executed
under jit on a device mesh.  TPU-first choices:

- layer weights are *stacked* [L, ...]; prefill/mixed programs run the
  decoder as one ``lax.scan`` (one compiled layer body regardless of depth,
  fast compiles across 7 token buckets), while the fused DECODE program
  unrolls the layer loop with static indices so XLA prefetches layer l+1's
  weights during layer l — decode is weights-bandwidth-bound and a scan's
  dynamic slices block that prefetch (measured ~25% on v5e);
- all shapes static: queries padded per bucket, padding tokens carry slot -1
  (dropped by the cache scatter) and are never read back (masked gather);
- bfloat16 weights/activations (MXU-native), f32 softmax/norm accumulations,
  f32 logits for sampling;
- one forward for prefill (Sq = bucket) and decode (Sq = 1) — same code path,
  attention always reads the paged cache it just wrote.

Tensor-parallel sharding is applied externally via pjit shardings
(parallel/mesh.py): heads shard over the "tp" mesh axis, XLA inserts the ICI
collectives.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.quant_matmul import qdot
from ..ops.ragged_attention import ragged_attention, write_kv_ragged
from ..ops.rope import apply_rope, rope_frequencies
from .config import ModelConfig
from .moe import init_moe_params, moe_mlp

Params = Dict[str, Any]


def linear(x: jnp.ndarray, lp: Params, name: str, out_dtype=None) -> jnp.ndarray:
    """``x @ lp[name]``, dispatching on quantization: an int8 weight leaf is
    recognised by its sibling ``name + "_scale"`` (models/quant.py) and runs
    the native int8 MXU path (ops/quant_matmul.qdot)."""
    w = lp[name]
    s = lp.get(name + "_scale")
    if s is None:
        r = x @ w
        return r.astype(out_dtype) if out_dtype is not None else r
    return qdot(x, w, s, out_dtype=out_dtype)


def qkv_proj(x: jnp.ndarray, lp: Params, q_size: int, kv_size: int):
    """q/k/v projections, using the fused wqkv leaf when present
    (models/quant.py fuse_projections — single dot + static splits)."""
    if "wqkv" in lp:
        qkv = linear(x, lp, "wqkv")
        if "bqkv" in lp:
            qkv = qkv + lp["bqkv"]
        return jnp.split(qkv, [q_size, q_size + kv_size], axis=-1)
    q, k, v = linear(x, lp, "wq"), linear(x, lp, "wk"), linear(x, lp, "wv")
    if "bq" in lp:  # Qwen2-style attention biases
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    return q, k, v


def mlp(x: jnp.ndarray, lp: Params) -> jnp.ndarray:
    """SwiGLU FFN, using the fused w_gateup leaf when present."""
    if "w_gateup" in lp:
        gu = linear(x, lp, "w_gateup", jnp.float32)
        F = gu.shape[-1] // 2
        gate = jax.nn.silu(gu[..., :F]).astype(x.dtype)
        up = gu[..., F:].astype(x.dtype)
        return linear(gate * up, lp, "w_down")
    gate = jax.nn.silu(linear(x, lp, "w_gate", jnp.float32)).astype(x.dtype)
    return linear(gate * linear(x, lp, "w_up"), lp, "w_down")


def embed_lookup(params: Params, token_ids: jnp.ndarray, dtype) -> jnp.ndarray:
    """Token embedding gather; int8 embeds dequantize the gathered rows by
    their per-row scale (scale axis = vocab row, shared with the tied head)."""
    e = params["embed"][token_ids]
    s = params.get("embed_scale")
    if s is None:
        return e
    return (e.astype(jnp.float32) * s[token_ids][:, None]).astype(dtype)


def lm_logits(params: Params, h_last: jnp.ndarray) -> jnp.ndarray:
    """Final-norm hidden rows → f32 logits, through lm_head or the tied
    embedding, quantized or not."""
    head = params.get("lm_head")
    if head is not None:
        s = params.get("lm_head_scale")
        if s is None:
            return (h_last @ head).astype(jnp.float32)
        return qdot(h_last, head, s, out_dtype=jnp.float32)
    s = params.get("embed_scale")
    if s is None:
        return (h_last @ params["embed"].T).astype(jnp.float32)
    return qdot(h_last, params["embed"].T, s, out_dtype=jnp.float32)


class PagedKVCache(NamedTuple):
    """Page-major per-layer KV slabs in the TPU ragged-attention layout:
    ``[num_layers, num_pages, page_size, 2*kv_heads, head_dim]`` with K at
    even combined-head indices and V at odd (ops/ragged_attention.py).
    Sequences own pages; a page table maps logical to physical page ids, so
    any physical order works — allocation never moves data."""

    pages: jnp.ndarray

    @classmethod
    def create(
        cls, config: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16
    ) -> "PagedKVCache":
        shape = (
            config.num_layers,
            num_pages,
            page_size,
            2 * config.num_kv_heads,
            config.head_dim,
        )
        return cls(pages=jnp.zeros(shape, dtype))


class RaggedBatch(NamedTuple):
    """One unified step: a flat token run of mixed prefill chunks and decode
    tokens (static T per bucket; row boundaries via cu_q_lens).

    Padding: tokens at/past cu_q_lens[num_seqs] carry slot -1 (write dropped)
    and produce zero attention; rows at/past num_seqs have kv_len 0.
    """

    token_ids: jnp.ndarray  # [T] int32
    positions: jnp.ndarray  # [T] int32
    slot_mapping: jnp.ndarray  # [T] int32 (-1 = padding)
    kv_lens: jnp.ndarray  # [S] int32
    page_indices: jnp.ndarray  # [S, pages_per_seq] int32
    cu_q_lens: jnp.ndarray  # [S+1] int32
    num_seqs: jnp.ndarray  # [1] int32
    # Batched multi-LoRA (llm/tenancy): per-token resident adapter slot
    # (-1 = base model).  None on LoRA-less engines — a None leaf vanishes
    # from the jit treedef, so existing programs are byte-identical.
    adapter_slots: Any = None  # [T] int32 | None


def _dtype(config: ModelConfig):
    return jnp.dtype(config.dtype)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Random-init a full params pytree (jit-friendly; used for benchmarks
    and tests; real checkpoints come through models/loader.py)."""
    dt = _dtype(config)
    D, H, KV, hd, F = (
        config.hidden_size,
        config.num_heads,
        config.num_kv_heads,
        config.head_dim,
        config.intermediate_size,
    )
    L, V = config.num_layers, config.vocab_size
    keys = jax.random.split(key, 12)

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": norm(keys[1], L, D, H * hd),
        "wk": norm(keys[2], L, D, KV * hd),
        "wv": norm(keys[3], L, D, KV * hd),
        "wo": norm(keys[4], L, H * hd, D),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if config.qkv_bias:
        layers.update(
            {
                "bq": norm(keys[10], L, H * hd),
                "bk": norm(keys[11], L, KV * hd),
                "bv": norm(keys[0], L, KV * hd),
            }
        )
    if config.is_moe:
        layers.update(init_moe_params(config, keys[5], dt))
    else:
        layers.update(
            {
                "w_gate": norm(keys[5], L, D, F),
                "w_up": norm(keys[6], L, D, F),
                "w_down": norm(keys[7], L, F, D),
            }
        )
    params: Params = {
        "embed": norm(keys[8], V, D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = norm(keys[9], D, V)
    return params


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def forward_ragged(
    params: Params,
    config: ModelConfig,
    rb: RaggedBatch,
    cache: PagedKVCache,
    *,
    attn_impl: str = "xla",  # "tpu" (pallas kernel) | "xla" (gather fallback)
    mesh=None,
    # Quantized (fp8/int8) page-dtype scale: a float, or a [L] per-layer
    # calibration vector.  The scale is folded ALGEBRAICALLY around the
    # attention call — stored = value/scale, q pre-scaled and the output
    # post-scaled by scale — so per-layer values stay fully traceable (the
    # pallas kernel's native k_scale/v_scale only accepts static floats).
    kv_scale=None,
    decode: bool = False,  # static: every row is a single-token decode row
    # Decode-path attention kernel (ops/ragged_attention.py
    # resolve_decode_kernel): "pallas_fused" routes the fused-dequant
    # split-KV kernel, which takes the (possibly traced per-layer)
    # kv_scale IN-KERNEL — the algebraic q/out fold below is skipped for
    # it, so the quantized KV stream is dequantized exactly once, in VMEM.
    decode_kernel: str = "stock",
    # Non-decode (prefill / mixed-chunk) attention kernel
    # (resolve_prefill_kernel): "pallas" routes the chunked paged prefill
    # kernel (ops/prefill_attention.py), which likewise takes kv_scale
    # IN-KERNEL — the algebraic fold is skipped for it too.
    prefill_kernel: str = "stock",
    # Static per-slot rank of the LoRA device bank (llm/tenancy/lora.py);
    # 0 = no LoRA.  Active only when BOTH the params tree carries bank
    # leaves and the batch carries adapter_slots.
    lora_rank: int = 0,
) -> Tuple[jnp.ndarray, PagedKVCache]:
    """Unified mixed prefill+decode forward over a flat ragged token run.

    Returns (logits [S, vocab] f32 — each row's LAST token's logits — and the
    updated cache).  Rows past num_seqs produce garbage logits the caller
    ignores.  One compiled program per token-count bucket serves every
    prefill/decode mix (the round-2 anti-recompile design; see
    ops/ragged_attention.py).

    With ``mesh``, the KV write + attention run under shard_map over the
    "tp" axis: each shard owns its heads' pages, so paged attention is fully
    local per chip and works with the opaque pallas kernel (XLA's auto-SPMD
    cannot partition a pallas call).  Everything else (projections, FFN,
    MoE, logits) auto-shards from the param PartitionSpecs.
    """
    (T,) = rb.token_ids.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    inv_freq = rope_frequencies(hd, config.rope_theta, config.rope_scaling)
    scale = hd**-0.5
    L, P_layer, ps = cache.pages.shape[0], cache.pages.shape[1], cache.pages.shape[2]

    ks_vec = (
        None
        if kv_scale is None
        else jnp.asarray(kv_scale, jnp.float32).reshape(-1)  # [1] or [L]
    )

    # The fused decode AND prefill kernels dequantize in-kernel (the scale
    # is an SMEM scalar operand, traced per-layer values included) — the
    # algebraic fold would double-apply it.
    fused_dequant = (
        decode_kernel == "pallas_fused"
        if decode
        else prefill_kernel == "pallas"
    )

    def attn_and_write(q, k, v, s_l, pages, slots, kv_lens, tables, cu, num):
        # s_l: this layer's scale ([] f32) or None.  q·(K·s) == (q·s)·K and
        # softmax(p)·(V·s) == (softmax(p)·V)·s, so scaling q in and the
        # output back out dequantizes exactly without kernel support.
        pages = write_kv_ragged(pages, k, v, slots, kv_scale=s_l)
        if s_l is not None and not fused_dequant:
            q = (q.astype(jnp.float32) * s_l).astype(q.dtype)
        out = ragged_attention(
            q,
            pages,
            kv_lens,
            tables,
            cu,
            num,
            sm_scale=scale,
            impl=attn_impl,
            decode=decode,
            decode_kernel=decode_kernel,
            prefill_kernel=prefill_kernel,
            kv_scale=s_l if fused_dequant else None,
        )
        if s_l is not None and not fused_dequant:
            out = (out.astype(jnp.float32) * s_l).astype(out.dtype)
        return out, pages

    if mesh is not None:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        heads = P(None, "tp", None)  # [T, heads, hd]
        pages_s = P(None, None, "tp", None)  # [L*pages, page_size, 2KV, hd]
        rep = P()  # ragged metadata + scale: replicated on every shard
        inner = attn_and_write

        def attn_and_write(q, k, v, s_l, pages, slots, kv_lens, tables, cu, num):
            if s_l is None:
                mapped = shard_map(
                    lambda q, k, v, *rest: inner(q, k, v, None, *rest),
                    mesh=mesh,
                    in_specs=(heads, heads, heads, pages_s,
                              rep, rep, rep, rep, rep),
                    out_specs=(heads, pages_s),
                    # Outputs are tp-sharded only — skip the strict
                    # replication check for the dp/ep axes.
                    check_vma=False,
                )
                return mapped(q, k, v, pages, slots, kv_lens, tables, cu, num)
            mapped = shard_map(
                inner,
                mesh=mesh,
                in_specs=(heads, heads, heads, rep, pages_s,
                          rep, rep, rep, rep, rep),
                out_specs=(heads, pages_s),
                check_vma=False,
            )
            return mapped(q, k, v, s_l, pages, slots, kv_lens, tables, cu, num)

    h = embed_lookup(params, rb.token_ids, _dtype(config))  # [T, D]

    # Batched segmented multi-LoRA (S-LoRA on TPU; llm/tenancy/lora.py):
    # all resident adapters' A/B factors live concatenated along a R*r rank
    # axis, and a per-token segment mask zeroes every adapter's columns but
    # the token's own — two dense matmuls serve rows from many adapters in
    # ONE forward, with exact per-row isolation and no gather/scatter.
    # Merge-free: the (possibly int8-quantized) base weights are untouched.
    lora_mask = None
    if (
        lora_rank > 0
        and rb.adapter_slots is not None
        and "lora_a_wq" in params["layers"]
    ):
        Rr = params["layers"]["lora_a_wq"].shape[-1]
        seg = jnp.arange(Rr, dtype=jnp.int32) // lora_rank  # column → slot
        lora_mask = (
            rb.adapter_slots[:, None] == seg[None, :]
        ).astype(_dtype(config))  # [T, R*r]; slot -1 (base) matches nothing

    def lora_delta(x_in, lp, name):
        a = lp.get("lora_a_" + name)
        if lora_mask is None or a is None:
            return None
        xa = (x_in @ a) * lora_mask  # [T, R*r], own-adapter columns only
        return (xa @ lp["lora_b_" + name]).astype(x_in.dtype)

    # The page slab rides the layer scan as a CARRY over a flat
    # layer-merged view [L*P, ps, 2KV, hd]; each layer scatters its rows at
    # a layer offset and attention gathers via offset page indices.  Making
    # it a carry (not xs/ys) lets XLA's while-loop aliasing update the slab
    # in place — per-step HBM traffic is the written rows + gathered
    # context, NOT the whole slab (threading it as xs/ys stacked a full
    # slab copy per step: measured 2.4 GB and ~23 ms/step at the bench pool
    # size before this change).
    def layer(carry, xs):
        h, pages = carry
        lp, l = xs
        x = rms_norm(h, lp["attn_norm"], config.rms_norm_eps)
        q, k, v = qkv_proj(x, lp, H * hd, KV * hd)
        if lora_mask is not None:
            dq, dk, dv = (
                lora_delta(x, lp, "wq"),
                lora_delta(x, lp, "wk"),
                lora_delta(x, lp, "wv"),
            )
            q = q if dq is None else q + dq
            k = k if dk is None else k + dk
            v = v if dv is None else v + dv
        q = q.reshape(T, H, hd)
        k = k.reshape(T, KV, hd)
        v = v.reshape(T, KV, hd)
        q = apply_rope(q, rb.positions, inv_freq)
        k = apply_rope(k, rb.positions, inv_freq)
        slots_l = jnp.where(
            rb.slot_mapping < 0, -1, rb.slot_mapping + l * (P_layer * ps)
        )
        tables_l = rb.page_indices + l * P_layer
        s_l = (
            None
            if ks_vec is None
            else ks_vec[jnp.minimum(l, ks_vec.shape[0] - 1)]
        )
        attn, pages = attn_and_write(
            q, k, v, s_l, pages, slots_l, rb.kv_lens,
            tables_l, rb.cu_q_lens, rb.num_seqs,
        )
        attn_flat = attn.reshape(T, H * hd)
        o = linear(attn_flat, lp, "wo")
        if lora_mask is not None:
            do = lora_delta(attn_flat, lp, "wo")
            o = o if do is None else o + do
        h = h + o
        x = rms_norm(h, lp["mlp_norm"], config.rms_norm_eps)
        if config.is_moe:
            h = h + moe_mlp(x[None], lp, config)[0]
        else:
            h = h + mlp(x, lp)
        return (h, pages), None

    flat = cache.pages.reshape((L * P_layer,) + cache.pages.shape[2:])
    if decode:
        # Unrolled layer loop for the fused decode program: STATIC layer
        # indices into the stacked weights let XLA prefetch layer l+1's
        # weights during layer l's compute — a scan's dynamic slices block
        # that (measured on v5e at batch 256: an 18-layer FFN chain runs
        # 9.4ms under scan vs 7.0ms unrolled; scan's unroll= option does
        # NOT recover it).  Decode is weights-bandwidth-bound, so this is
        # where prefetch pays; prefill keeps the scan's compact HLO (it is
        # compute-bound at 59-83% MFU and compiles 7 token buckets).
        carry = (h, flat)
        for l in range(L):
            lp = jax.tree_util.tree_map(lambda a: a[l], params["layers"])
            carry, _ = layer(carry, (lp, l))
        h, flat = carry
    else:
        (h, flat), _ = jax.lax.scan(
            layer,
            (h, flat),
            (params["layers"], jnp.arange(L, dtype=jnp.int32)),
        )
    pages = flat.reshape(cache.pages.shape)

    h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
    rows = jnp.clip(rb.cu_q_lens[1:] - 1, 0, T - 1)  # [S] last token per row
    logits = lm_logits(params, h[rows])  # [S, vocab] f32
    return logits, PagedKVCache(pages)


def forward_sp_prefill(
    params: Params,
    config: ModelConfig,
    token_ids: jnp.ndarray,  # [Tg] int32, Tg divisible by the mesh's sp size
    valid_len,  # int or [] int32 — true prompt length (<= Tg; rest padding)
    mesh,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Whole-prompt sequence-parallel prefill for long contexts.

    Tokens shard over the mesh's "sp" axis; every matmul is local to its
    token shard (weights replicated over sp) and attention runs as RING
    attention (ops/ring_attention.py) — per-chip attention memory is
    O((Tg/sp)^2) and K/V blocks move neighbor-to-neighbor over ICI.  The
    reference has no counterpart (SURVEY §5: no sequence parallelism
    anywhere); this is the TPU-native long-context path the north-star
    configs call for.

    Returns (logits [vocab] f32 of the LAST valid token — the first decode
    token's distribution — and kv [L, Tg, 2*KV, hd] combined-interleaved
    pages-layout rows for sealing the prompt into the paged cache).
    """
    from ..ops.ring_attention import ring_attention
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    (Tg,) = token_ids.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    inv_freq = rope_frequencies(hd, config.rope_theta, config.rope_scaling)
    scale = hd**-0.5
    valid = jnp.asarray(valid_len, jnp.int32).reshape(())

    # Tokens shard over "sp", heads over "tp": with both axes active each
    # chip rings over its own heads' K/V only (no per-layer all-gather of
    # tp-sharded projections, no redundant attention across tp replicas).
    heads = P("sp", "tp", None)
    ring = shard_map(
        lambda q, k, v, n: ring_attention(q, k, v, n[0], sm_scale=scale),
        mesh=mesh,
        in_specs=(heads, heads, heads, P()),
        out_specs=heads,
        check_vma=False,
    )

    positions = jnp.arange(Tg, dtype=jnp.int32)
    # [Tg, D] — sharded over sp by input spec
    h = embed_lookup(params, token_ids, _dtype(config))

    def layer(carry, lp):
        h = carry
        x = rms_norm(h, lp["attn_norm"], config.rms_norm_eps)
        q, k, v = qkv_proj(x, lp, H * hd, KV * hd)
        q = apply_rope(q.reshape(Tg, H, hd), positions, inv_freq)
        k = apply_rope(k.reshape(Tg, KV, hd), positions, inv_freq)
        v = v.reshape(Tg, KV, hd)
        attn = ring(q, k, v, jnp.asarray([valid], jnp.int32))
        h = h + linear(attn.reshape(Tg, H * hd), lp, "wo")
        x = rms_norm(h, lp["mlp_norm"], config.rms_norm_eps)
        if config.is_moe:
            h = h + moe_mlp(x[None], lp, config)[0]
        else:
            h = h + mlp(x, lp)
        # pages layout rows: K at even combined-head indices, V at odd
        comb = jnp.stack([k, v], axis=2).reshape(Tg, 2 * KV, hd)
        return h, comb

    h, kv = jax.lax.scan(layer, h, params["layers"])

    h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
    logits = lm_logits(params, h[jnp.clip(valid - 1, 0, Tg - 1)])
    return logits, kv  # kv: [L, Tg, 2KV, hd]
