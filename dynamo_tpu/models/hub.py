"""Checkpoint acquisition: model name → local directory.

Reference behavior: ``dynamo-run`` resolves its model argument before
anything else — an existing path is used as-is, anything else is treated as
a HuggingFace repo id and snapshot-downloaded into the local cache
(/root/reference/launch/dynamo-run/src/lib.rs:125-130,
/root/reference/lib/llm/src/hub.rs).  This module is the TPU build's
equivalent, shared by the CLI (`--arch`/`--checkpoint`), the engine
(EngineConfig.checkpoint_path), and the model card builder.

Resolution order for ``resolve_model(spec)``:
  1. an existing local directory (or .gguf file) → returned unchanged;
  2. a known alias (e.g. the north-star ``deepseek-r1-distill-llama-8b``)
     → its HF repo id;
  3. a HF repo id → ``huggingface_hub.snapshot_download`` of just the
     serving artifacts (safetensors + tokenizer + configs), honoring
     HF_HOME / DYN_MODEL_CACHE; offline environments get a clear error
     naming the directory to pre-stage instead of a hang.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

# North-star + convenience aliases → HF repo ids (BASELINE.md workloads).
ALIASES = {
    "deepseek-r1-distill-llama-8b": "deepseek-ai/DeepSeek-R1-Distill-Llama-8B",
    "deepseek-r1-distill-llama-70b": "deepseek-ai/DeepSeek-R1-Distill-Llama-70B",
    "llama-3.1-8b-instruct": "meta-llama/Llama-3.1-8B-Instruct",
    "llama-3.1-70b-instruct": "meta-llama/Llama-3.1-70B-Instruct",
    "mixtral-8x7b-instruct": "mistralai/Mixtral-8x7B-Instruct-v0.1",
    "qwen2.5-7b-instruct": "Qwen/Qwen2.5-7B-Instruct",
}

# Only the artifacts serving needs: weights, tokenizer, configs.  Skips
# original/consolidated torch shards, README blobs, etc.
_PATTERNS = [
    "*.safetensors",
    "*.safetensors.index.json",
    "config.json",
    "generation_config.json",
    "tokenizer.json",
    "tokenizer.model",  # sentencepiece-only repos (older Llama/Mistral)
    "tokenizer_config.json",
    "special_tokens_map.json",
]


def cache_dir() -> str:
    return os.environ.get(
        "DYN_MODEL_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "dynamo_tpu", "models"),
    )


def resolve_model(spec: str, revision: Optional[str] = None) -> str:
    """Resolve a model spec to a local checkpoint directory (see module
    docstring).  Raises FileNotFoundError with remediation guidance when the
    spec is remote and the environment cannot download."""
    if os.path.isdir(spec) or spec.endswith(".gguf"):
        return spec
    repo = ALIASES.get(spec.lower(), spec)
    # A pre-staged copy under the cache dir wins (offline deployments stage
    # checkpoints here, or point DYN_MODEL_CACHE at a shared volume).
    staged = os.path.join(cache_dir(), repo.replace("/", "--"))
    if os.path.isdir(staged) and os.path.exists(
        os.path.join(staged, "config.json")
    ):
        return staged
    if "/" not in repo:
        raise FileNotFoundError(
            f"model {spec!r} is neither a local directory, a known alias, "
            f"nor a HF repo id (org/name); known aliases: {sorted(ALIASES)}"
        )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - hub is in the image
        raise FileNotFoundError(
            f"model {spec!r} needs huggingface_hub to download; pre-stage "
            f"the checkpoint at {staged} instead"
        ) from e
    logger.info("downloading %s (revision=%s)", repo, revision or "main")
    try:
        # No explicit cache_dir: huggingface_hub already resolves HF_HOME /
        # HF_HUB_CACHE to the standard $HF_HOME/hub layout, so an existing
        # cached snapshot (pulled by transformers or hf CLI) is reused.
        return snapshot_download(
            repo_id=repo,
            revision=revision,
            allow_patterns=_PATTERNS,
        )
    except Exception as e:
        raise FileNotFoundError(
            f"could not download {repo!r} ({type(e).__name__}: {e}); in an "
            f"offline deployment pre-stage the serving artifacts "
            f"({', '.join(_PATTERNS)}) at {staged}"
        ) from e


# A LoRA adapter directory's serving artifacts (llm/tenancy/lora.py —
# PEFT layout): the factor tensors + the rank/alpha config.
_ADAPTER_PATTERNS = [
    "adapter_model.safetensors",
    "adapter_config.json",
]


def resolve_adapter(spec: str) -> str:
    """Resolve a LoRA adapter spec to a local PEFT directory, mirroring
    ``resolve_model``: an existing directory passes through; anything else
    is a HF repo id snapshot-downloaded (adapter artifacts only), with the
    same pre-staged offline cache fallback under ``cache_dir()``."""
    if os.path.isdir(spec):
        return spec
    staged = os.path.join(cache_dir(), spec.replace("/", "--"))
    if os.path.isdir(staged) and os.path.exists(
        os.path.join(staged, "adapter_model.safetensors")
    ):
        return staged
    if "/" not in spec:
        raise FileNotFoundError(
            f"adapter {spec!r} is neither a local directory nor a HF repo "
            f"id (org/name); pre-stage PEFT artifacts "
            f"({', '.join(_ADAPTER_PATTERNS)}) at {staged}"
        )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - hub is in the image
        raise FileNotFoundError(
            f"adapter {spec!r} needs huggingface_hub to download; "
            f"pre-stage the PEFT artifacts at {staged}"
        ) from e
    logger.info("downloading adapter %s", spec)
    try:
        return snapshot_download(repo_id=spec, allow_patterns=_ADAPTER_PATTERNS)
    except Exception as e:
        raise FileNotFoundError(
            f"could not download adapter {spec!r} ({type(e).__name__}: {e});"
            f" in an offline deployment pre-stage "
            f"({', '.join(_ADAPTER_PATTERNS)}) at {staged}"
        ) from e


def tokenizer_spec(path: str) -> Optional[dict]:
    """Tokenizer spec dict (llm/discovery.make_tokenizer input) for a
    resolved checkpoint directory, or None if it ships no tokenizer."""
    if path.endswith(".gguf"):
        return {"kind": "gguf", "file": path}
    if os.path.exists(os.path.join(path, "tokenizer.json")):
        return {"kind": "hf", "dir": path}
    if os.path.exists(os.path.join(path, "tokenizer.model")):
        # sentencepiece-only checkpoint (older Llama/Mistral): served via
        # the vendored sp runtime (llm/sp.py; reference sp.rs).
        return {"kind": "sp", "file": os.path.join(path, "tokenizer.model")}
    return None
