"""Int8 weight quantization (W8A8-dynamic) for the native JAX engine.

The reference's published baseline serves a *quantized-weights* checkpoint —
``neuralmagic/DeepSeek-R1-Distill-Llama-70B-FP8-dynamic``
(/root/reference/examples/llm/benchmarks/README.md) — with FP8 execution
delegated to vLLM.  This build owns its engine, so it owns quantization.
v5e has no fp8 MXU; its native low-precision path is int8 (~2x bf16 peak,
half the HBM bytes), so the TPU-first mapping of "FP8-dynamic" is:

- **weights**: symmetric per-output-channel int8, quantized once at load
  (``w_q = round(w / s)``, ``s = max|w| / 127`` along the input axis);
- **activations**: symmetric per-token (per-row) int8, quantized
  *dynamically* inside the forward (``a = max|x| / 127`` per row);
- **matmul**: native int8 x int8 ``dot_general`` accumulating int32 on the
  MXU, rescaled by ``a * s`` in f32 afterwards.

Measured on v5e (tools/quant_microbench.py): decode-geometry FFN chain
1.31 ms vs bf16's 2.26 ms (1.73x; int8 bytes stream at ~720 GB/s — at the
HBM roofline), prefill 360 vs 193 TFLOP/s (1.87x).  Weight-only int8
("w8a16", dequantize-then-bf16-matmul) measured *slower* than bf16 — XLA
materializes the dequantized weights instead of fusing the convert into the
dot — so it is deliberately not offered.

int32 accumulation is exact: the largest contraction here (F=28672 for 70B)
bounds |acc| <= 28672 * 127 * 127 ~ 4.6e8 < 2^31.

Quantized leaves live in the same params pytree: each weight ``name`` gains
a sibling ``name + "_scale"`` (f32, the weight's output-channel axis), and
the forward dispatches on the scale leaf's presence — no config plumbing
through model code.  Norms, biases and the MoE router (tiny,
routing-accuracy-critical) stay in bf16.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

# Weight leaves that quantize, with the axis that is the *input* (contracted)
# axis of the per-layer matmul — scales are taken over it, leaving the output
# channel axis.  Shapes are the stacked [L, ...] layouts of models/llama.py.
_LAYER_QUANT_AXES = {
    "wq": 1,  # [L, D, H*hd]   -> scale [L, H*hd]
    "wk": 1,  # [L, D, KV*hd]
    "wv": 1,  # [L, D, KV*hd]
    "wo": 1,  # [L, H*hd, D]   -> scale [L, D]
    "w_gate": 1,  # [L, D, F]
    "w_up": 1,  # [L, D, F]
    "w_down": 1,  # [L, F, D]
    "moe_gate": 2,  # [L, E, D, F] -> scale [L, E, F]
    "moe_up": 2,  # [L, E, D, F]
    "moe_down": 2,  # [L, E, F, D] -> scale [L, E, D]
    # Fused leaves (fuse_projections): same [L, in, out] layout, scales on
    # the concatenated output axis — quantize/dequantize must handle trees
    # in EITHER layout (engine params are fused by default single-shard).
    "wqkv": 1,  # [L, D, (H+2KV)*hd]
    "w_gateup": 1,  # [L, D, 2F]
}

# Top-level leaves.  embed [V, D] scales per vocab row (axis 1) — the same
# per-row scale serves both the lookup (dequantize the gathered row) and the
# tied lm_head (embed.T's output-channel axis IS the vocab row).
_TOP_QUANT_AXES = {"embed": 1, "lm_head": 0}  # lm_head [D, V] -> scale [V]


def quantize_array_np(w: np.ndarray, axis: int) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-channel int8 quantization in numpy (load path: keeps
    full-size f32 transients off the device and bounded to one tensor)."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=axis)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    # Clip before the int8 cast (matching ops/quant_matmul.quantize_rows):
    # rint(w/s) can land on ±127.0000x in float32 even though |w| <= amax
    # exactly, and an unclipped cast would wrap +127.x to -128.
    q = np.clip(
        np.rint(wf / np.expand_dims(scale, axis)), -127, 127
    ).astype(np.int8)
    return q, scale


def is_quantized(params: Dict[str, Any]) -> bool:
    return "embed_scale" in params or any(
        k.endswith("_scale") for k in params.get("layers", {})
    )


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a loaded (bf16) params tree in place of a new tree.  Used
    when params were built outside the loader (tests, pre-loaded trees);
    checkpoints quantize tensor-at-a-time in models/loader.py instead."""
    import jax.numpy as jnp

    if is_quantized(params):
        return params
    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "layers":
            continue
        axis = _TOP_QUANT_AXES.get(name)
        if axis is None:
            out[name] = leaf
        else:
            q, s = _quantize_jnp(leaf, axis)
            out[name], out[name + "_scale"] = q, s
    layers: Dict[str, Any] = {}
    for name, leaf in params["layers"].items():
        if name.startswith("lora_"):
            # Multi-LoRA device banks (llm/tenancy/lora.py) stay in float:
            # adapters are merge-free deltas applied AROUND the (possibly
            # int8) base projections, so quantizing them would re-calibrate
            # nothing and lose the low-rank factors' dynamic range — and
            # slots are rewritten at promotion time, which would invalidate
            # any per-slot scale immediately.
            layers[name] = leaf
            continue
        axis = _LAYER_QUANT_AXES.get(name)
        if axis is None:
            layers[name] = leaf
        else:
            q, s = _quantize_jnp(leaf, axis)
            layers[name], layers[name + "_scale"] = q, s
    out["layers"] = layers
    return out


def _quantize_jnp(w, axis: int):
    import jax.numpy as jnp

    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    scale = jnp.maximum(amax / 127.0, 1e-12).astype(jnp.float32)
    # Same clip-before-cast as quantize_array_np / quantize_rows: float32
    # round-off at exactly ±127 must not wrap to -128.
    q = jnp.clip(
        jnp.round(wf / jnp.expand_dims(scale, axis)), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_params(params: Dict[str, Any], dtype="float32") -> Dict[str, Any]:
    """Exact f32/bf16 tree from a quantized one — the reference forward for
    golden-token quality gates compares against THIS (so the only difference
    under test is the engine's int8 execution, not the rounding of weights)."""
    import jax.numpy as jnp

    def deq(group: Dict[str, Any], axes: Dict[str, int]) -> Dict[str, Any]:
        out = {}
        for name, leaf in group.items():
            if name.endswith("_scale") or name == "layers":
                continue
            axis = axes.get(name)
            if axis is not None and name + "_scale" in group:
                s = jnp.expand_dims(group[name + "_scale"], axis)
                out[name] = (leaf.astype(jnp.float32) * s).astype(dtype)
            else:
                out[name] = leaf
        return out

    out = deq(params, _TOP_QUANT_AXES)
    out["layers"] = deq(params["layers"], _LAYER_QUANT_AXES)
    return out


def fuse_projections(params: Dict[str, Any]) -> Dict[str, Any]:
    """Concatenate q|k|v and gate|up along their output axes: 7 matmuls per
    dense layer become 5, and the fused dots share one activation
    quantization (decode launches fewer kernels per layer — measured on the
    per-layer overhead the r5 cost breakdown attributes).

    SINGLE-SHARD ONLY (engine applies it when mesh is None): a tp-sharded
    fused output axis would split across q/k/v segment boundaries and force
    resharding at the static split.  Works for quantized and bf16 trees;
    MoE experts keep their layout.  The forward dispatches on the fused
    leaf names (models/llama.py)."""
    import jax.numpy as jnp

    layers = dict(params["layers"])
    if "wq" in layers and "wqkv" not in layers:
        layers["wqkv"] = jnp.concatenate(
            [layers.pop("wq"), layers.pop("wk"), layers.pop("wv")], axis=-1
        )
        if "wq_scale" in layers:
            layers["wqkv_scale"] = jnp.concatenate(
                [layers.pop("wq_scale"), layers.pop("wk_scale"),
                 layers.pop("wv_scale")], axis=-1,
            )
        if "bq" in layers:
            layers["bqkv"] = jnp.concatenate(
                [layers.pop("bq"), layers.pop("bk"), layers.pop("bv")],
                axis=-1,
            )
    if "w_gate" in layers and "w_gateup" not in layers:
        layers["w_gateup"] = jnp.concatenate(
            [layers.pop("w_gate"), layers.pop("w_up")], axis=-1
        )
        if "w_gate_scale" in layers:
            layers["w_gateup_scale"] = jnp.concatenate(
                [layers.pop("w_gate_scale"), layers.pop("w_up_scale")],
                axis=-1,
            )
    return dict(params, layers=layers)


def init_params_quantized(config, key) -> Dict[str, Any]:
    """Random-init a quantized tree DIRECTLY in int8 — full-depth 8B bf16
    random-init would not fit single-chip HBM, which is the point of
    quantizing.  Distribution mimics init_params' N(0, 0.02): uniform int8
    (std ~73) with a constant scale of 0.02/73 per output channel."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(config.dtype)
    D, H, KV, hd, F = (
        config.hidden_size,
        config.num_heads,
        config.num_kv_heads,
        config.head_dim,
        config.intermediate_size,
    )
    L, V, E = config.num_layers, config.vocab_size, config.num_experts
    keys = iter(jax.random.split(key, 24))
    s0 = np.float32(0.02 / 73.0)

    def q(*shape):
        return jax.random.randint(next(keys), shape, -127, 128, dtype=jnp.int8)

    def s(*shape):
        return jnp.full(shape, s0, jnp.float32)

    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": q(L, D, H * hd), "wq_scale": s(L, H * hd),
        "wk": q(L, D, KV * hd), "wk_scale": s(L, KV * hd),
        "wv": q(L, D, KV * hd), "wv_scale": s(L, KV * hd),
        "wo": q(L, H * hd, D), "wo_scale": s(L, D),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if config.qkv_bias:
        layers.update(
            {
                "bq": jnp.zeros((L, H * hd), dt),
                "bk": jnp.zeros((L, KV * hd), dt),
                "bv": jnp.zeros((L, KV * hd), dt),
            }
        )
    if config.is_moe:
        Fm = config.moe_intermediate_size or F
        layers.update(
            {
                "router": (jax.random.normal(next(keys), (L, D, E), jnp.float32) * 0.02).astype(dt),
                "moe_gate": q(L, E, D, Fm), "moe_gate_scale": s(L, E, Fm),
                "moe_up": q(L, E, D, Fm), "moe_up_scale": s(L, E, Fm),
                "moe_down": q(L, E, Fm, D), "moe_down_scale": s(L, E, D),
            }
        )
    else:
        layers.update(
            {
                "w_gate": q(L, D, F), "w_gate_scale": s(L, F),
                "w_up": q(L, D, F), "w_up_scale": s(L, F),
                "w_down": q(L, F, D), "w_down_scale": s(L, D),
            }
        )
    params: Dict[str, Any] = {
        "embed": q(V, D),
        "embed_scale": s(V),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = q(D, V)
        params["lm_head_scale"] = s(V)
    return params
