"""HF safetensors checkpoint → stacked params pytree.

The reference's model loading happens inside vLLM/sglang; its own code only
resolves paths + metadata (ModelDeploymentCard, lib/llm/src/model_card/
create.rs).  Here we load weights natively: HF llama/mixtral layouts map onto
the stacked-[L, ...] tree that models/llama.py consumes (torch [out, in]
linears transpose to [in, out] matmul layout).

Memory notes: tensors stream from safetensors one at a time; per-layer
tensors accumulate as numpy then stack.  Sharded (multi-host) loading applies
the param shardings at device_put time via parallel.shard_tree.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np

from .config import ModelConfig

_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    # Qwen2-style attention biases ([out] vectors, no transpose).
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
    # Mixtral MoE router: torch [E, D] → transpose → router [D, E].
    "block_sparse_moe.gate.weight": ("router", True),
}

# Mixtral expert sub-keys: block_sparse_moe.experts.{e}.{w}.weight.
# w1 = gate proj [F, D], w2 = down proj [D, F], w3 = up proj [F, D];
# all transpose into the [in, out] matmul layout moe_mlp consumes
# (models/moe.py: moe_gate/moe_up [E, D, F], moe_down [E, F, D]).
_EXPERT_MAP = {"w1": "moe_gate", "w2": "moe_down", "w3": "moe_up"}


def _iter_safetensors(path: str):
    from safetensors import safe_open

    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="numpy") as f:
            for key in f.keys():
                yield key, f.get_tensor(key)


def load_params(
    config: ModelConfig, path: str, dtype: Any = None, quant: str | None = None
) -> Dict[str, Any]:
    """Load a HF llama-family checkpoint directory into the params tree.
    A ``.gguf`` path loads through the GGUF container instead.

    ``quant="int8"`` quantizes weight tensors ONE AT A TIME on the host
    (models/quant.py axes) before they reach the device — a full-depth 8B
    checkpoint in bf16 (~16GB) would not fit single-chip HBM, which is the
    point of quantizing.  Matches the reference baseline's quantized-weights
    workload (examples/llm/benchmarks/README.md: ``...-FP8-dynamic``)."""
    import jax.numpy as jnp

    if quant not in (None, "int8"):
        raise ValueError(f"unknown weight quant {quant!r} (supported: int8)")
    if path.endswith(".gguf"):
        from .gguf import load_params_gguf
        from .quant import quantize_params

        if not quant:
            return load_params_gguf(config, path, dtype)
        # Quantizing: keep the full bf16 tree OFF the accelerator — load and
        # quantize on the host CPU device, then move only the int8 tree over
        # (the HF branch below gets the same guarantee tensor-at-a-time).
        import jax

        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            params = quantize_params(load_params_gguf(config, path, dtype))
        return jax.tree_util.tree_map(jax.device_put, params)

    from .quant import _LAYER_QUANT_AXES, _TOP_QUANT_AXES, quantize_array_np

    dt = jnp.dtype(dtype or config.dtype)
    L, E = config.num_layers, config.num_experts
    per_layer: Dict[str, List[Any]] = {}
    # Per-layer quantization scales, same [L] slots as per_layer.
    per_scale: Dict[str, List[Any]] = {}
    # MoE expert tensors: name → [L][E] grid, stacked to [L, E, ...] at the end.
    per_expert: Dict[str, List[List[Any]]] = {}
    per_expert_scale: Dict[str, List[List[Any]]] = {}
    params: Dict[str, Any] = {"layers": {}}

    def put_layer(name: str, idx: int, value: np.ndarray) -> None:
        if quant and name in _LAYER_QUANT_AXES:
            # Stacked axis is 0, so the per-tensor quant axis is one less.
            q, s = quantize_array_np(value, _LAYER_QUANT_AXES[name] - 1)
            per_scale.setdefault(name, [None] * L)[idx] = s
            value = q
        per_layer.setdefault(name, [None] * L)[idx] = value

    def put_top(name: str, value: np.ndarray) -> None:
        if quant and name in _TOP_QUANT_AXES:
            q, s = quantize_array_np(value, _TOP_QUANT_AXES[name])
            params[name] = jnp.asarray(q)
            params[name + "_scale"] = jnp.asarray(s)
        else:
            params[name] = jnp.asarray(value, dt)

    for key, tensor in _iter_safetensors(path):
        if key == "model.embed_tokens.weight":
            put_top("embed", tensor)
        elif key == "model.norm.weight":
            params["final_norm"] = jnp.asarray(tensor, dt)
        elif key == "lm_head.weight":
            put_top("lm_head", tensor.T)
        elif key.startswith("model.layers."):
            rest = key[len("model.layers.") :]
            idx_str, sub = rest.split(".", 1)
            if sub.startswith("block_sparse_moe.experts."):
                if not config.is_moe:
                    raise ValueError(
                        f"config {config.name!r} is dense but checkpoint has "
                        f"MoE expert tensors ({key})"
                    )
                e_rest = sub[len("block_sparse_moe.experts.") :]
                e_str, w_key = e_rest.split(".", 1)
                name = _EXPERT_MAP.get(w_key.removesuffix(".weight"))
                if name is None:
                    continue
                value = tensor.T
                if quant and name in _LAYER_QUANT_AXES:
                    # Stacked axes are [L, E], so quant axis is two less.
                    q, s = quantize_array_np(value, _LAYER_QUANT_AXES[name] - 2)
                    sgrid = per_expert_scale.setdefault(
                        name, [[None] * E for _ in range(L)]
                    )
                    sgrid[int(idx_str)][int(e_str)] = s
                    value = q
                grid = per_expert.setdefault(name, [[None] * E for _ in range(L)])
                grid[int(idx_str)][int(e_str)] = value
                continue
            mapped = _LAYER_MAP.get(sub)
            if mapped is None:
                continue  # rotary inv_freq buffers etc.
            name, transpose = mapped
            put_layer(name, int(idx_str), tensor.T if transpose else tensor)

    for name, tensors in per_layer.items():
        missing = [i for i, t in enumerate(tensors) if t is None]
        if missing:
            raise ValueError(f"checkpoint missing {name} for layers {missing}")
        stacked = np.stack(tensors)
        if name in per_scale:
            params["layers"][name] = jnp.asarray(stacked)  # int8 as-is
            params["layers"][name + "_scale"] = jnp.asarray(
                np.stack(per_scale[name])
            )
        else:
            params["layers"][name] = jnp.asarray(stacked, dt)

    for name, grid in per_expert.items():
        missing = [
            (i, e) for i in range(L) for e in range(E) if grid[i][e] is None
        ]
        if missing:
            raise ValueError(f"checkpoint missing {name} for (layer, expert) {missing[:8]}")
        stacked = np.stack([np.stack(row) for row in grid])
        if name in per_expert_scale:
            params["layers"][name] = jnp.asarray(stacked)  # int8 as-is
            params["layers"][name + "_scale"] = jnp.asarray(
                np.stack([np.stack(row) for row in per_expert_scale[name]])
            )
        else:
            params["layers"][name] = jnp.asarray(stacked, dt)

    if config.is_moe:
        # Fail at load, not at first forward's KeyError (a dense checkpoint
        # loaded into an MoE config would otherwise silently drop experts).
        needed = {"router", "moe_gate", "moe_up", "moe_down"}
        absent = needed - set(params["layers"])
        if absent:
            raise ValueError(
                f"config {config.name!r} is MoE ({E} experts) but checkpoint is "
                f"missing {sorted(absent)} (block_sparse_moe.gate/experts tensors)"
            )
    if "embed" not in params:
        raise ValueError("checkpoint has no model.embed_tokens.weight")
    if config.tie_word_embeddings:
        params.pop("lm_head", None)
        params.pop("lm_head_scale", None)
    return params


def save_params_hf(params: Dict[str, Any], path: str) -> None:
    """Write params back out in HF naming (testing/interchange helper)."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    # NB: safetensors silently mis-serialises non-contiguous arrays — every
    # tensor (especially transposes) must be made contiguous first.
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.ascontiguousarray(params["embed"]),
        "model.norm.weight": np.ascontiguousarray(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = np.ascontiguousarray(np.asarray(params["lm_head"]).T)
    inv = {v[0]: (k, v[1]) for k, v in _LAYER_MAP.items()}
    inv_expert = {v: k for k, v in _EXPERT_MAP.items()}
    for name, stacked in params["layers"].items():
        arr = np.asarray(stacked)
        if name in inv_expert:
            hf_w = inv_expert[name]
            for i in range(arr.shape[0]):
                for e in range(arr.shape[1]):
                    out[
                        f"model.layers.{i}.block_sparse_moe.experts.{e}.{hf_w}.weight"
                    ] = np.ascontiguousarray(arr[i, e].T)
            continue
        if name not in inv:
            continue
        hf_sub, transpose = inv[name]
        for i in range(arr.shape[0]):
            t = arr[i].T if transpose else arr[i]
            out[f"model.layers.{i}.{hf_sub}"] = np.ascontiguousarray(t)
    save_file(out, os.path.join(path, "model.safetensors"))
