"""Mixture-of-experts FFN with capacity-based dispatch (GShard/Switch style).

The reference only *configures* expert parallelism for TRT-LLM
(examples/tensorrt_llm/configs/llm_api_config.yaml:24-26); here MoE runs
natively.  TPU-first design: token→expert dispatch is expressed as dense
einsums against one-hot dispatch/combine tensors with a fixed per-expert
capacity — fully static shapes, shardable over an "ep" mesh axis (experts
dimension), with the all-to-all realised by XLA when expert and token
shardings differ.  Overflowing tokens (beyond capacity) fall through the
residual connection — standard Switch behaviour.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .config import ModelConfig


def init_moe_params(config: ModelConfig, key: jax.Array, dt) -> Dict[str, jnp.ndarray]:
    L, D = config.num_layers, config.hidden_size
    E, F = config.num_experts, config.moe_intermediate_size or config.intermediate_size
    keys = jax.random.split(key, 4)

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    return {
        "router": norm(keys[0], L, D, E),
        "moe_gate": norm(keys[1], L, E, D, F),
        "moe_up": norm(keys[2], L, E, D, F),
        "moe_down": norm(keys[3], L, E, F, D),
    }


def moe_mlp(
    x: jnp.ndarray,  # [B, Sq, D]
    lp: Dict[str, jnp.ndarray],  # this layer's params (leading L stripped)
    config: ModelConfig,
    capacity_factor: float | None = None,
) -> jnp.ndarray:
    """Gather/scatter dispatch: per-expert token-index tables [E, C] instead
    of one-hot dispatch tensors, so memory is O(E·C·D) activations + O(T·K·E)
    routing ints (no [T, E, C] one-hots).

    capacity_factor None = dropless (C = T, the worst case of every token
    routing to one expert): inference must not drop tokens, and dropless also
    keeps prefill/decode bit-consistent.  Bounded capacity is opt-in for
    throughput experiments; overflowing tokens fall through the residual.
    """
    B, Sq, D = x.shape
    T = B * Sq
    E, K = config.num_experts, config.num_experts_per_token
    capacity = T if capacity_factor is None else max(1, int(capacity_factor * T * K / E))

    xt = x.reshape(T, D)
    router_logits = (xt @ lp["router"]).astype(jnp.float32)  # [T, E]
    weights, chosen = jax.lax.top_k(router_logits, K)  # [T, K]
    weights = jax.nn.softmax(weights, axis=-1)  # renormalise over chosen

    # Queue position of each (t, k) assignment within its expert.
    flat_e = chosen.reshape(T * K)  # expert id per assignment
    flat_t = jnp.repeat(jnp.arange(T, dtype=jnp.int32), K)  # token per assignment
    flat_w = weights.reshape(T * K)
    onehot_e = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)  # [T*K, E]
    pos = (jnp.cumsum(onehot_e, axis=0) - 1)[jnp.arange(T * K), flat_e]  # [T*K]
    overflow = pos >= capacity
    pos_safe = jnp.where(overflow, capacity, pos)  # OOB rows dropped by scatter

    # dispatch_idx[e, c] = source token index (T = padding row).
    dispatch_idx = jnp.full((E, capacity), T, jnp.int32)
    dispatch_idx = dispatch_idx.at[flat_e, pos_safe].set(flat_t, mode="drop")
    gate_w = jnp.zeros((E, capacity), jnp.float32)
    gate_w = gate_w.at[flat_e, pos_safe].set(flat_w, mode="drop")

    from ..ops.quant_matmul import expert_linear

    x_pad = jnp.concatenate([xt, jnp.zeros((1, D), xt.dtype)], axis=0)
    xe = x_pad[dispatch_idx]  # [E, C, D]
    gate = jax.nn.silu(
        expert_linear(xe, lp, "moe_gate", jnp.float32)
    ).astype(x.dtype)
    up = expert_linear(xe, lp, "moe_up")
    ye = expert_linear(gate * up, lp, "moe_down")  # [E, C, D]

    # Combine: weighted scatter-add back to token rows.
    ye_w = ye.astype(jnp.float32) * gate_w[..., None]
    yt = jnp.zeros((T + 1, D), jnp.float32)
    yt = yt.at[dispatch_idx.reshape(-1)].add(ye_w.reshape(-1, D), mode="drop")
    return yt[:T].astype(x.dtype).reshape(B, Sq, D)
