"""Model families executed natively in JAX (the reference delegates model
execution to vLLM/sglang engine subprocesses; here the engine IS the
framework — SURVEY.md §2.8, §7 stage 4)."""

from .config import ModelConfig, get_config, register_config  # noqa: F401
