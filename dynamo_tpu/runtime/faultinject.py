"""Deterministic fault injection for chaos tests and the fault-matrix sweep.

Fault points (hooked where the failure would really occur, behind
``faults.enabled`` so the disabled path costs one attribute read):

=================  =========================================  ==============
point              hooked in                                  simulates
=================  =========================================  ==============
``connect_error``  ``transports/service.MuxConnection``       dead worker /
                                                              refused dial
``delay``          ``transports/service.ServiceServer``       slow worker
                   (before the response prologue)             (stalls TTFB)
``error_prologue`` ``transports/service.ServiceServer``       worker sick at
                                                              stream setup
``drop_mid_stream`` ``transports/service.ServiceServer``      worker killed
                   (connection aborted after an item)         after 1st token
``watch_stall``    ``transports/hub.HubState._notify``        hub partition:
                                                              watchers stale
``watch_error``    ``transports/hub.Watcher``                 watch stream
                                                              crash
``worker_crash``   ``transports/service.ServiceServer``       whole worker
                   (aborts EVERY connection + stops           dies mid-step
                   accepting; fires ``on_crash``)
``hub_outage``     ``transports/hub.HubServer``               control plane
                   (drops new + established connections       down (leases,
                   while armed; disarm = hub back up)         watches, queues)
``slow_stream``    ``transports/service.ServiceServer``       straggler: ITL
                   (``delay_s`` sleep before each item)       outlier worker
``kv_pressure``    ``engine/scheduler`` free-block view       KV pool squeeze
                   (``delay_s`` = fraction withheld)          → preemptions
``tenant_flood``   ``benchmarks/goodput.py`` trace driver     noisy neighbor:
                   (``delay_s`` = rate multiplier; a seeded   one tenant
                   flood trace replays over the fault's       floods the fleet
                   scheduled window)
``kv_corrupt``     per-plane KV integrity boundaries          KV payload
                   (``match`` names the plane): ``disk`` =    bit-rot on the
                   ``DiskKvStore.read`` post-OS-read flip,    named medium /
                   ``host`` = ``_restore_pass`` pre-scatter   boundary; the
                   flip, ``wire`` = ``inject_blocks``         checksum plane
                   post-parse flip (covers pull, migration    must detect it
                   push, disagg import)                       before scatter
``hub_shard_kill`` ``benchmarks/goodput.py`` ChaosFleet       one hub shard's
                   (kills the victim shard's PRIMARY, holds   primary dies
                   the window, then promotes its warm         mid-burst; the
                   ``HubStandby`` onto the same address)      standby takes
                                                              over the shard
``bulk_conn_drop`` ``transports/bulk.BulkServer``             bulk peer dies
                   (aborts the peer connection between        mid-transfer;
                   chunks; cached transfer state survives     the client
                   for resume)                                resumes, else
                                                              falls back
``bulk_slow_peer`` ``transports/bulk.BulkServer``             straggler bulk
                   (``delay_s`` stall before each chunk)      peer stalls
                                                              each chunk
=================  =========================================  ==============

``tenant_flood`` is a *traffic* fault, not a transport one: the armed level
is read by the overload-rung trace driver as the flooding tenant's rate
multiplier, and the system under test is the QoS plane (scheduler WFQ,
edge quotas — llm/qos.py), whose job is to keep the OTHER tenants whole.

``kv_corrupt`` is a *data* fault: it flips one payload byte after the
structural checks' vantage point, and the system under test is the KV
integrity plane (engine/integrity.py) — detection before any scatter,
descendant drop + negative cache, byte-identical recompute fallback.
Arm per plane (``kv_corrupt:disk``, ``kv_corrupt:host``,
``kv_corrupt:wire``) or ``kv_corrupt`` for all three.

``hub_shard_kill`` is a *topology* fault, not an armed one: like the
chaos ladder's real ``hub_outage`` kill, the L8 rung actually closes the
victim shard's primary HubServer and later promotes its replication-fed
standby (transports/hub.HubStandby) onto the same address — the system
under test is the sharded control plane (transports/shard.py): per-shard
park/replay, lease-floor preservation across the handoff, and the routed
clients' degraded-mode routing cache.  Armed per-shard *outage* (drop
connections without failover) is already expressible as
``hub_outage:<shard address>``.

``bulk_conn_drop`` / ``bulk_slow_peer`` are *bulk data-plane* faults
(transports/bulk.py, docs/bulk_plane.md): hook keys are
``<bulk address>/<source>``, so a fault can target one peer's KV export
stream (``bulk_conn_drop:kv_export``) or every bulk transfer (``*``).
``bulk_conn_drop`` aborts the TCP connection between chunks while the
server's live transfer state survives — the system under test is
resume-from-last-verified-chunk plus the fallback ladder (hub path, then
local recompute): streams stay byte-identical and none drop (the L9 chaos
rung).  ``bulk_slow_peer`` stalls ``delay_s`` before each chunk (a
straggling peer NIC); the client's per-attempt timeout converts a
hopeless straggler into a hub-path fallback instead of a hung pull.

Arming: programmatic (``faults.arm("connect_error", match=addr, count=2)``)
or env-driven for subprocess workers — ``DYN_FAULTS`` is a comma-separated
list of ``point[:match][#count]`` specs (``match`` substring-matches the
hook's key, and may itself contain ``:`` as in ``host:port``; ``*`` matches
everything; no ``#count`` = until disarmed), e.g.
``DYN_FAULTS='connect_error:127.0.0.1:9001#2,delay:*'``.

A ``count``-armed fault auto-expires after firing ``count`` times, so a test
can kill exactly the first N dials and then watch recovery.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "DYN_FAULTS"


@dataclass
class _Fault:
    point: str
    match: str = "*"
    count: Optional[int] = None  # None = until disarmed
    delay_s: float = 0.05  # only meaningful for the "delay" point
    fired: int = field(default=0)

    def matches(self, key: str) -> bool:
        return self.match == "*" or self.match in key

    @property
    def exhausted(self) -> bool:
        return self.count is not None and self.fired >= self.count


class FaultInjector:
    """Process-global registry of armed fault points.

    ``enabled`` is the single hot-path guard: every hook site reads it first
    (``if faults.enabled and faults.should(...)``) so production traffic with
    nothing armed pays one attribute load.
    """

    def __init__(self):
        self.enabled = False
        self._points: Dict[str, List[_Fault]] = {}

    # -- arming -------------------------------------------------------------

    def arm(
        self,
        point: str,
        match: str = "*",
        count: Optional[int] = None,
        delay_s: float = 0.05,
    ) -> _Fault:
        fault = _Fault(point=point, match=match, count=count, delay_s=delay_s)
        self._points.setdefault(point, []).append(fault)
        self.enabled = True
        logger.warning("fault armed: %s match=%r count=%s", point, match, count)
        return fault

    def disarm(self, point: Optional[str] = None, match: Optional[str] = None) -> None:
        if point is None:
            self._points.clear()
        elif match is None:
            self._points.pop(point, None)
        else:
            kept = [f for f in self._points.get(point, []) if f.match != match]
            if kept:
                self._points[point] = kept
            else:
                self._points.pop(point, None)
        self.enabled = any(self._points.values())

    def reset(self) -> None:
        self.disarm()

    # -- hook-site queries ---------------------------------------------------

    def _find(self, point: str, key: str) -> Optional[_Fault]:
        for fault in self._points.get(point, []):
            if not fault.exhausted and fault.matches(key):
                return fault
        return None

    def is_armed(self, point: str, key: str = "") -> bool:
        """Non-consuming check (for faults that hold, e.g. watch_stall)."""
        return self._find(point, key) is not None

    def should(self, point: str, key: str = "") -> bool:
        """Consuming check: counts one firing against a count-limited fault."""
        fault = self._find(point, key)
        if fault is None:
            return False
        fault.fired += 1
        if fault.exhausted:
            self._prune(point)
        logger.warning("fault fired: %s key=%r (%d)", point, key, fault.fired)
        return True

    def delay_for(self, point: str, key: str = "") -> float:
        """Consuming delay lookup: seconds to stall, or 0.0 if not armed."""
        fault = self._find(point, key)
        if fault is None:
            return 0.0
        fault.fired += 1
        if fault.exhausted:
            self._prune(point)
        return fault.delay_s

    def level_for(self, point: str, key: str = "") -> float:
        """Non-consuming magnitude lookup: the armed fault's ``delay_s``
        reinterpreted as a level (e.g. ``kv_pressure`` = fraction of the
        free-block pool withheld), or 0.0 when not armed.  Holding faults
        read this every pass, so it never counts against ``count``."""
        fault = self._find(point, key)
        return 0.0 if fault is None else fault.delay_s

    def _prune(self, point: str) -> None:
        kept = [f for f in self._points.get(point, []) if not f.exhausted]
        if kept:
            self._points[point] = kept
        else:
            self._points.pop(point, None)
        self.enabled = any(self._points.values())

    # -- env ----------------------------------------------------------------

    def load_env(self, raw: Optional[str] = None) -> None:
        """Parse ``DYN_FAULTS`` (``point[:match][@level][#count]`` list)."""
        raw = os.environ.get(ENV_VAR, "") if raw is None else raw
        for spec in filter(None, (s.strip() for s in raw.split(","))):
            count: Optional[int] = None
            # '#' separates the count so a match may contain ':' (host:port)
            if "#" in spec:
                spec, _, count_s = spec.rpartition("#")
                if count_s.isdigit():
                    count = int(count_s)
            delay_s = 0.05
            if "@" in spec:
                spec, _, level_s = spec.rpartition("@")
                try:
                    delay_s = float(level_s)
                except ValueError:
                    spec = f"{spec}@{level_s}"  # not a level; restore
            point, _, match = spec.partition(":")
            self.arm(point, match=match or "*", count=count, delay_s=delay_s)


faults = FaultInjector()
if os.environ.get(ENV_VAR):
    faults.load_env()
