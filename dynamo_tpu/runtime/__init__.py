"""Distributed runtime core (reference: lib/runtime/)."""

from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    Context,
    ResponseStream,
    collect,
    engine_from_generator,
)
from .pipeline import MapOperator, Operator, ServiceBackend, build_pipeline

__all__ = [
    "AsyncEngine",
    "AsyncEngineContext",
    "Context",
    "ResponseStream",
    "collect",
    "engine_from_generator",
    "MapOperator",
    "Operator",
    "ServiceBackend",
    "build_pipeline",
]
