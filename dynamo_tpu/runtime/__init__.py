"""Distributed runtime core (reference: lib/runtime/)."""

from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    Context,
    ResponseStream,
    collect,
    engine_from_generator,
)
from .config import RuntimeConfig, env_overrides  # noqa: F401
from .logging_config import JsonlFormatter, parse_filter, setup_logging  # noqa: F401
from .pipeline import MapOperator, Operator, ServiceBackend, build_pipeline
from .client import Client, NoInstancesError, RouterMode
from .resilience import (
    AdmissionController,
    AdmissionRejected,
    BreakerState,
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
)
from .faultinject import faults
from .health import (
    HealthConfig,
    HealthWatchdog,
    WorkerLatencyTracker,
    health_metrics,
    probe_address,
    worker_latency,
)
from .component import (
    Component,
    DistributedRuntime,
    Endpoint,
    Namespace,
    endpoint_path,
    parse_endpoint_path,
)
from .transports.hub import (
    HubClient,
    HubServer,
    HubSessionLost,
    HubStandby,
    InprocHub,
    WatchEvent,
)
from .transports.shard import (
    CrossShardError,
    ShardedHubClient,
    ShardMap,
    hub_key,
    hub_prefix,
    hub_subject,
    shard_metrics,
)
from .transports.service import RemoteEngine, RemoteEngineError, ServiceServer

__all__ = [
    "Client",
    "NoInstancesError",
    "RouterMode",
    "AdmissionController",
    "AdmissionRejected",
    "BreakerState",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceededError",
    "RetryPolicy",
    "faults",
    "HealthConfig",
    "HealthWatchdog",
    "WorkerLatencyTracker",
    "health_metrics",
    "probe_address",
    "worker_latency",
    "HubSessionLost",
    "Component",
    "DistributedRuntime",
    "Endpoint",
    "Namespace",
    "endpoint_path",
    "parse_endpoint_path",
    "HubClient",
    "HubServer",
    "HubStandby",
    "InprocHub",
    "WatchEvent",
    "CrossShardError",
    "ShardedHubClient",
    "ShardMap",
    "hub_key",
    "hub_prefix",
    "hub_subject",
    "shard_metrics",
    "RemoteEngine",
    "RemoteEngineError",
    "ServiceServer",
    "AsyncEngine",
    "AsyncEngineContext",
    "Context",
    "ResponseStream",
    "collect",
    "engine_from_generator",
    "MapOperator",
    "Operator",
    "ServiceBackend",
    "build_pipeline",
]
