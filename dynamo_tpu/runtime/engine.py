"""AsyncEngine core: the universal service trait + per-request context.

Reference semantics (not code): lib/runtime/src/engine.rs:46-109 —
``AsyncEngine<Req, Resp, E>::generate()`` is the single trait every service
stage implements; ``AsyncEngineContext`` carries the request id plus two-level
cancellation (``stop_generating`` = graceful, ``kill`` = immediate).

TPU-native design notes: the runtime layer is pure host-side asyncio; nothing
here touches JAX.  Engines that drive a TPU device loop observe
``ctx.is_stopped`` between device steps (a batched synchronous device loop
cannot be pre-empted mid-step, so cancellation is polled at step granularity).
"""

from __future__ import annotations

import asyncio
import uuid
from abc import ABC, abstractmethod
from typing import AsyncIterator, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")
Req = TypeVar("Req")
Resp = TypeVar("Resp")


class AsyncEngineContext:
    """Per-request identity + cancellation.

    Two levels of cancellation mirror the reference (engine.rs:46-85):
    - ``stop_generating()`` — graceful: stop producing new items, flush what's
      in flight (used on client disconnect).
    - ``kill()`` — immediate: also stop streaming already-produced items.

    Child contexts are linked so cancelling a parent cascades.
    """

    __slots__ = (
        "_id", "_stopped", "_killed", "_children", "_stop_event", "deadline",
        "trace",
    )

    def __init__(self, id: Optional[str] = None, deadline=None, trace=None):
        self._id = id if id is not None else uuid.uuid4().hex
        self._stopped = False
        self._killed = False
        self._children: List["AsyncEngineContext"] = []
        self._stop_event: asyncio.Event = asyncio.Event()
        # Optional resilience.Deadline: the request's remaining wall-clock
        # budget, decremented across hops (serialized on the wire by the
        # service plane, enforced by Client retries and the HTTP edge).
        self.deadline = deadline
        # Optional tracing.TraceContext: the request's span-plane identity,
        # set by the HTTP edge (sampling decision) or the service transport
        # (``trace`` request-header key) and read by every instrumented hop
        # (runtime/tracing.py).  None = untraced — the zero-cost path.
        self.trace = trace

    @property
    def id(self) -> str:
        return self._id

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    @property
    def is_killed(self) -> bool:
        return self._killed

    def stop_generating(self) -> None:
        self._stopped = True
        self._stop_event.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._killed = True
        for c in self._children:
            c.kill()
        self.stop_generating()

    def link_child(self, child: "AsyncEngineContext") -> None:
        self._children.append(child)
        if self._stopped:
            child.stop_generating()
        if self._killed:
            child.kill()

    async def stopped(self) -> None:
        """Wait until stop_generating()/kill() is called."""
        await self._stop_event.wait()


class Context(Generic[T]):
    """``SingleIn<T>`` — a request payload + its engine context.

    Reference: lib/runtime/src/pipeline.rs:209-236 (``SingleIn<T> =
    Context<T>``) and pipeline/context.rs.  ``map``/``transfer`` move the
    context between pipeline stages without re-creating ids.
    """

    __slots__ = ("data", "ctx")

    def __init__(self, data: T, ctx: Optional[AsyncEngineContext] = None):
        self.data = data
        self.ctx = ctx if ctx is not None else AsyncEngineContext()

    @classmethod
    def with_id(cls, data: T, id: str) -> "Context[T]":
        return cls(data, AsyncEngineContext(id))

    @property
    def id(self) -> str:
        return self.ctx.id

    def map(self, fn: Callable[[T], U]) -> "Context[U]":
        return Context(fn(self.data), self.ctx)

    def transfer(self, data: U) -> "Context[U]":
        return Context(data, self.ctx)

    # Convenience passthroughs
    @property
    def is_stopped(self) -> bool:
        return self.ctx.is_stopped

    def stop_generating(self) -> None:
        self.ctx.stop_generating()


class ResponseStream(Generic[T]):
    """``ManyOut<T>`` — an async stream of response items with its context.

    Async-iterating the stream honours ``kill()`` (items are dropped once
    killed) and stops cleanly when the producer finishes.  Dropping the
    consumer (``GeneratorExit`` / task cancellation) propagates
    ``stop_generating()`` upstream so device loops stop scheduling the request
    — the reference does the same when a TCP response send fails
    (pipeline/network/ingress/push_handler.rs:100-116).
    """

    def __init__(self, iterator: AsyncIterator[T], ctx: AsyncEngineContext):
        self._iterator = iterator
        self.ctx = ctx

    @property
    def id(self) -> str:
        return self.ctx.id

    def __aiter__(self) -> "ResponseStream[T]":
        return self

    async def __anext__(self) -> T:
        if self.ctx.is_killed:
            await self._close_inner()
            raise StopAsyncIteration
        try:
            item = await self._iterator.__anext__()
        except asyncio.CancelledError:
            # Consumer task torn down (e.g. HTTP client disconnected): tell
            # upstream to stop scheduling this request.
            self.ctx.stop_generating()
            raise
        if self.ctx.is_killed:
            await self._close_inner()
            raise StopAsyncIteration
        return item

    async def aclose(self) -> None:
        """Abandon the stream: stop upstream generation and close the source."""
        self.ctx.stop_generating()
        await self._close_inner()

    async def _close_inner(self) -> None:
        aclose = getattr(self._iterator, "aclose", None)
        if aclose is not None:
            try:
                await aclose()
            except RuntimeError:
                pass

    def map(self, fn: Callable[[T], U]) -> "ResponseStream[U]":
        src = self

        async def mapped() -> AsyncIterator[U]:
            try:
                async for item in src._iterator:
                    yield fn(item)
            finally:
                await src._close_inner()

        return ResponseStream(mapped(), self.ctx)


class AsyncEngine(ABC, Generic[Req, Resp]):
    """The universal service trait: ``SingleIn<Req> -> ManyOut<Resp>``.

    Every stage — HTTP handler, preprocessor, router, the TPU engine itself,
    and remote clients — implements this one interface, so local and
    distributed pipelines compose identically (reference: engine.rs:103-109).
    """

    @abstractmethod
    async def generate(self, request: Context[Req]) -> ResponseStream[Resp]:
        ...


def engine_from_generator(
    fn: Callable[[Context[Req]], AsyncIterator[Resp]]
) -> AsyncEngine[Req, Resp]:
    """Build an AsyncEngine from a plain async-generator function."""

    class _Lambda(AsyncEngine):
        async def generate(self, request: Context) -> ResponseStream:
            return ResponseStream(fn(request), request.ctx)

    return _Lambda()


async def collect(stream: ResponseStream[T]) -> List[T]:
    """Drain a stream into a list (test/aggregation helper)."""
    return [item async for item in stream]
