"""Routed client: discovery-watching AsyncEngine with pluggable routing.

Reference semantics: lib/runtime/src/component/client.rs — the client watches
the instance prefix, maintains the live instance set (shrinking on lease
expiry), and routes each request Random/RoundRobin/Direct.  KV-aware routing
plugs in above this layer (the KV router picks a worker_id, then calls
``direct``).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

from .engine import AsyncEngine, Context, ResponseStream
from .transports.service import RemoteEngine


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    # KV-cache-aware routing: interpreted by the serving layer (ModelWatcher
    # builds a KvPushRouter around the client); the Client itself treats it
    # as round-robin fallback.  Reference: component/client.rs RouterMode::KV.
    KV = "kv"


class NoInstancesError(RuntimeError):
    """No live instances registered for the endpoint."""


class Client(AsyncEngine):
    """AsyncEngine over the live instances of one endpoint."""

    def __init__(self, hub, instance_prefix: str, router_mode: RouterMode = RouterMode.ROUND_ROBIN):
        self.hub = hub
        self.instance_prefix = instance_prefix
        self.router_mode = router_mode
        self._instances: Dict[int, Dict[str, Any]] = {}
        self._rr_index = 0
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._ready = asyncio.Event()
        self._static_engine: Optional[RemoteEngine] = None

    @classmethod
    def static(cls, address: str, path: str) -> "Client":
        client = cls(hub=None, instance_prefix="")
        client._static_engine = RemoteEngine(address, path)
        client._ready.set()
        return client

    async def start(self) -> "Client":
        if self._static_engine is not None or self._watch_task is not None:
            return self
        self._watcher = await self.hub.watch_prefix(self.instance_prefix)
        self._watch_task = asyncio.create_task(self._watch_loop())
        # The hub terminates the snapshot with a sync marker; wait for it so
        # the first generate() sees every already-registered instance.
        await self._watcher.synced.wait()
        return self

    async def _watch_loop(self) -> None:
        try:
            async for event in self._watcher:
                try:
                    worker_id = int(event.key.rsplit("/", 1)[-1])
                except ValueError:
                    # unrelated key under the prefix; the watch must survive
                    logger.warning("ignoring non-instance key %r", event.key)
                    continue
                try:
                    if event.type == "put":
                        self._instances[worker_id] = event.value
                    else:
                        self._instances.pop(worker_id, None)
                    if self._instances:
                        self._ready.set()
                    else:
                        self._ready.clear()
                except Exception:  # noqa: BLE001 — keep the watch alive
                    logger.exception("error handling instance event %r", event)
        except asyncio.CancelledError:
            pass

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        if self._watcher is not None:
            await self._watcher.aclose()

    # -- instance access ----------------------------------------------------

    @property
    def instance_ids(self) -> List[int]:
        return list(self._instances.keys())

    def instance(self, worker_id: int) -> Optional[Dict[str, Any]]:
        return self._instances.get(worker_id)

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._ready.wait(), timeout)

    # -- routing ------------------------------------------------------------

    def _pick(self, worker_id: Optional[int], mode: RouterMode) -> Dict[str, Any]:
        if not self._instances:
            raise NoInstancesError(f"no instances under {self.instance_prefix!r}")
        if worker_id is not None:
            info = self._instances.get(worker_id)
            if info is None:
                raise NoInstancesError(f"instance {worker_id} not found")
            return info
        ids = sorted(self._instances.keys())
        if mode == RouterMode.RANDOM:
            return self._instances[random.choice(ids)]
        # ROUND_ROBIN (and KV fallback when no overlap decision was made)
        self._rr_index = (self._rr_index + 1) % len(ids)
        return self._instances[ids[self._rr_index]]

    def _engine_for(self, info: Dict[str, Any]) -> RemoteEngine:
        return RemoteEngine(info["address"], info["path"])

    async def generate(
        self,
        request: Context,
        worker_id: Optional[int] = None,
        mode: Optional[RouterMode] = None,
    ) -> ResponseStream:
        if self._static_engine is not None:
            return await self._static_engine.generate(request)
        info = self._pick(worker_id, mode if mode is not None else self.router_mode)
        return await self._engine_for(info).generate(request)

    # Convenience verbs mirroring the reference bindings (_core.pyi):
    async def random(self, request: Context) -> ResponseStream:
        return await self.generate(request, mode=RouterMode.RANDOM)

    async def round_robin(self, request: Context) -> ResponseStream:
        return await self.generate(request, mode=RouterMode.ROUND_ROBIN)

    async def direct(self, request: Context, worker_id: int) -> ResponseStream:
        return await self.generate(request, worker_id=worker_id)
