"""Routed client: discovery-watching AsyncEngine with pluggable routing.

Reference semantics: lib/runtime/src/component/client.rs — the client watches
the instance prefix, maintains the live instance set (shrinking on lease
expiry), and routes each request Random/RoundRobin/Direct.  KV-aware routing
plugs in above this layer (the KV router picks a worker_id, then calls
``direct``).

Request resilience (SURVEY §5 failure detection, runtime/resilience.py):
lease expiry bounds how long a dead worker stays routable, but between the
crash and the TTL every pick would hit a corpse.  ``generate`` therefore
retries connect-time and before-first-token failures on OTHER instances
(bounded attempts, exponential backoff with full jitter), consults a
per-worker-address circuit breaker when picking (open breakers are skipped;
a half-open probe re-admits the worker after its reset window), and honours
the request deadline at every hop.  Once a token has streamed the request is
NOT idempotent — mid-stream failures surface to the caller untouched.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import time
from typing import Any, Dict, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

from .engine import AsyncEngine, Context, ResponseStream
from .health import worker_latency
from .resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
    metrics,
)
from .tracing import span as trace_span
from .transports.service import RemoteEngine, RemoteEngineError
from .transports.shard import shard_metrics


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    # KV-cache-aware routing: interpreted by the serving layer (ModelWatcher
    # builds a KvPushRouter around the client); the Client itself treats it
    # as round-robin fallback.  Reference: component/client.rs RouterMode::KV.
    KV = "kv"


class NoInstancesError(RuntimeError):
    """No live instances registered for the endpoint (HTTP edge → 503)."""

    def __init__(self, message: str, prefix: str = ""):
        super().__init__(message)
        self.prefix = prefix


def _resilience_config() -> Dict[str, Any]:
    """The layered config's `resilience` section ({} if unloadable)."""
    from .config import RuntimeConfig

    try:
        return RuntimeConfig.from_layers().resilience
    except Exception:  # noqa: BLE001 — bad config file must not kill routing
        logger.warning("could not load resilience config; using defaults",
                       exc_info=True)
        return {}


def _is_retryable(exc: BaseException) -> bool:
    """Transport/worker failures may be replayed elsewhere; app errors not."""
    if isinstance(exc, RemoteEngineError):
        return exc.retryable
    return isinstance(exc, (ConnectionError, OSError, EOFError))


class Client(AsyncEngine):
    """AsyncEngine over the live instances of one endpoint."""

    WATCH_BACKOFF_INITIAL = 0.1
    WATCH_BACKOFF_MAX = 5.0

    def __init__(
        self,
        hub,
        instance_prefix: str,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        retry_policy: Optional[RetryPolicy] = None,
        breaker_failure_threshold: Optional[int] = None,
        breaker_reset_s: Optional[float] = None,
    ):
        self.hub = hub
        self.instance_prefix = instance_prefix
        self.router_mode = router_mode
        # Unset knobs fall back to the layered config's `resilience` section
        # (DYN_RESILIENCE__RETRY_MAX_ATTEMPTS=5 etc.), then to defaults.
        cfg: Dict[str, Any] = {}
        if None in (retry_policy, breaker_failure_threshold, breaker_reset_s):
            cfg = _resilience_config()
        self.retry_policy = retry_policy or RetryPolicy.from_config(cfg)
        self.breaker_failure_threshold = (
            breaker_failure_threshold
            if breaker_failure_threshold is not None
            else int(cfg.get("breaker_failure_threshold", 3))
        )
        self.breaker_reset_s = (
            breaker_reset_s
            if breaker_reset_s is not None
            else float(cfg.get("breaker_reset_s", 5.0))
        )
        self._instances: Dict[int, Dict[str, Any]] = {}
        # One cached RemoteEngine per live instance: constructing per call
        # re-dialed TCP each time; the cache is evicted on connection failure
        # and on instance removal (it is also what the breaker keys off).
        self._engines: Dict[int, RemoteEngine] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}  # by worker address
        self._rr_index = 0
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._ready = asyncio.Event()
        self._static_engine: Optional[RemoteEngine] = None
        # Degraded-mode routing cache: the instance table above IS the
        # cache — picks never block on hub RTT.  While the watch is down
        # (hub/shard outage, failover window) the cache serves stale with
        # the staleness bound surfaced on /metrics; a successful resync
        # clears it.
        self._stale_since: Optional[float] = None

    @classmethod
    def static(cls, address: str, path: str) -> "Client":
        client = cls(hub=None, instance_prefix="")
        client._static_engine = RemoteEngine(address, path)
        client._ready.set()
        return client

    async def start(self) -> "Client":
        if self._static_engine is not None or self._watch_task is not None:
            return self
        self._watcher = await self.hub.watch_prefix(self.instance_prefix)
        self._watch_task = asyncio.create_task(self._watch_loop())
        # The hub terminates the snapshot with a sync marker; wait for it so
        # the first generate() sees every already-registered instance.
        await self._watcher.synced.wait()
        return self

    def _apply_event(self, event) -> None:
        try:
            worker_id = int(event.key.rsplit("/", 1)[-1])
        except ValueError:
            # unrelated key under the prefix; the watch must survive
            logger.warning("ignoring non-instance key %r", event.key)
            return
        try:
            if event.type == "put":
                self._instances[worker_id] = event.value
            else:
                self._instances.pop(worker_id, None)
                self._engines.pop(worker_id, None)
                self._prune_breakers()
            if self._instances:
                self._ready.set()
            else:
                self._ready.clear()
        except Exception:  # noqa: BLE001 — keep the watch alive
            logger.exception("error handling instance event %r", event)

    def _prune_breakers(self) -> None:
        """Drop breakers for addresses no live instance uses (workers churn
        through ephemeral ports; stale gauges must not accumulate)."""
        live = {info["address"] for info in self._instances.values()}
        for address in list(self._breakers):
            if address not in live:
                del self._breakers[address]
                metrics.unregister_breaker(address)

    async def _watch_loop(self) -> None:
        """Consume instance deltas; survive watcher death (not just close).

        A watcher that RAISES (hub hiccup, protocol slip) used to silently
        end this task, freezing the instance set stale forever.  Now the
        watch is re-established with exponential backoff and the instance
        set is fully re-synced from the hub KV — deletes missed during the
        outage must not leave phantom instances (mirrors the watch-restart
        shape in deploy/controller.py).
        """
        backoff = self.WATCH_BACKOFF_INITIAL
        while True:
            try:
                async for event in self._watcher:
                    backoff = self.WATCH_BACKOFF_INITIAL
                    self._apply_event(event)
                return  # watcher closed cleanly (client shutdown)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception(
                    "instance watch for %r died; re-establishing",
                    self.instance_prefix,
                )
                if self._stale_since is None:
                    self._stale_since = time.monotonic()
                    shard_metrics.note_cache_stale(id(self), self._stale_since)
            while True:
                try:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, self.WATCH_BACKOFF_MAX)
                    old, self._watcher = self._watcher, None
                    if old is not None:
                        try:
                            await old.aclose()
                        except asyncio.CancelledError:
                            raise
                        except Exception:  # noqa: BLE001 — dead watcher
                            pass
                    self._watcher = await self.hub.watch_prefix(
                        self.instance_prefix
                    )
                    await self._resync()
                    metrics.watch_restarts_total += 1
                    logger.info(
                        "instance watch for %r re-established (%d instances)",
                        self.instance_prefix,
                        len(self._instances),
                    )
                    break
                except asyncio.CancelledError:
                    return
                except Exception:  # noqa: BLE001 — hub still down
                    logger.warning(
                        "watch re-establish for %r failed; retrying in %.1fs",
                        self.instance_prefix,
                        backoff,
                    )

    async def _resync(self) -> None:
        """Replace the instance set with the hub's current view."""
        snapshot = await self.hub.kv_get_prefix(self.instance_prefix)
        fresh: Dict[int, Dict[str, Any]] = {}
        for key, value in snapshot.items():
            try:
                fresh[int(key.rsplit("/", 1)[-1])] = value
            except ValueError:
                continue
        for wid in set(self._engines) - set(fresh):
            self._engines.pop(wid, None)
        self._instances = fresh
        self._prune_breakers()
        self._stale_since = None
        shard_metrics.note_cache_fresh(id(self))
        if fresh:
            self._ready.set()
        else:
            self._ready.clear()

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None
        if self._watcher is not None:
            await self._watcher.aclose()
        shard_metrics.note_cache_fresh(id(self))

    # -- instance access ----------------------------------------------------

    @property
    def instance_ids(self) -> List[int]:
        return list(self._instances.keys())

    def instance(self, worker_id: int) -> Optional[Dict[str, Any]]:
        return self._instances.get(worker_id)

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        try:
            await asyncio.wait_for(self._ready.wait(), timeout)
        except asyncio.TimeoutError:
            raise NoInstancesError(
                f"no instances under {self.instance_prefix!r} "
                f"after {timeout:g}s",
                prefix=self.instance_prefix,
            ) from None

    # -- routing ------------------------------------------------------------

    def _breaker(self, address: str) -> CircuitBreaker:
        breaker = self._breakers.get(address)
        if breaker is None:
            breaker = CircuitBreaker(
                key=address,
                failure_threshold=self.breaker_failure_threshold,
                reset_timeout_s=self.breaker_reset_s,
            )
            self._breakers[address] = metrics.register_breaker(breaker)
        return breaker

    def _note_pick(self) -> None:
        """Account a pick served from the local routing cache (every pick
        is — admission never blocks on hub RTT); stale hits ride through a
        hub/shard failover window on the last synced view."""
        shard_metrics.routing_cache_hits_total += 1
        if self._stale_since is not None:
            shard_metrics.routing_cache_stale_hits_total += 1

    def _pick(
        self,
        worker_id: Optional[int],
        mode: RouterMode,
        exclude: Set[int] = frozenset(),
    ) -> Tuple[int, Dict[str, Any]]:
        if not self._instances:
            raise NoInstancesError(
                f"no instances under {self.instance_prefix!r}",
                prefix=self.instance_prefix,
            )
        if worker_id is not None:
            info = self._instances.get(worker_id)
            if info is None:
                raise NoInstancesError(
                    f"instance {worker_id} not found",
                    prefix=self.instance_prefix,
                )
            self._note_pick()
            return worker_id, info
        ids = sorted(self._instances.keys())
        candidates = [i for i in ids if i not in exclude] or ids
        # Skip instances whose breaker is open — unless that empties the
        # pool, in which case trying a sick worker beats certain failure.
        healthy = [
            i
            for i in candidates
            if self._breaker(self._instances[i]["address"]).can_attempt()
        ]
        if healthy:
            candidates = healthy
        if mode == RouterMode.RANDOM:
            wid = random.choice(candidates)
        else:
            # ROUND_ROBIN (and KV fallback when no overlap decision was made)
            self._rr_index += 1
            wid = candidates[self._rr_index % len(candidates)]
        self._note_pick()
        return wid, self._instances[wid]

    def _engine_for(self, worker_id: int, info: Dict[str, Any]) -> RemoteEngine:
        engine = self._engines.get(worker_id)
        if engine is None or engine.address != info["address"]:
            engine = RemoteEngine(info["address"], info["path"])
            self._engines[worker_id] = engine
        return engine

    def _evict(self, worker_id: int) -> None:
        self._engines.pop(worker_id, None)

    async def _acquire(
        self,
        request: Context,
        worker_id: Optional[int],
        mode: RouterMode,
        state: Dict[str, Any],
        deadline: Optional[Deadline],
    ) -> Tuple[int, str, ResponseStream]:
        """Open a response stream, retrying connect/prologue failures on
        other instances.  ``state`` ({"attempt", "tried"}) is shared with the
        first-token failover wrapper so the TOTAL attempt budget is bounded
        across both phases."""
        # Route span (runtime/tracing.py): pick + connect, with every retry
        # / failover / breaker-open recorded as span events — the routed
        # client is the one vantage point that sees them all.  NOOP (zero
        # cost) for untraced requests.
        rspan = trace_span(
            getattr(request.ctx, "trace", None), "client.route", "client"
        )
        try:
            return await self._acquire_routed(
                request, worker_id, mode, state, deadline, rspan
            )
        finally:
            rspan.finish()

    async def _acquire_routed(
        self,
        request: Context,
        worker_id: Optional[int],
        mode: RouterMode,
        state: Dict[str, Any],
        deadline: Optional[Deadline],
        rspan,
    ) -> Tuple[int, str, ResponseStream]:
        policy = self.retry_policy
        while True:
            if deadline is not None and deadline.expired:
                metrics.deadline_exceeded_total += 1
                raise DeadlineExceededError("deadline exceeded (routing)")
            try:
                wid, info = self._pick(worker_id, mode, exclude=state["tried"])
            except NoInstancesError:
                if worker_id is not None:
                    raise  # direct routing: the chosen worker is simply gone
                # Pool TRANSIENTLY empty — e.g. a hub restart resynced the
                # instance watch before the workers' lease monitors re-put
                # their registrations.  The fleet is still serving, so wait
                # for discovery to repopulate within the retry budget
                # instead of failing a survivable request (a hub crash
                # pauses traffic, it doesn't kill it).
                state["attempt"] += 1
                metrics.retries_total += 1
                rspan.event("no_instances", attempt=state["attempt"])
                if state["attempt"] >= policy.max_attempts:
                    metrics.retries_exhausted_total += 1
                    raise
                delay = max(policy.backoff(state["attempt"]), 0.1)
                if deadline is not None:
                    delay = min(delay, max(deadline.remaining(), 0.0))
                logger.warning(
                    "request %s: no live instances under %r; waiting %.2fs "
                    "for discovery (attempt %d/%d)",
                    request.id, self.instance_prefix, delay,
                    state["attempt"], policy.max_attempts,
                )
                try:
                    await asyncio.wait_for(self._ready.wait(), delay)
                except asyncio.TimeoutError:
                    pass
                # Membership changed wholesale: prior exclusions are stale.
                state["tried"] = set()
                continue
            address = info["address"]
            breaker = self._breaker(address)
            breaker.on_attempt()
            engine = self._engine_for(wid, info)
            try:
                if deadline is not None:
                    stream = await deadline.bound(
                        engine.generate(request), "connect"
                    )
                else:
                    stream = await engine.generate(request)
            except DeadlineExceededError:
                # An exhausted budget is the request's problem, not proof the
                # worker is sick — don't poison its breaker, but do hand back
                # the half-open probe slot if this attempt was the probe.
                breaker.release_probe()
                metrics.deadline_exceeded_total += 1
                raise
            except asyncio.CancelledError:
                breaker.release_probe()
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if not _is_retryable(e):
                    breaker.release_probe()
                    raise
                breaker.record_failure()
                rspan.event(
                    "retry", worker=wid, breaker=str(breaker.state.value),
                )
                self._evict(wid)
                if worker_id is not None:
                    # Direct routing (the KV router chose): no failover
                    # target exists, so this is not a retry — don't let the
                    # retry counters suggest otherwise.
                    raise
                state["tried"].add(wid)
                state["attempt"] += 1
                metrics.retries_total += 1
                if state["attempt"] >= policy.max_attempts:
                    metrics.retries_exhausted_total += 1
                    raise
                logger.warning(
                    "request %s: worker %s failed (%s); failing over "
                    "(attempt %d/%d)",
                    request.id,
                    wid,
                    e,
                    state["attempt"],
                    policy.max_attempts,
                )
                delay = policy.backoff(state["attempt"])
                if deadline is not None:
                    delay = min(delay, max(deadline.remaining(), 0.0))
                if delay > 0:
                    await asyncio.sleep(delay)
                if state["tried"] >= set(self._instances):
                    # full lap: every live instance failed once — allow
                    # re-dials (the next lap rides the backoff ladder)
                    state["tried"] = set()
                continue
            breaker.record_success()
            rspan.set(worker=wid, address=address)
            return wid, address, stream

    async def generate(
        self,
        request: Context,
        worker_id: Optional[int] = None,
        mode: Optional[RouterMode] = None,
    ) -> ResponseStream:
        if self._static_engine is not None:
            return await self._static_engine.generate(request)
        mode = mode if mode is not None else self.router_mode
        deadline = getattr(request.ctx, "deadline", None)
        state: Dict[str, Any] = {"attempt": 0, "tried": set()}
        wid, address, stream = await self._acquire(
            request, worker_id, mode, state, deadline
        )
        # Every routed stream gets the guard: it consumes live-migration
        # ``migrated`` markers (splicing the target's continuation into one
        # client-visible stream) and resumes seeded streams after mid-flight
        # crashes.  Direct routing (the KV router already chose a worker)
        # keeps its no-failover contract for pre-first-token failures —
        # allow_failover gates only those; the migration splice and seeded
        # resume are deterministic continuations, safe on any instance.
        return ResponseStream(
            _StreamGuard(self, request, mode, state, deadline,
                         wid, address, stream,
                         allow_failover=worker_id is None),
            request.ctx,
        )

    # Convenience verbs mirroring the reference bindings (_core.pyi):
    async def random(self, request: Context) -> ResponseStream:
        return await self.generate(request, mode=RouterMode.RANDOM)

    async def round_robin(self, request: Context) -> ResponseStream:
        return await self.generate(request, mode=RouterMode.ROUND_ROBIN)

    async def direct(self, request: Context, worker_id: int) -> ResponseStream:
        return await self.generate(request, worker_id=worker_id)


class _StreamGuard:
    """Stream wrapper: failover, live-migration splice, seeded resume.

    Three distinct recovery surfaces, in order of when they can fire:

    - **Before the first token** a worker that accepted the prologue can
      still die; nothing user-visible has happened, so the request is
      safely replayable on another instance (bounded attempts shared with
      the connect phase).  Disabled for direct (KV-router-chosen) routing.
    - **A ``migrated`` item** mid-stream is the source worker's cutover
      marker (llm/migration): it carries a self-contained resume request
      plus the target's address.  The guard re-dispatches there (falling
      back to any instance — the resume request is deterministic) and
      splices the continuation in; the caller sees one uninterrupted,
      token-identical stream and never observes the marker.
    - **After the first token** a crash is recoverable only when replaying
      cannot change what the caller already saw: the resume request needs
      deterministic continuation.  Explicit-seed requests always have it;
      greedy (temperature-0) streams are seed-independent and resume
      seedless; for UNSEEDED SAMPLED requests the engine resolves a seed
      at admission and stamps it on the first stream item
      (``resolved_seed`` — engine.py generate), which the guard captures
      here.  So every stream that has delivered a token is resumable; only
      an unseeded sampled stream from a pre-QoS engine (no stamp seen)
      still propagates the error untouched.

    The deadline bounds the wait for every item and every re-dispatch.
    """

    def __init__(
        self,
        client: Client,
        request: Context,
        mode: RouterMode,
        state: Dict[str, Any],
        deadline: Optional[Deadline],
        wid: int,
        address: str,
        stream: ResponseStream,
        allow_failover: bool = True,
    ):
        self._client = client
        self._request = request
        self._mode = mode
        self._state = state
        self._deadline = deadline
        self._wid = wid
        self._address = address
        self._stream = stream
        self._allow_failover = allow_failover
        self._got_first = False
        # Per-worker latency observations (runtime/health.py): the routed
        # client is the one vantage point that sees queueing + transport +
        # engine together, so the straggler scan feeds off these.
        self._t_dispatched = time.monotonic()
        self._t_last_item: Optional[float] = None
        # Resume bookkeeping: the fed-token stream (base prompt + every
        # delivered token) and the original prompt length.  Only tracked
        # for token-shaped requests (dict with token_ids) — other payloads
        # (KV imports, control calls) can't resume and never migrate.
        self._all_tokens: Optional[List[int]] = None
        self._orig_prompt_len = 0
        # Engine-resolved sampler seed for UNSEEDED requests (stamped on the
        # first stream item): makes every stream crash-resumable, not just
        # explicit-seed ones.
        self._resolved_seed: Optional[int] = None
        self._track_request(request.data)

    def __aiter__(self):
        return self

    async def __anext__(self):
        while True:
            try:
                if self._deadline is not None:
                    item = await self._deadline.bound(
                        self._stream.__anext__(),
                        "first token" if not self._got_first else "stream",
                    )
                else:
                    item = await self._stream.__anext__()
            except (StopAsyncIteration, asyncio.CancelledError):
                raise
            except DeadlineExceededError:
                metrics.deadline_exceeded_total += 1
                await self.aclose()
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if not _is_retryable(e):
                    raise
                if self._got_first:
                    if not await self._try_resume(e):
                        raise
                    continue
                if not self._allow_failover:
                    raise
                self._record_failure()
                if not await self._budget_ok(e, "died before first token"):
                    raise
                with trace_span(
                    self._trace(), "client.failover", "client",
                    attrs={"from_worker": self._wid},
                ):
                    self._wid, self._address, self._stream = (
                        await self._client._acquire(
                            self._request, None, self._mode, self._state,
                            self._deadline,
                        )
                    )
                self._reset_latency_anchor()
                continue
            if isinstance(item, dict) and "resolved_seed" in item:
                # Captured (and stripped) before anything else: the stamp
                # may ride the migrated marker when cutover precedes the
                # first token.
                self._resolved_seed = int(item.pop("resolved_seed"))
            if isinstance(item, dict) and item.get("migrated"):
                await self._splice(item["migrated"])
                continue
            now = time.monotonic()
            if not self._got_first:
                worker_latency.record_ttft(
                    self._wid, self._address,
                    (now - self._t_dispatched) * 1e3,
                )
            elif self._t_last_item is not None:
                worker_latency.record_itl(
                    self._wid, self._address,
                    (now - self._t_last_item) * 1e3,
                )
            self._t_last_item = now
            self._got_first = True
            if self._all_tokens is not None and isinstance(item, dict):
                self._all_tokens.extend(item.get("token_ids") or ())
            return item

    # -- recovery helpers ---------------------------------------------------

    def _trace(self):
        """The stream's active TraceContext (None = untraced — every span
        call below is then the shared no-op)."""
        return getattr(self._request.ctx, "trace", None)

    def _reset_latency_anchor(self) -> None:
        """Re-anchor the per-worker latency observations after any
        re-dispatch (failover, resume, splice): the recovery gap belongs to
        the WORKER THAT FAILED, not to the replacement — charging it there
        would make the watchdog's straggler scan quarantine the healthy
        failover target exactly when the fleet is already degraded."""
        self._t_dispatched = time.monotonic()
        self._t_last_item = None

    def _track_request(self, data: Any) -> None:
        """(Re)anchor resume tracking on a request payload: its token_ids
        become the fed-stream base, and its ``resume`` annotation (if any)
        preserves the original prompt length across re-dispatches."""
        if not isinstance(data, dict) or not isinstance(
            data.get("token_ids"), list
        ):
            return
        self._all_tokens = list(data["token_ids"])
        resume = (data.get("annotations") or {}).get("resume") or {}
        self._orig_prompt_len = int(
            resume.get("orig_prompt_len")
            or self._orig_prompt_len
            or len(self._all_tokens)
        )

    def _record_failure(self) -> None:
        client = self._client
        client._breaker(self._address).record_failure()
        client._evict(self._wid)
        self._state["tried"].add(self._wid)

    async def _budget_ok(self, exc: BaseException, what: str) -> bool:
        """Count one retry against the shared budget; backoff if granted."""
        client = self._client
        self._state["attempt"] += 1
        metrics.retries_total += 1
        metrics.failovers_total += 1
        if self._state["attempt"] >= client.retry_policy.max_attempts:
            metrics.retries_exhausted_total += 1
            return False
        logger.warning(
            "request %s: worker %s %s (%s); failing over (attempt %d/%d)",
            self._request.id, self._wid, what, exc,
            self._state["attempt"], client.retry_policy.max_attempts,
        )
        delay = client.retry_policy.backoff(self._state["attempt"])
        if self._deadline is not None:
            delay = min(delay, max(self._deadline.remaining(), 0.0))
        if delay > 0:
            await asyncio.sleep(delay)
        return True

    def _resume_request(self) -> Optional[Context]:
        """Self-contained continuation request from delivered tokens, or
        None when replay could diverge (no seed known client-side)."""
        data = self._request.data if isinstance(self._request.data, dict) else None
        if data is None or self._all_tokens is None:
            return None
        samp = dict(data.get("sampling_options") or {})
        if samp.get("seed") is None:
            if self._resolved_seed is not None:
                # The serving engine stamped its RESOLVED seed on the first
                # stream item exactly for this moment (unseeded sampled
                # requests, engine.py generate).
                samp["seed"] = self._resolved_seed
            elif (samp.get("temperature") or 0.0) > 0.0:
                # Sampled with no seed known client-side: an engine-
                # assigned default incorporates the worker's own engine
                # seed — another instance may re-derive differently.
                # Refuse, as before the resolved-seed stamp existed.
                return None
            # Greedy (temperature 0): argmax is seed-independent, so the
            # continuation is deterministic on any worker — resume seedless.
        resume = dict(data)
        resume["sampling_options"] = samp
        resume["token_ids"] = list(self._all_tokens)
        ann = dict(data.get("annotations") or {})
        prev = dict(ann.get("resume") or {})
        prev["orig_prompt_len"] = self._orig_prompt_len
        ann["resume"] = prev
        resume["annotations"] = ann
        return Context(resume, self._request.ctx)

    async def _try_resume(self, exc: BaseException) -> bool:
        """Mid-stream crash: continue a seeded stream on another worker."""
        request = self._resume_request()
        if request is None:
            return False
        self._record_failure()
        if not await self._budget_ok(exc, "died mid-stream"):
            return False
        with trace_span(
            self._trace(), "client.resume", "client",
            attrs={"from_worker": self._wid, "error": type(exc).__name__},
        ):
            self._wid, self._address, self._stream = (
                await self._client._acquire(
                    request, None, self._mode, self._state, self._deadline
                )
            )
        self._request = request
        self._reset_latency_anchor()
        metrics.stream_resumes_total += 1
        return True

    async def _splice(self, mig: Dict[str, Any]) -> None:
        """Cutover marker: re-dispatch the resume request to the migration
        target and continue the stream there.  A dead target is survivable
        — the resume request is deterministic, so any instance will do."""
        # Splice span: the cutover's client-visible cost (source stream
        # release + target re-dispatch).  The resume request carries the
        # trace in its annotations (migration snapshot), so the target's
        # engine spans join the SAME trace — one migrated stream, one
        # timeline.
        wid = mig.get("worker_id")
        sspan = trace_span(
            self._trace(), "client.splice", "client",
            attrs={"target_worker": wid},
        )
        try:
            await self._splice_inner(mig, sspan)
        except BaseException as e:
            # The raise paths (deadline exhausted, non-retryable target
            # error) are exactly the failed cutovers whose cost matters —
            # record the span instead of leaking it (finish is idempotent).
            sspan.set(error=type(e).__name__)
            raise
        finally:
            sspan.finish()

    async def _splice_inner(self, mig: Dict[str, Any], sspan) -> None:
        req_data = mig.get("request") or {}
        request = Context(req_data, self._request.ctx)
        client = self._client
        wid = mig.get("worker_id")
        try:
            # The marker is the source stream's last payload by protocol —
            # release its mux slot before splicing in the continuation.
            # NOT aclose(): that would stop_generating() the ctx the resume
            # request shares, cancelling the continuation it sets up.
            await self._stream._close_inner()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — source is done with us either way
            pass
        target_addr: Optional[str] = None
        try:
            info = client._instances.get(wid) if wid is not None else None
            if info is not None:
                engine = client._engine_for(wid, info)
                target_addr = info["address"]
            elif mig.get("address") and mig.get("path"):
                # The target may not be in the instance set (e.g. a static
                # deployment); dial it directly from the marker's address.
                engine = RemoteEngine(mig["address"], mig["path"])
                target_addr = mig["address"]
            else:
                raise RemoteEngineError("migration target unspecified")
            if self._deadline is not None:
                stream = await self._deadline.bound(
                    engine.generate(request), "migration splice"
                )
            else:
                stream = await engine.generate(request)
            # Track the TARGET's identity (even when it is not in the
            # instance set): a later mid-stream failure must evict and
            # blacklist the worker that actually failed, not the (healthy)
            # pre-migration source.
            self._wid, self._address = wid, target_addr
        except asyncio.CancelledError:
            raise
        except DeadlineExceededError:
            # Budget ran out mid-splice: the request's problem, not the
            # target's — no breaker poison, and no fallback dispatch (it
            # would be bounded by the same exhausted deadline).
            metrics.deadline_exceeded_total += 1
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            if not _is_retryable(e):
                raise
            # Blacklist the TARGET before falling back — self._wid/_address
            # still name the pre-migration source here, and without the
            # bookkeeping the picker could hand the same dead target
            # straight back, burning a second attempt from the shared
            # budget on a known-dead worker.
            if wid is not None:
                client._evict(wid)
                self._state["tried"].add(wid)
            if target_addr:
                client._breaker(target_addr).record_failure()
            logger.warning(
                "request %s: migration target %s unreachable (%s); "
                "resuming on any instance", self._request.id, wid, e,
            )
            sspan.event("target_unreachable", error=type(e).__name__)
            self._wid, self._address, stream = await client._acquire(
                request, None, self._mode, self._state, self._deadline
            )
        self._stream = stream
        # Task-confined: _StreamGuard is owned by the one consumer task
        # driving this stream, so the request swap cannot race a peer.
        self._request = request  # dynalint: disable=DYN101
        self._reset_latency_anchor()
        # The target's view of the fed stream is authoritative from here.
        self._track_request(req_data)
        # The in-flight request is now the self-contained resolved-seed
        # resume request — safe on ANY instance, so a direct-routed
        # stream's no-failover contract no longer applies: if the target
        # dies before its first post-splice token, fail over rather than
        # kill a request whose source already released the sequence.
        self._allow_failover = True
        metrics.migration_splices_total += 1

    async def aclose(self) -> None:
        await self._stream.aclose()
