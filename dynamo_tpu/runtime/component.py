"""Distributed component model: Runtime → Namespace → Component → Endpoint.

Reference semantics: lib/runtime/src/component.rs:16-42 (naming hierarchy),
component/endpoint.rs:376-460 (endpoint registration under a lease),
lib/runtime/src/distributed.rs (DistributedRuntime = runtime + transports).

Registration scheme (hub KV): ``instances/{ns}/{comp}/{ep}/{worker_id}`` →
``{address, path, worker_id, metadata}`` attached to the worker's lease, so a
dead worker's registrations vanish when its lease expires and every watcher
(clients, HTTP frontend model list, KV indexer) observes the delete — the
reference's etcd-lease liveness design (SURVEY §5 failure detection).
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

from .client import Client, RouterMode
from .engine import AsyncEngine, engine_from_generator
from .transports.hub import HubClient, InprocHub
from .transports.shard import ShardedHubClient, hub_key, hub_prefix, hub_subject
from .transports.service import ServiceServer

INSTANCE_PREFIX = "instances"


def instance_key(ns: str, comp: str, ep: str, worker_id: int) -> str:
    return hub_key(INSTANCE_PREFIX, ns, comp, ep, worker_id)


def instance_prefix(ns: str, comp: Optional[str] = None,
                    ep: Optional[str] = None) -> str:
    """Watch/query prefix under the discovery namespace, at any depth."""
    segments = [INSTANCE_PREFIX, ns]
    if comp is not None:
        segments.append(comp)
        if ep is not None:
            segments.append(ep)
    return hub_prefix(*segments)


def endpoint_path(ns: str, comp: str, ep: str) -> str:
    """The service-plane path an engine is served at (``dyn://ns.comp.ep``)."""
    return f"{ns}.{comp}.{ep}"


def parse_endpoint_path(path: str) -> tuple:
    """Parse ``dyn://ns.comp.ep`` or ``ns.comp.ep`` (reference protocols.rs:49)."""
    if path.startswith("dyn://"):
        path = path[len("dyn://") :]
    parts = path.split(".")
    if len(parts) != 3:
        raise ValueError(f"endpoint path must be ns.component.endpoint, got {path!r}")
    return parts[0], parts[1], parts[2]


class DistributedRuntime:
    """Per-process distributed runtime: hub connection + one service server.

    Construct via ``DistributedRuntime.detached()`` (in-process hub; the
    reference's static mode) or ``DistributedRuntime.connect(address)`` (TCP
    hub).  Every process gets a ``worker_id`` and a primary lease; all
    endpoint registrations default to that lease.
    """

    # 10s tolerates multi-second event-loop stalls (JAX tracing holds the
    # GIL hard even from worker threads); the lease monitor below re-grants
    # and re-registers if a stall still outlives the lease.
    DEFAULT_LEASE_TTL = 10.0

    def __init__(self, hub, host: str = "127.0.0.1", lease_ttl: Optional[float] = None):
        self.hub = hub
        self.worker_id: int = uuid.uuid4().int & ((1 << 63) - 1)
        self.primary_lease: Optional[int] = None
        self.lease_ttl = lease_ttl or self.DEFAULT_LEASE_TTL
        self._host = host
        self._service_server: Optional[ServiceServer] = None
        self._shutdown_event = asyncio.Event()
        # key → value for every primary-lease registration, so a lost lease
        # (event-loop stall > TTL, hub restart) self-heals: re-grant +
        # re-put everything.
        self._registrations: Dict[str, Any] = {}
        self._lease_monitor_task: Optional[asyncio.Task] = None

    @classmethod
    async def detached(cls) -> "DistributedRuntime":
        hub = await InprocHub().start()
        return await cls(hub)._init()

    @classmethod
    async def connect(
        cls,
        address: str,
        host: str = "127.0.0.1",
        lease_ttl: Optional[float] = None,
    ) -> "DistributedRuntime":
        """Connect to the hub control plane.

        ``address`` is one ``host:port`` (a plain ``HubClient`` — byte-
        compatible with every pre-sharding deployment) or a comma-separated
        shard map ``host:port,host:port,...`` (a ``ShardedHubClient``
        routing each key/subject to its owner shard).
        """
        if "," in address:
            hub = await ShardedHubClient(address).connect()
        else:
            hub = await HubClient(address).connect()
        return await cls(hub, host=host, lease_ttl=lease_ttl)._init()

    async def _init(self) -> "DistributedRuntime":
        self.primary_lease = await self.hub.lease_grant(self.lease_ttl)
        self._lease_monitor_task = asyncio.get_running_loop().create_task(
            self._lease_monitor()
        )
        return self

    async def register_key(self, key: str, value: Any) -> None:
        """kv_put under the primary lease, tracked for re-registration."""
        self._registrations[key] = value
        await self.hub.kv_put(key, value, self.primary_lease)

    async def unregister_key(self, key: str) -> None:
        self._registrations.pop(key, None)
        await self.hub.kv_delete(key)

    async def _lease_monitor(self) -> None:
        """Elastic recovery (SURVEY §5 failure detection): if the primary
        lease expired (e.g. a compile stalled the loop past the TTL, or the
        hub itself restarted and lost all lease state), grant a fresh one
        and restore every tracked registration — the worker re-appears to
        watchers instead of staying dead.

        A hub outage must NOT kill this monitor: it is the exact mechanism
        by which a worker rejoins a restarted hub, so connection errors are
        retried on a shortened cadence until shutdown."""
        interval = self.lease_ttl
        while not self._shutdown_event.is_set():
            await asyncio.sleep(interval)
            interval = self.lease_ttl
            if self.primary_lease is None:
                continue
            try:
                alive = await self.hub.lease_keepalive(self.primary_lease)
                if alive:
                    continue
                logger.warning("primary lease lost; re-registering %d keys",
                               len(self._registrations))
                self.primary_lease = await self.hub.lease_grant(self.lease_ttl)
                for key, value in list(self._registrations.items()):
                    await self.hub.kv_put(key, value, self.primary_lease)
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError, OSError):
                # Hub unreachable or mid-restart: retry soon — the backoff
                # budget for fleet re-registration is this cadence plus the
                # HubClient's own reconnect backoff.
                interval = min(self.lease_ttl, max(self.lease_ttl / 5.0, 0.2))
                logger.warning(
                    "lease monitor: hub unreachable; retrying in %.1fs",
                    interval,
                )

    async def service_server(self) -> ServiceServer:
        if self._service_server is None:
            server = await ServiceServer(host=self._host).start()
            if self._service_server is None:  # re-check: bind awaited above
                self._service_server = server
            else:
                # Lost a concurrent lazy-init race while awaiting the bind
                # (dynalint DYN101): endpoints registered on the duplicate
                # would be invisible to the advertised address — keep the
                # winner, close the spare.
                await server.close()
        return self._service_server

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    def shutdown(self) -> None:
        self._shutdown_event.set()

    async def wait_for_shutdown(self) -> None:
        await self._shutdown_event.wait()

    async def close(self) -> None:
        self.shutdown()
        if self._lease_monitor_task is not None:
            self._lease_monitor_task.cancel()
            self._lease_monitor_task = None
        if self._service_server is not None:
            await self._service_server.close()
        if self.primary_lease is not None:
            try:
                # Bounded: revoking against a down/reconnecting hub must not
                # park teardown for the client's whole grace budget — an
                # unrevoked lease just expires by TTL.
                await asyncio.wait_for(
                    self.hub.lease_revoke(self.primary_lease), 2.0
                )
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                pass
        await self.hub.close()


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    # Event plane scoped to the namespace (reference traits/events.rs:30-79)
    def subject(self, topic: str) -> str:
        return hub_subject(self.name, topic)

    async def publish(self, topic: str, payload: Any) -> None:
        await self.runtime.hub.publish(self.subject(topic), payload)

    async def subscribe(self, topic: str):
        return await self.runtime.hub.subscribe(self.subject(topic))


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    @property
    def runtime(self) -> DistributedRuntime:
        return self.namespace.runtime

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    async def create_service(self) -> "Component":
        """API-parity no-op: services materialize on first endpoint serve."""
        return self

    def subject(self, topic: str) -> str:
        return hub_subject(self.namespace.name, self.name, topic)

    async def publish(self, topic: str, payload: Any) -> None:
        await self.runtime.hub.publish(self.subject(topic), payload)

    async def subscribe(self, topic: str):
        return await self.runtime.hub.subscribe(self.subject(topic))


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def runtime(self) -> DistributedRuntime:
        return self.component.runtime

    @property
    def path(self) -> str:
        return endpoint_path(self.component.namespace.name, self.component.name, self.name)

    def instance_key(self, worker_id: int) -> str:
        return instance_key(
            self.component.namespace.name, self.component.name, self.name, worker_id
        )

    @property
    def instance_prefix(self) -> str:
        return instance_prefix(
            self.component.namespace.name, self.component.name, self.name
        )

    async def serve_endpoint(
        self,
        engine,
        lease: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> "ServedEndpoint":
        """Serve an AsyncEngine (or async-generator handler) at this endpoint.

        Registers the instance in the hub KV under a lease (defaults to the
        process primary lease) and on the process service server.  Reference:
        EndpointConfigBuilder::start, component/endpoint.rs:376-460.
        """
        runtime = self.runtime
        if not isinstance(engine, AsyncEngine):
            engine = engine_from_generator(engine)
        server = await runtime.service_server()
        server.register(self.path, engine)
        info = self._instance_info(server.address, metadata)
        key = self.instance_key(runtime.worker_id)
        if lease is None:
            await runtime.register_key(key, info)  # self-healing registration
        else:
            await runtime.hub.kv_put(key, info, lease)
        return ServedEndpoint(self, server)

    def _instance_info(
        self, address: str, metadata: Optional[Dict[str, Any]]
    ) -> Dict[str, Any]:
        return {
            "address": address,
            "path": self.path,
            "worker_id": self.runtime.worker_id,
            "metadata": metadata or {},
        }

    async def update_metadata(
        self, metadata: Optional[Dict[str, Any]] = None
    ) -> None:
        """Rewrite this worker's live instance registration with new
        metadata (e.g. de-advertising a capability mid-drain), keeping the
        record shape in one place."""
        runtime = self.runtime
        server = await runtime.service_server()
        await runtime.register_key(
            self.instance_key(runtime.worker_id),
            self._instance_info(server.address, metadata),
        )

    async def client(self, router_mode: RouterMode = RouterMode.ROUND_ROBIN) -> Client:
        client = Client(self.runtime.hub, self.instance_prefix, router_mode=router_mode)
        await client.start()
        return client

    def static_client(self, address: str) -> Client:
        """Client pinned to one known address — no discovery (static mode)."""
        return Client.static(address, self.path)


class ServedEndpoint:
    """Handle for a served endpoint: supports deregistration."""

    def __init__(self, endpoint: Endpoint, server: ServiceServer):
        self.endpoint = endpoint
        self._server = server

    async def stop(self) -> None:
        runtime = self.endpoint.runtime
        self._server.unregister(self.endpoint.path)
        await runtime.unregister_key(self.endpoint.instance_key(runtime.worker_id))
