"""General timestamped stream record/replay.

Reference counterpart: lib/llm/src/recorder.rs (674 LoC) — capture ANY
request/response stream to JSONL with timestamps, replay it later with or
without the original pacing (debugging, billing audit, load reproduction).
The KV-event recorder (llm/kv_router/recorder.py) is the specialized
sibling; this one wraps arbitrary engines/streams.

Line format (one JSON object per line):
  {"ts": <epoch s>, "stream": <id>, "kind": "request"|"item"|"end",
   "data": <payload>}

Usage:
  rec = StreamRecorder(path)
  engine = RecordingEngine(inner_engine, rec)   # drop-in AsyncEngine wrap
  ...
  async for req, items in replay_streams(path): ...        # audit
  await replay_into(path, engine, timed=True)              # load replay
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, AsyncIterator, Dict, List, Optional, TextIO, Tuple

from .engine import AsyncEngine, Context, ResponseStream


class StreamRecorder:
    """Append-only JSONL for timestamped multi-stream capture."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[TextIO] = open(path, "a", encoding="utf-8")
        self.count = 0

    def record(self, stream: str, kind: str, data: Any) -> None:
        # Tolerate records after close (a stream still draining during
        # shutdown must not blow up its teardown) — they are dropped.
        # Writes are synchronous line appends; heavy production capture
        # should point at fast local disk (the reference's recorder has
        # the same property).
        if self._fh is None or self._fh.closed:
            return
        self._fh.write(
            json.dumps(
                {"ts": time.time(), "stream": stream, "kind": kind, "data": data}
            )
            + "\n"
        )
        self.count += 1

    def flush(self) -> None:
        if self._fh is not None:
            self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class RecordingEngine(AsyncEngine):
    """Drop-in AsyncEngine wrapper: records every request and every
    response item flowing through, without altering either (the reference
    wires its recorder the same way, as a pipeline tap)."""

    def __init__(self, inner: AsyncEngine, recorder: StreamRecorder):
        self.inner = inner
        self.recorder = recorder

    def __getattr__(self, name):  # passthrough (metrics, stats, close, ...)
        return getattr(self.inner, name)

    async def generate(self, request: Context) -> ResponseStream:
        sid = request.id or uuid.uuid4().hex
        self.recorder.record(sid, "request", request.data)
        inner_stream = await self.inner.generate(request)
        rec = self.recorder

        async def tap() -> AsyncIterator[Any]:
            try:
                async for item in inner_stream:
                    rec.record(sid, "item", item)
                    yield item
            finally:
                rec.record(sid, "end", None)
                rec.flush()

        return ResponseStream(tap(), request.ctx)


def load_streams(path: str) -> List[Tuple[Dict[str, Any], List[Any], List[float]]]:
    """Parse a recording into [(request, items, timestamps)] per stream,
    in request order."""
    streams: Dict[str, Tuple[Dict[str, Any], List[Any], List[float]]] = {}
    order: List[str] = []
    live: Dict[str, str] = {}  # raw sid → current unique key
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            sid = row["stream"]
            if row["kind"] == "request":
                # Request ids are client-settable and files append across
                # runs, so a sid can repeat — keep every occurrence
                # distinct instead of silently dropping the earlier one.
                key = sid
                n = 1
                while key in streams:
                    key = f"{sid}#{n}"
                    n += 1
                live[sid] = key
                streams[key] = (row["data"], [], [row["ts"]])
                order.append(key)
            elif row["kind"] == "item" and live.get(sid) in streams:
                key = live[sid]
                streams[key][1].append(row["data"])
                streams[key][2].append(row["ts"])
    return [streams[key] for key in order]


async def replay_into(
    path: str, engine: AsyncEngine, timed: bool = False
) -> List[List[Any]]:
    """Re-issue every recorded request against ``engine``.  Untimed:
    strictly serial, in recorded order (deterministic audit diffs).
    Timed: every request LAUNCHES at its recorded offset from the first —
    overlapping recorded load replays as overlapping load, which is the
    point of load reproduction.  Returns each stream's items in recorded
    request order."""
    rows = load_streams(path)
    if not rows:
        return []

    async def one(request) -> List[Any]:
        stream = await engine.generate(Context(request))
        return [item async for item in stream]

    if not timed:
        return [await one(request) for request, _items, _tss in rows]

    t0 = rows[0][2][0]

    async def timed_one(request, offset: float) -> List[Any]:
        await asyncio.sleep(max(0.0, offset))
        return await one(request)

    return list(
        await asyncio.gather(
            *(timed_one(req, tss[0] - t0) for req, _items, tss in rows)
        )
    )
