"""Pipeline graph: operators with forward (request) and backward (response) edges.

Reference semantics: lib/runtime/src/pipeline/nodes.rs:16-210 — a pipeline is a
chain ``frontend → op₁ → … → opₙ → backend(engine)`` where each operator
transforms the request on the way down (forward edge) and the response stream
on the way back up (backward edge).  One operator object owns both directions
so paired state (e.g. a tokenizer used to encode the prompt and incrementally
decode the output) lives in one place.

Python design: rather than the reference's explicit dual-edge node graph we use
structured composition — an ``Operator`` receives the request and the *next*
engine and returns the transformed stream.  This keeps the same power
(operators can short-circuit, fan out, or annotate both directions) with far
less machinery, and composes into a single ``AsyncEngine`` so a pipeline can
itself be served as an endpoint (``SegmentSource``/``SegmentSink`` in the
reference are just "serve this engine remotely" here).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, Sequence, TypeVar

from .engine import AsyncEngine, Context, ResponseStream

ReqIn = TypeVar("ReqIn")
ReqOut = TypeVar("ReqOut")
RespIn = TypeVar("RespIn")
RespOut = TypeVar("RespOut")


class Operator(ABC, Generic[ReqIn, ReqOut, RespIn, RespOut]):
    """A bidirectional pipeline stage.

    ``generate`` receives the inbound request and the downstream engine; it
    transforms the request, calls ``next``, and transforms the returned stream.
    Equivalent of the reference's ``PipelineOperator`` with
    ``forward_edge()``/``backward_edge()`` (pipeline/nodes.rs:122-210).
    """

    @abstractmethod
    async def generate(
        self,
        request: Context[ReqIn],
        next: AsyncEngine[ReqOut, RespIn],
    ) -> ResponseStream[RespOut]:
        ...

    def chain(self, next: AsyncEngine[ReqOut, RespIn]) -> AsyncEngine[ReqIn, RespOut]:
        """Bind this operator in front of an engine, yielding a new engine."""
        op = self

        class _Chained(AsyncEngine):
            async def generate(self, request: Context) -> ResponseStream:
                return await op.generate(request, next)

        return _Chained()


class MapOperator(Operator[ReqIn, ReqOut, RespIn, RespOut]):
    """Operator from two pure functions: request map + response-item map."""

    def __init__(self, fwd, bwd):
        self._fwd = fwd
        self._bwd = bwd

    async def generate(self, request, next):
        stream = await next.generate(request.map(self._fwd))
        bwd = self._bwd
        return stream.map(bwd) if bwd is not None else stream


def build_pipeline(
    operators: Sequence[Operator],
    engine: AsyncEngine,
) -> AsyncEngine:
    """Compose ``operators`` (outermost first) in front of ``engine``.

    ``build_pipeline([preprocessor, backend], tpu_engine)`` is the reference's
    ``frontend.link(preprocessor.forward_edge()).link(backend.forward_edge())
    .link(ServiceBackend::from_engine(engine)).link(backend.backward_edge())
    .link(preprocessor.backward_edge()).link(frontend)``
    (launch/dynamo-run/src/input/http.rs:92-111) — collapsed: composition
    nests the backward edges automatically.
    """
    composed = engine
    for op in reversed(list(operators)):
        composed = op.chain(composed)
    return composed


class ServiceBackend:
    """Namespace-compatible alias: the sink of a pipeline is just the engine."""

    @staticmethod
    def from_engine(engine: AsyncEngine) -> AsyncEngine:
        return engine
