"""Transports: frame codec, control-plane hub, TCP service plane.

Reference equivalents: lib/runtime/src/transports/{etcd,nats,zmq}.rs and
lib/runtime/src/pipeline/network/**.  This build collapses etcd+NATS into a
single self-contained hub process (discovery KV w/ leases + pub/sub + queues)
and replaces the NATS-request/TCP-callback split with direct TCP
request+streamed-response on one connection.
"""

from .codec import Frame, FrameType, read_frame, write_frame
from .hub import HubClient, HubServer, InprocHub, WatchEvent

__all__ = [
    "Frame",
    "FrameType",
    "read_frame",
    "write_frame",
    "HubClient",
    "HubServer",
    "InprocHub",
    "WatchEvent",
]
