"""Peer-to-peer bulk data plane: direct worker↔worker transfers with the
hub doing rendezvous only.

Reference semantics: the reference Dynamo moves KV payloads over a
dedicated NIXL (UCX/RDMA) side channel while etcd/NATS carry only control
traffic.  Here every worker runs a lightweight ``BulkServer`` stream
server, registers its bulk address in the hub under ``bulk/addr/<worker>``
(``bulk_addr_key``), and a transfer proceeds as:

1. **Rendezvous** (hub, control-plane sized): the initiator looks up the
   peer's bulk address and mints a **one-shot transfer ticket** —
   ``{id, peer, lease, salt, budget, expires}`` — written to
   ``bulk/ticket/<id>`` under the initiator's lease so abandoned tickets
   die with it.
2. **Transfer** (direct TCP, hub not involved): the initiator dials the
   peer's ``BulkServer`` and fetches from a named *source* or pushes to a
   named *sink*.  The server spends the ticket exactly once (hub
   ``kv_delete`` is the fleet-wide arbiter; local used-set when the hub is
   unreachable), enforces the salt scope and the byte budget, then streams
   the payload chunked over the ``transports/codec.py`` framing.

Wire format (all frames are codec frames, ``[type][stream][len][payload]``):

    client → server   REQ_HEADER   {op, source, ticket, resume_from,
                                    size?, chunks?, salt?, meta?}
    client → server   REQ_DATA     {i, crc, data} ...        (push only)
    client → server   REQ_DATA     {done: true}              (push only)
    server → client   RESP_PROLOGUE {ok, size?, chunks?, have?, chunk_bytes,
                                     error?, kind?}
    server → client   RESP_ITEM    {i, crc, data} ...        (fetch only)
    server → client   RESP_ITEM    {reply}                   (push only)
    server → client   RESP_COMPLETE {crc, size}
    server → client   RESP_ERROR   {error, kind}

Every chunk carries a CRC-32 stamp (``engine/integrity.bytes_checksum``)
verified on receipt, and RESP_COMPLETE carries the whole-blob CRC, so a
damaged chunk is detected before anything is decoded.  The server caches
the produced chunk list per live transfer, so a transfer is **resumable
from the last verified chunk** after a connection drop: the client
reconnects with the same ticket and ``resume_from`` (fetch) or reads the
server's ``have`` watermark (push), and the resumed stream is
byte-identical to an undropped one.

Fallback ladder (each consumer wraps its bulk call in this order):

    bulk plane  →  hub path (today's transport, the A/B oracle)
                →  local recompute (KV only; engine integrity plane)

so a dead peer, an expired ticket, or a hub rendezvous outage never drops
a stream — it costs one ``dynamo_tpu_bulk_fallbacks_total`` tick and the
bytes ride the control plane as before.  The whole plane sits behind
``DYN_BULK_PLANE`` (default off).

Fault points (chaos ladder L9, ``tools/fault_matrix.py``):
``bulk_conn_drop`` aborts the peer connection between chunks (keyed
``<address>/<source>``; the live transfer survives for resume), and
``bulk_slow_peer`` stalls ``delay_s`` per chunk.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import uuid
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ...engine.integrity import bytes_checksum
from ..faultinject import faults
from . import codec
from .codec import FrameType
from .shard import hub_key, hub_prefix

logger = logging.getLogger(__name__)

ENV_FLAG = "DYN_BULK_PLANE"
#: Default chunk size for bulk framing.  256 KiB keeps per-chunk CRC cost
#: negligible while a resume after a drop loses at most one chunk.
DEFAULT_CHUNK_BYTES = 1 << 18
TICKET_TTL_S = 30.0
#: Payloads at or above this ride the bulk plane; dynalint DYN402 flags
#: producers of bulk-sized payloads published through hub subjects.
BULK_THRESHOLD_BYTES = 64 * 1024


def bulk_enabled() -> bool:
    """True when ``DYN_BULK_PLANE`` opts this process into the bulk plane."""
    return os.environ.get(ENV_FLAG, "0").lower() not in ("", "0", "false", "no", "off")


def _chunk_bytes() -> int:
    try:
        return max(1, int(os.environ.get("DYN_BULK_CHUNK_BYTES", DEFAULT_CHUNK_BYTES)))
    except ValueError:
        return DEFAULT_CHUNK_BYTES


def _metrics():
    # Lazy: llm.metrics imports numpy-adjacent modules; the transport layer
    # must stay importable on its own.
    from ...llm.metrics import bulk_metrics

    return bulk_metrics


# --------------------------------------------------------------------------
# Hub keys (canonical builders — dynalint DYN401 sanctioned tails)
# --------------------------------------------------------------------------


def bulk_addr_key(worker_id: Any) -> str:
    """Hub key a worker registers its bulk-server address under."""
    return hub_key("bulk", "addr", str(worker_id))


def bulk_ticket_key(ticket_id: str) -> str:
    """Hub key a one-shot transfer ticket is parked under until spent."""
    return hub_key("bulk", "ticket", str(ticket_id))


def bulk_sink_key(kind: str, worker_id: Any) -> str:
    """Hub key a named bulk *sink* (e.g. the span aggregator's ``traces``
    ingest) registers its address under."""
    return hub_key("bulk", "sink", str(kind), str(worker_id))


def bulk_sink_prefix(kind: str) -> str:
    """Prefix listing every registered bulk sink of ``kind``."""
    return hub_prefix("bulk", "sink", str(kind))


# --------------------------------------------------------------------------
# Errors / tickets
# --------------------------------------------------------------------------


class TicketError(RuntimeError):
    """The server refused the ticket (expired, reused, wrong peer/salt)."""


class BulkTransferError(RuntimeError):
    """A bulk transfer failed.

    ``retryable`` distinguishes exhaustion of the resume budget (the
    caller's fallback ladder applies) from a hard protocol refusal
    (``kind`` in ``ticket|unavailable|budget|size|sink|crc``) where
    retrying the same ticket cannot succeed.
    """

    def __init__(self, msg: str, *, retryable: bool = False, kind: str = ""):
        super().__init__(msg)
        self.retryable = retryable
        self.kind = kind


class _ChunkDamage(Exception):
    """Internal: a chunk failed its CRC or arrived out of order; the
    transfer resumes from the last verified chunk."""

    def __init__(self, index: int):
        super().__init__(f"chunk {index} damaged or out of order")
        self.index = index


def mint_ticket(
    peer: Any,
    *,
    salt: Optional[str] = None,
    budget: int = 0,
    ttl_s: float = TICKET_TTL_S,
    clock: Callable[[], float] = time.time,
) -> Dict[str, Any]:
    """A one-shot transfer ticket: spendable once, by ``peer``, within
    ``ttl_s``, for at most ``budget`` bytes (0 = unbounded), scoped to
    ``salt`` so a ticket minted for one tenant's KV chain cannot fetch
    another's."""
    return {
        "id": uuid.uuid4().hex,
        "peer": str(peer),
        "lease": None,
        "salt": salt or "",
        "budget": int(budget),
        "expires": clock() + ttl_s,
    }


# --------------------------------------------------------------------------
# Server
# --------------------------------------------------------------------------

SourceFn = Callable[[Dict[str, Any]], Awaitable[bytes]]
SinkFn = Callable[[bytes, Dict[str, Any]], Awaitable[Any]]


class BulkServer:
    """One per worker: serves registered bulk *sources* (peer fetches from
    us) and *sinks* (peer pushes to us) over direct TCP.

    The hub appears only in ``_admit``'s one-shot ticket spend — and even
    there a hub outage degrades to the local used-set instead of failing
    the transfer, so the data path has no hard control-plane dependency.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        *,
        worker_id: Optional[Any] = None,
        hub: Optional[Any] = None,
        chunk_bytes: Optional[int] = None,
        clock: Callable[[], float] = time.time,
        live_ttl_s: float = 30.0,
    ):
        self.host = host
        self.worker_id = worker_id
        self.hub = hub
        self.chunk_bytes = int(chunk_bytes or _chunk_bytes())
        self.clock = clock
        self.live_ttl_s = live_ttl_s
        self._sources: Dict[str, SourceFn] = {}
        self._sinks: Dict[str, SinkFn] = {}
        self._used: Dict[str, float] = {}  # ticket id → expiry (reuse guard)
        self._live: Dict[str, Dict[str, Any]] = {}  # ticket id → transfer state
        self._server: Optional[asyncio.AbstractServer] = None
        self._port = 0
        self._conn_tasks: set = set()

    # -- registration --------------------------------------------------------

    def register_source(self, name: str, fn: SourceFn) -> None:
        self._sources[name] = fn

    def register_sink(self, name: str, fn: SinkFn) -> None:
        self._sinks[name] = fn

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "BulkServer":
        self._server = await asyncio.start_server(self._accept, self.host, 0)
        self._port = self._server.sockets[0].getsockname()[1]
        logger.info("bulk server listening on %s", self.address)
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self._port}"

    async def close(self) -> None:
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._conn_tasks.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.ensure_future(self._handle(reader, writer))
        self._conn_tasks.add(task)
        task.add_done_callback(self._conn_tasks.discard)

    # -- connection handling -------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            frame = await codec.read_frame(reader)
            if frame.type != FrameType.REQ_HEADER:
                return
            hdr = frame.unpack()
            try:
                live = await self._admit(hdr)
            except TicketError as exc:
                await codec.write_frame(
                    writer,
                    FrameType.RESP_PROLOGUE,
                    {"ok": False, "error": str(exc), "kind": "ticket"},
                )
                return
            op = hdr.get("op")
            if op == "fetch":
                await self._serve_fetch(hdr, live, writer)
            elif op == "push":
                await self._serve_push(hdr, live, reader, writer)
            else:
                self._live.pop(live["id"], None)
                await codec.write_frame(
                    writer,
                    FrameType.RESP_PROLOGUE,
                    {"ok": False, "error": f"unknown op {op!r}", "kind": "unavailable"},
                )
        except asyncio.CancelledError:
            raise
        except (ConnectionError, asyncio.IncompleteReadError, OSError, ValueError):
            pass  # peer vanished / garbage frame: nothing to answer to
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except asyncio.CancelledError:
                raise
            except (ConnectionError, OSError):
                pass

    def _expire(self) -> None:
        now = self.clock()
        for tid in [t for t, exp in self._used.items() if exp < now]:
            self._used.pop(tid, None)
        for tid in [t for t, st in self._live.items() if st["deadline"] < now]:
            self._live.pop(tid, None)

    async def _admit(self, hdr: Dict[str, Any]) -> Dict[str, Any]:
        """Validate + spend the ticket; returns the live transfer state.

        A reconnect for an in-flight transfer (same ticket id still live)
        is a **resume**, never a reuse — the ticket was spent when the
        transfer was admitted, and the cached state guarantees the resumed
        stream is byte-identical.
        """
        self._expire()
        ticket = hdr.get("ticket")
        if not isinstance(ticket, dict) or not ticket.get("id"):
            raise TicketError("missing transfer ticket")
        tid = str(ticket["id"])
        live = self._live.get(tid)
        if live is not None:
            live["deadline"] = self.clock() + self.live_ttl_s
            return live
        if int(hdr.get("resume_from") or 0) > 0:
            raise TicketError("resume for unknown transfer")
        if tid in self._used:
            raise TicketError("ticket already spent")
        if (
            self.worker_id is not None
            and str(ticket.get("peer") or "") != str(self.worker_id)
        ):
            raise TicketError("ticket minted for a different peer")
        expires = float(ticket.get("expires") or 0.0)
        if expires < self.clock():
            raise TicketError("ticket expired")
        if (ticket.get("salt") or "") != (hdr.get("salt") or ""):
            raise TicketError("ticket salt scope mismatch")
        if self.hub is not None:
            # The hub record is the fleet-wide one-shot arbiter: the first
            # delete wins; a second spend (replayed ticket) finds nothing.
            try:
                fresh = await self.hub.kv_delete(bulk_ticket_key(tid))
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.warning(
                    "bulk: hub unreachable for ticket %s; degrading to the "
                    "local reuse guard",
                    tid,
                )
                fresh = True
            if not fresh:
                raise TicketError("ticket already spent (hub)")
        self._used[tid] = max(expires, self.clock() + self.live_ttl_s)
        live = {
            "id": tid,
            "budget": int(ticket.get("budget") or 0),
            "deadline": self.clock() + self.live_ttl_s,
            "chunks": [],
            "nbytes": 0,
        }
        self._live[tid] = live
        return live

    # -- fetch (peer pulls from our source) ----------------------------------

    async def _serve_fetch(
        self,
        hdr: Dict[str, Any],
        live: Dict[str, Any],
        writer: asyncio.StreamWriter,
    ) -> None:
        name = str(hdr.get("source") or "")
        key = f"{self.address}/{name}"
        fn = self._sources.get(name)
        if fn is None:
            self._live.pop(live["id"], None)
            await codec.write_frame(
                writer,
                FrameType.RESP_PROLOGUE,
                {"ok": False, "error": f"no bulk source {name!r}", "kind": "unavailable"},
            )
            return
        if "blob_crc" not in live:
            # Produce once per ticket and cache the chunk list: a resumed
            # transfer re-serves the SAME bytes (byte-identity across drops).
            try:
                blob = await fn(hdr.get("meta") or {})
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self._live.pop(live["id"], None)
                await codec.write_frame(
                    writer,
                    FrameType.RESP_PROLOGUE,
                    {"ok": False, "error": f"source failed: {exc}", "kind": "unavailable"},
                )
                return
            if live["budget"] and len(blob) > live["budget"]:
                self._live.pop(live["id"], None)
                await codec.write_frame(
                    writer,
                    FrameType.RESP_PROLOGUE,
                    {
                        "ok": False,
                        "error": f"{len(blob)}B exceeds ticket budget {live['budget']}B",
                        "kind": "budget",
                    },
                )
                return
            cb = self.chunk_bytes
            live["chunks"] = [blob[o : o + cb] for o in range(0, len(blob), cb)]
            live["blob_crc"] = bytes_checksum(blob)
            live["size"] = len(blob)
        resume_from = int(hdr.get("resume_from") or 0)
        chunks: List[bytes] = live["chunks"]
        await codec.write_frame(
            writer,
            FrameType.RESP_PROLOGUE,
            {
                "ok": True,
                "size": live["size"],
                "chunks": len(chunks),
                "chunk_bytes": self.chunk_bytes,
            },
        )
        for i in range(resume_from, len(chunks)):
            if faults.enabled:
                delay = faults.delay_for("bulk_slow_peer", key)
                if delay:
                    await asyncio.sleep(delay)
            chunk = chunks[i]
            await codec.write_frame(
                writer,
                FrameType.RESP_ITEM,
                {"i": i, "crc": bytes_checksum(chunk), "data": chunk},
            )
            if faults.enabled and faults.should("bulk_conn_drop", key):
                # Abort (no FIN) AFTER a verified chunk shipped — the
                # drop_mid_stream shape: the client holds partial state and
                # resumes.  Live state survives — that is the point.
                writer.transport.abort()
                return
        await codec.write_frame(
            writer,
            FrameType.RESP_COMPLETE,
            {"crc": live["blob_crc"], "size": live["size"]},
        )
        self._live.pop(live["id"], None)

    # -- push (peer pushes into our sink) ------------------------------------

    async def _serve_push(
        self,
        hdr: Dict[str, Any],
        live: Dict[str, Any],
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        name = str(hdr.get("source") or "")
        key = f"{self.address}/{name}"
        fn = self._sinks.get(name)
        if fn is None:
            self._live.pop(live["id"], None)
            await codec.write_frame(
                writer,
                FrameType.RESP_PROLOGUE,
                {"ok": False, "error": f"no bulk sink {name!r}", "kind": "unavailable"},
            )
            return
        size = int(hdr.get("size") or 0)
        if live["budget"] and size > live["budget"]:
            self._live.pop(live["id"], None)
            await codec.write_frame(
                writer,
                FrameType.RESP_PROLOGUE,
                {
                    "ok": False,
                    "error": f"declared {size}B exceeds ticket budget {live['budget']}B",
                    "kind": "budget",
                },
            )
            return
        chunks: List[bytes] = live["chunks"]
        await codec.write_frame(
            writer,
            FrameType.RESP_PROLOGUE,
            {"ok": True, "have": len(chunks), "chunk_bytes": self.chunk_bytes},
        )
        while True:
            if faults.enabled:
                delay = faults.delay_for("bulk_slow_peer", key)
                if delay:
                    await asyncio.sleep(delay)
            frame = await codec.read_frame(reader)
            if frame.type != FrameType.REQ_DATA:
                return
            item = frame.unpack()
            if item.get("done"):
                break
            if int(item.get("i", -1)) != len(chunks):
                await codec.write_frame(
                    writer,
                    FrameType.RESP_ERROR,
                    {"error": "chunk out of order", "kind": "order"},
                )
                return  # live survives; client restarts from `have`
            data = item.get("data") or b""
            if bytes_checksum(data) != item.get("crc"):
                await codec.write_frame(
                    writer,
                    FrameType.RESP_ERROR,
                    {"error": "chunk CRC mismatch", "kind": "crc"},
                )
                return  # live survives; the damaged chunk is re-sent
            live["nbytes"] += len(data)
            if live["budget"] and live["nbytes"] > live["budget"]:
                self._live.pop(live["id"], None)
                await codec.write_frame(
                    writer,
                    FrameType.RESP_ERROR,
                    {"error": "ticket byte budget exceeded", "kind": "budget"},
                )
                return
            chunks.append(data)
            if faults.enabled and faults.should("bulk_conn_drop", key):
                # Abort AFTER the chunk verified and landed: the reconnect's
                # prologue reports ``have`` past it, so the client resumes
                # from the server's verified frontier.
                writer.transport.abort()
                return
        blob = b"".join(chunks)
        if len(blob) != size:
            self._live.pop(live["id"], None)
            await codec.write_frame(
                writer,
                FrameType.RESP_ERROR,
                {
                    "error": f"assembled {len(blob)}B != declared {size}B",
                    "kind": "size",
                },
            )
            return
        try:
            reply = await fn(blob, hdr.get("meta") or {})
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            self._live.pop(live["id"], None)
            await codec.write_frame(
                writer,
                FrameType.RESP_ERROR,
                {"error": f"sink failed: {exc}", "kind": "sink"},
            )
            return
        await codec.write_frame(writer, FrameType.RESP_ITEM, {"reply": reply})
        await codec.write_frame(
            writer,
            FrameType.RESP_COMPLETE,
            {"crc": bytes_checksum(blob), "size": len(blob)},
        )
        self._live.pop(live["id"], None)


# --------------------------------------------------------------------------
# Client
# --------------------------------------------------------------------------


async def _open(address: str) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    host, _, port = address.rpartition(":")
    return await asyncio.open_connection(host, int(port))


async def _close(writer: asyncio.StreamWriter) -> None:
    writer.close()
    try:
        await writer.wait_closed()
    except asyncio.CancelledError:
        raise
    except (ConnectionError, OSError):
        pass


_RESUMABLE = (ConnectionError, EOFError, OSError, asyncio.TimeoutError, _ChunkDamage)


async def bulk_fetch(
    address: str,
    source: str,
    ticket: Dict[str, Any],
    meta: Optional[Dict[str, Any]] = None,
    *,
    salt: Optional[str] = None,
    timeout_s: float = 30.0,
    max_resumes: int = 3,
) -> bytes:
    """Fetch a blob from ``source`` on the peer at ``address``.

    Verified chunks accumulate across attempts: a connection drop resumes
    from ``len(received)`` instead of restarting, and the server's cached
    chunk list guarantees the resumed bytes match.  Raises
    ``BulkTransferError`` (``retryable=True`` once the resume budget is
    exhausted; ``retryable=False`` on a protocol refusal)."""
    received: List[bytes] = []
    attempt = 0
    while True:
        try:
            return await asyncio.wait_for(
                _fetch_once(address, source, ticket, received, meta=meta, salt=salt),
                timeout_s,
            )
        except asyncio.CancelledError:
            raise
        except BulkTransferError:
            raise
        except _RESUMABLE as exc:
            attempt += 1
            if attempt > max_resumes:
                raise BulkTransferError(
                    f"bulk fetch {source!r} from {address} failed after "
                    f"{attempt} attempts: {exc!r}",
                    retryable=True,
                ) from exc
            await asyncio.sleep(0.01 * attempt)


async def _fetch_once(
    address: str,
    source: str,
    ticket: Dict[str, Any],
    received: List[bytes],
    *,
    meta: Optional[Dict[str, Any]],
    salt: Optional[str],
) -> bytes:
    if received:
        _metrics().resumes_total += 1
    reader, writer = await _open(address)
    try:
        hdr: Dict[str, Any] = {
            "op": "fetch",
            "source": source,
            "ticket": ticket,
            "resume_from": len(received),
        }
        if meta is not None:
            hdr["meta"] = meta
        if salt:
            hdr["salt"] = salt
        await codec.write_frame(writer, FrameType.REQ_HEADER, hdr)
        frame = await codec.read_frame(reader)
        pro = frame.unpack()
        if frame.type != FrameType.RESP_PROLOGUE or not pro.get("ok"):
            raise BulkTransferError(
                f"bulk fetch refused by {address}: {pro.get('error')}",
                kind=str(pro.get("kind") or ""),
            )
        total = int(pro.get("chunks") or 0)
        while len(received) < total:
            frame = await codec.read_frame(reader)
            if frame.type == FrameType.RESP_ERROR:
                err = frame.unpack()
                raise BulkTransferError(
                    f"bulk fetch error from {address}: {err.get('error')}",
                    kind=str(err.get("kind") or ""),
                )
            if frame.type != FrameType.RESP_ITEM:
                raise _ChunkDamage(len(received))
            item = frame.unpack()
            if int(item.get("i", -1)) != len(received):
                raise _ChunkDamage(len(received))
            data = item.get("data") or b""
            if bytes_checksum(data) != item.get("crc"):
                raise _ChunkDamage(len(received))
            received.append(data)
        frame = await codec.read_frame(reader)
        done = frame.unpack()
        blob = b"".join(received)
        if (
            frame.type != FrameType.RESP_COMPLETE
            or bytes_checksum(blob) != done.get("crc")
            or len(blob) != done.get("size")
        ):
            raise BulkTransferError(
                f"bulk fetch from {address}: whole-stream verification failed",
                kind="crc",
            )
        m = _metrics()
        m.transfers_total += 1
        m.bytes_total += len(blob)
        return blob
    finally:
        await _close(writer)


async def bulk_push(
    address: str,
    sink: str,
    ticket: Dict[str, Any],
    blob: bytes,
    meta: Optional[Dict[str, Any]] = None,
    *,
    salt: Optional[str] = None,
    timeout_s: float = 30.0,
    max_resumes: int = 3,
    chunk_bytes: Optional[int] = None,
) -> Any:
    """Push ``blob`` into ``sink`` on the peer at ``address``; returns the
    sink's reply.  Resume is server-anchored: after a drop the reconnect's
    prologue reports how many chunks the server verified (``have``) and
    the client continues from there."""
    cb = int(chunk_bytes or _chunk_bytes())
    chunks = [blob[o : o + cb] for o in range(0, len(blob), cb)]
    attempt = 0
    while True:
        try:
            return await asyncio.wait_for(
                _push_once(address, sink, ticket, blob, chunks, meta=meta, salt=salt),
                timeout_s,
            )
        except asyncio.CancelledError:
            raise
        except BulkTransferError:
            raise
        except _RESUMABLE as exc:
            attempt += 1
            if attempt > max_resumes:
                raise BulkTransferError(
                    f"bulk push {sink!r} to {address} failed after "
                    f"{attempt} attempts: {exc!r}",
                    retryable=True,
                ) from exc
            await asyncio.sleep(0.01 * attempt)


async def _push_once(
    address: str,
    sink: str,
    ticket: Dict[str, Any],
    blob: bytes,
    chunks: List[bytes],
    *,
    meta: Optional[Dict[str, Any]],
    salt: Optional[str],
) -> Any:
    reader, writer = await _open(address)
    try:
        hdr: Dict[str, Any] = {
            "op": "push",
            "source": sink,
            "ticket": ticket,
            "resume_from": 0,
            "size": len(blob),
            "chunks": len(chunks),
        }
        if meta is not None:
            hdr["meta"] = meta
        if salt:
            hdr["salt"] = salt
        await codec.write_frame(writer, FrameType.REQ_HEADER, hdr)
        frame = await codec.read_frame(reader)
        pro = frame.unpack()
        if frame.type != FrameType.RESP_PROLOGUE or not pro.get("ok"):
            raise BulkTransferError(
                f"bulk push refused by {address}: {pro.get('error')}",
                kind=str(pro.get("kind") or ""),
            )
        have = int(pro.get("have") or 0)
        if have:
            _metrics().resumes_total += 1
        for i in range(have, len(chunks)):
            chunk = chunks[i]
            await codec.write_frame(
                writer,
                FrameType.REQ_DATA,
                {"i": i, "crc": bytes_checksum(chunk), "data": chunk},
            )
        await codec.write_frame(writer, FrameType.REQ_DATA, {"done": True})
        reply: Any = None
        while True:
            frame = await codec.read_frame(reader)
            if frame.type == FrameType.RESP_ERROR:
                err = frame.unpack()
                if err.get("kind") in ("crc", "order"):
                    raise _ChunkDamage(-1)
                raise BulkTransferError(
                    f"bulk push error from {address}: {err.get('error')}",
                    kind=str(err.get("kind") or ""),
                )
            if frame.type == FrameType.RESP_ITEM:
                reply = frame.unpack().get("reply")
            elif frame.type == FrameType.RESP_COMPLETE:
                break
        m = _metrics()
        m.transfers_total += 1
        m.bytes_total += len(blob)
        return reply
    finally:
        await _close(writer)


# --------------------------------------------------------------------------
# Rendezvous (the hub's only role in a transfer)
# --------------------------------------------------------------------------


class BulkRendezvous:
    """Address lookup + ticket minting against the hub.

    Every method degrades instead of raising on hub trouble (``lookup``
    serves its TTL cache stale; ``prepare*`` returns ``None``) — the
    caller's fallback ladder, not an exception, handles a rendezvous
    outage."""

    def __init__(
        self,
        hub: Any,
        *,
        lease: Optional[int] = None,
        ttl_s: float = TICKET_TTL_S,
        clock: Callable[[], float] = time.time,
        cache_ttl_s: float = 5.0,
    ):
        self.hub = hub
        self.lease = lease
        self.ttl_s = ttl_s
        self.clock = clock
        self.cache_ttl_s = cache_ttl_s
        self._cache: Dict[str, Tuple[float, Dict[str, Any]]] = {}

    async def lookup(self, worker_id: Any) -> Optional[str]:
        """The peer's bulk address, or None when it runs no bulk server."""
        wid = str(worker_id)
        now = self.clock()
        hit = self._cache.get(wid)
        if hit is not None and hit[0] > now:
            return hit[1].get("address")
        try:
            rec = await self.hub.kv_get(bulk_addr_key(wid))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("bulk rendezvous: hub lookup failed for %s", wid)
            return hit[1].get("address") if hit is not None else None
        if not isinstance(rec, dict) or not rec.get("address"):
            self._cache.pop(wid, None)
            return None
        self._cache[wid] = (now + self.cache_ttl_s, rec)
        return rec["address"]

    async def _park(self, ticket: Dict[str, Any]) -> bool:
        ticket["lease"] = self.lease
        try:
            await self.hub.kv_put(bulk_ticket_key(ticket["id"]), ticket, self.lease)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("bulk rendezvous: ticket park failed")
            return False
        return True

    async def prepare(
        self, worker_id: Any, *, salt: Optional[str] = None, budget: int = 0
    ) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Rendezvous for a transfer with ``worker_id``: (address, ticket),
        or None when the peer is unreachable / the hub is down."""
        address = await self.lookup(worker_id)
        if not address:
            return None
        ticket = mint_ticket(
            worker_id, salt=salt, budget=budget, ttl_s=self.ttl_s, clock=self.clock
        )
        if not await self._park(ticket):
            return None
        return address, ticket

    async def prepare_sink(
        self, kind: str, *, salt: Optional[str] = None, budget: int = 0
    ) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Rendezvous with any registered sink of ``kind`` (e.g. the span
        aggregator's ``traces`` ingest): (address, ticket) or None."""
        try:
            recs = await self.hub.kv_get_prefix(bulk_sink_prefix(kind))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("bulk rendezvous: sink scan failed for %s", kind)
            return None
        for rec in sorted((recs or {}).items()):
            rec = rec[1]
            if not isinstance(rec, dict) or not rec.get("address"):
                continue
            ticket = mint_ticket(
                rec.get("worker_id") or "",
                salt=salt,
                budget=budget,
                ttl_s=self.ttl_s,
                clock=self.clock,
            )
            if not await self._park(ticket):
                return None
            return rec["address"], ticket
        return None
