"""Frame codec for the TCP service plane.

Reference semantics: lib/runtime/src/pipeline/network/codec/two_part.rs —
length-prefixed two-part (header + data) framing.  Here every frame is

    [1 byte type][4 bytes big-endian payload length][payload]

and a request is two frames (REQ_HEADER carrying the control message,
REQ_DATA carrying the serialized request), mirroring ``TwoPartMessage``.
Responses stream as RESP_* frames on the same connection; CANCEL/KILL flow
client→server mid-stream (the reference's ZMQ "Harmony" control messages,
transports/zmq.rs:44-52).

Every frame carries a u32 STREAM id, so one connection multiplexes many
concurrent requests (the reference multiplexes via NATS subjects + response
stream registration; a connection per request measured as pure churn at
high concurrency).  Stream 0 is connection control (heartbeats).

    [1 byte type][4 bytes stream id][4 bytes payload length][payload]

Payload encoding is msgpack (falls back to JSON if a payload is not
msgpack-serializable).
"""

from __future__ import annotations

import asyncio
import enum
import json
import struct
from dataclasses import dataclass
from typing import Any

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # 256 MiB guard against corrupt length prefixes
_HDR = struct.Struct(">BII")


class FrameType(enum.IntEnum):
    REQ_HEADER = 1  # control message: {id, endpoint, request_type}
    REQ_DATA = 2  # request payload
    RESP_PROLOGUE = 3  # {ok: bool, error: str|None} — reference's ResponseStreamPrologue
    RESP_ITEM = 4  # one streamed response item
    RESP_COMPLETE = 5  # end of stream
    RESP_ERROR = 6  # mid-stream error (terminates stream)
    CANCEL = 7  # client → server: stop_generating()
    KILL = 8  # client → server: kill()
    HEARTBEAT = 9


@dataclass(frozen=True)
class Frame:
    type: FrameType
    payload: bytes
    stream: int = 0  # multiplexing stream id (0 = connection control)

    def unpack(self) -> Any:
        return decode(self.payload)


def encode(obj: Any) -> bytes:
    try:
        return msgpack.packb(obj, use_bin_type=True)
    except (TypeError, ValueError):
        return b"\x00json" + json.dumps(obj).encode()


def decode(buf: bytes) -> Any:
    if buf[:5] == b"\x00json":
        return json.loads(buf[5:])
    return msgpack.unpackb(buf, raw=False)


async def write_frame(
    writer: asyncio.StreamWriter,
    ftype: FrameType,
    obj: Any = None,
    *,
    stream: int = 0,
    raw: bytes | None = None,
) -> None:
    payload = raw if raw is not None else encode(obj)
    writer.write(_HDR.pack(int(ftype), stream, len(payload)) + payload)
    await writer.drain()


async def read_frame(reader: asyncio.StreamReader) -> Frame:
    hdr = await reader.readexactly(_HDR.size)
    ftype, stream, length = _HDR.unpack(hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds MAX_FRAME")
    payload = await reader.readexactly(length) if length else b""
    return Frame(FrameType(ftype), payload, stream)
