"""Hub sharding: static shard map, canonical key builders, sharded client.

The single hub process (transports/hub.py) stands in for the reference's
etcd + NATS layer and was the fleet's control-plane SPOF and scaling
ceiling.  This module splits that plane across N independent hub shards
behind a small **static shard map**:

- ``ShardMap``        — parses a ``host:port[,host:port...]`` spec and maps
  every key/prefix/subject/queue to its owner shard by a stable hash of
  the **routing token** (the first ``/``-segment of a key, the first
  ``.``-token of a subject).  Routing by the leading segment keeps every
  watch prefix in the tree wholly on one shard — a prefix watch never has
  to merge deltas across shards.
- ``hub_key`` / ``hub_prefix`` / ``hub_subject`` — the canonical builders
  every hub key/subject construction in ``dynamo_tpu`` routes through
  (enforced by dynalint DYN401): ad-hoc f-strings at hub call sites
  bypass the routing contract and become findings.
- ``ShardedHubClient`` — same async interface as ``HubClient``/
  ``InprocHub``; owns one ``HubClient`` per shard so PR 7's park/replay +
  session-resume semantics hold **per shard**: one shard's outage parks
  only the traffic it owns, and never stalls keys owned by its siblings.
  Leases are composite (granted on every shard; ``kv_put`` translates to
  the owner shard's lease id) so a single primary lease keeps liveness
  semantics across the whole map.
- ``HubShardMetrics`` — per-shard connect/reconnect/failover/park/replay
  counters plus the routed client's degraded-mode cache hits/staleness,
  rendered onto the edge ``/metrics`` next to the resilience block.

A one-address spec degrades to a single shard that accepts every key —
wire- and byte-compatible with today's hub (``DistributedRuntime.connect``
keeps handing out a plain ``HubClient`` for single addresses).
"""

from __future__ import annotations

import itertools
import logging
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ...labels import escape_label
from .hub import HubClient, Subscription, Watcher

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# Canonical key/subject builders (dynalint DYN401 sanctioned tails)
# --------------------------------------------------------------------------


def hub_key(*segments: Any) -> str:
    """Join path segments into a hub KV key (``a/b/c``).

    The first segment is the **routing token**: every key built from the
    same leading segment lands on the same shard, so code that needs two
    keys co-located must give them the same leading segment.
    """
    parts = [str(s) for s in segments]
    if not parts or not parts[0]:
        raise ValueError("hub_key needs a non-empty leading segment")
    return "/".join(parts)


def hub_prefix(*segments: Any) -> str:
    """A watchable/queryable prefix: ``hub_key(...) + "/"``.

    Always ends in ``/`` so the leading routing token is complete — a
    prefix like ``"inst"`` would match keys with different routing tokens
    and cannot be owned by one shard.
    """
    return hub_key(*segments) + "/"


def hub_subject(*tokens: Any) -> str:
    """Join tokens into a pub/sub subject (``ns.topic``); the first token
    routes the subject to its shard."""
    parts = [str(t) for t in tokens]
    if not parts or not parts[0]:
        raise ValueError("hub_subject needs a non-empty leading token")
    return ".".join(parts)


def route_token(key: str) -> str:
    """The shard-routing token of a KV key / queue name: the first
    ``/``-segment."""
    if not key:
        raise ValueError("cannot route an empty hub key")
    return key.split("/", 1)[0]


def prefix_route_token(prefix: str) -> Optional[str]:
    """Routing token of a prefix, or None when the prefix does not pin one
    (no ``/`` yet — it could match keys with different leading segments)."""
    if "/" in prefix:
        return prefix.split("/", 1)[0]
    return None


def subject_route_token(pattern: str) -> Optional[str]:
    """Routing token of a subject/pattern, or None when the leading token
    is a wildcard (the pattern spans shards)."""
    if not pattern:
        raise ValueError("cannot route an empty subject")
    head = pattern.split(".", 1)[0]
    if head in ("*", ">"):
        return None
    return head


class CrossShardError(ValueError):
    """A prefix/pattern spans hub shards: the shard map cannot route it to
    one owner, and merging watch deltas across shards is not supported.
    Pin the leading routing token (``hub_prefix``) or run one shard."""


class ShardMap:
    """Static shard map: an ordered list of hub addresses; routing is a
    stable hash (crc32) of the routing token, so the same key routes to
    the same shard in every process with the same spec."""

    def __init__(self, addresses: List[str]):
        if not addresses:
            raise ValueError("shard map needs at least one address")
        self.addresses = list(addresses)

    @classmethod
    def parse(cls, spec: str) -> "ShardMap":
        """``host:port`` or ``host:port,host:port,...`` (order matters: it
        is part of the map identity — every client must use the same)."""
        addrs = [a.strip() for a in spec.split(",") if a.strip()]
        return cls(addrs)

    @property
    def spec(self) -> str:
        return ",".join(self.addresses)

    def __len__(self) -> int:
        return len(self.addresses)

    def shard_of_token(self, token: str) -> int:
        if len(self.addresses) == 1:
            return 0
        return zlib.crc32(token.encode()) % len(self.addresses)

    def shard_for_key(self, key: str) -> int:
        return self.shard_of_token(route_token(key))

    def shard_for_prefix(self, prefix: str) -> int:
        if len(self.addresses) == 1:
            return 0
        token = prefix_route_token(prefix)
        if token is None:
            raise CrossShardError(
                f"prefix {prefix!r} does not pin a routing token and would "
                f"span {len(self.addresses)} hub shards; use hub_prefix() "
                "to build a single-shard prefix"
            )
        return self.shard_of_token(token)

    def shard_for_subject(self, pattern: str) -> int:
        if len(self.addresses) == 1:
            return 0
        token = subject_route_token(pattern)
        if token is None:
            raise CrossShardError(
                f"subject pattern {pattern!r} starts with a wildcard and "
                f"would span {len(self.addresses)} hub shards; lead with a "
                "concrete token (hub_subject)"
            )
        return self.shard_of_token(token)


# --------------------------------------------------------------------------
# Metrics
# --------------------------------------------------------------------------


class HubShardMetrics:
    """Process-global hub-shard counters (``dynamo_tpu_hub_shard_*``).

    Per-shard series are keyed by the shard address; the routing-cache
    counters come from the routed ``Client``'s degraded-mode cache
    (runtime/client.py) — picks served from the local instance table,
    including through a shard failover window, with the staleness bound
    surfaced as a gauge.
    """

    def __init__(self):
        self.connects: Dict[str, int] = {}
        self.reconnects: Dict[str, int] = {}
        self.failovers: Dict[str, int] = {}
        self.parked: Dict[str, int] = {}
        self.replayed: Dict[str, int] = {}
        self.parked_shed: Dict[str, int] = {}
        # Control-plane publish volume per shard: the bulk plane's proof
        # metric — with DYN_BULK_PLANE on, KV pulls / migration copies /
        # span batches leave this series for dynamo_tpu_bulk_bytes_total.
        self.publishes: Dict[str, int] = {}
        self.publish_bytes: Dict[str, int] = {}
        self.routing_cache_hits_total = 0
        self.routing_cache_stale_hits_total = 0
        # owner id → monotonic stamp of when that routed client's watch
        # died; the staleness gauge is the worst live entry.
        self._stale_since: Dict[int, float] = {}

    def _bump(self, table: Dict[str, int], shard: str, n: int = 1) -> None:
        table[shard] = table.get(shard, 0) + n

    def note_connect(self, shard: str) -> None:
        self._bump(self.connects, shard)

    def note_reconnect(self, shard: str) -> None:
        self._bump(self.reconnects, shard)

    def note_failover(self, shard: str) -> None:
        self._bump(self.failovers, shard)

    def note_parked(self, shard: str) -> None:
        self._bump(self.parked, shard)

    def note_replayed(self, shard: str) -> None:
        self._bump(self.replayed, shard)

    def note_shed(self, shard: str, n: int = 1) -> None:
        self._bump(self.parked_shed, shard, n)

    def note_publish(self, shard: str, nbytes: int) -> None:
        self._bump(self.publishes, shard)
        self._bump(self.publish_bytes, shard, max(0, int(nbytes)))

    def note_cache_stale(self, owner: int, since: float) -> None:
        self._stale_since[owner] = since

    def note_cache_fresh(self, owner: int) -> None:
        self._stale_since.pop(owner, None)

    @property
    def routing_cache_staleness_s(self) -> float:
        """Worst current staleness of any routed client's instance cache
        (seconds since its watch died; 0 = every cache synced)."""
        if not self._stale_since:
            return 0.0
        now = time.monotonic()
        return max(0.0, now - min(self._stale_since.values()))

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_hub_shard"
        lines: List[str] = []

        def per_shard(name: str, help_: str, table: Dict[str, int]) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            if not table:
                lines.append(f"{ns}_{name} 0")
                return
            for shard, n in sorted(table.items()):
                lines.append(
                    f'{ns}_{name}{{shard="{escape_label(shard)}"}} {n}'
                )

        per_shard("connects_total", "Initial connects per hub shard.",
                  self.connects)
        per_shard("reconnects_total", "Reconnects per hub shard.",
                  self.reconnects)
        per_shard("failovers_total",
                  "Standby promotions observed per hub shard.",
                  self.failovers)
        per_shard("parked_requests_total",
                  "Requests parked awaiting a shard reconnect.",
                  self.parked)
        per_shard("replayed_requests_total",
                  "Idempotent requests replayed after a shard reconnect.",
                  self.replayed)
        per_shard("parked_shed_total",
                  "Parked requests shed by the park-buffer cap "
                  "(oldest-idempotent-first).",
                  self.parked_shed)
        per_shard("publishes_total",
                  "Pub/sub publishes sent through this hub shard.",
                  self.publishes)
        per_shard("publish_bytes_total",
                  "Approximate payload bytes published through this hub "
                  "shard (bulk payloads leave this series under "
                  "DYN_BULK_PLANE — docs/bulk_plane.md).",
                  self.publish_bytes)
        lines.append(f"# HELP {ns}_routing_cache_hits_total Instance picks "
                     "served from the local routing cache (never blocks on "
                     "hub RTT).")
        lines.append(f"# TYPE {ns}_routing_cache_hits_total counter")
        lines.append(f"{ns}_routing_cache_hits_total "
                     f"{self.routing_cache_hits_total}")
        lines.append(f"# HELP {ns}_routing_cache_stale_hits_total Picks "
                     "served while the cache's watch was down (degraded "
                     "mode).")
        lines.append(f"# TYPE {ns}_routing_cache_stale_hits_total counter")
        lines.append(f"{ns}_routing_cache_stale_hits_total "
                     f"{self.routing_cache_stale_hits_total}")
        lines.append(f"# HELP {ns}_routing_cache_staleness_seconds Worst "
                     "current staleness of any routed client's instance "
                     "cache (0 = synced).")
        lines.append(f"# TYPE {ns}_routing_cache_staleness_seconds gauge")
        lines.append(f"{ns}_routing_cache_staleness_seconds "
                     f"{self.routing_cache_staleness_s:.3f}")
        return "\n".join(lines) + "\n"


# One per process, like runtime.resilience.metrics.
shard_metrics = HubShardMetrics()


# --------------------------------------------------------------------------
# Sharded client
# --------------------------------------------------------------------------


class ShardedHubClient:
    """Shard-aware hub client: one ``HubClient`` per shard, routed by the
    shard map.  Same async interface as ``HubClient``/``InprocHub``.

    Each per-shard client keeps its own reconnect loop, park/replay buffer
    and session-resume machinery, so a dead shard parks only the requests
    it owns.  Composite leases: ``lease_grant`` grants one lease per shard
    and hands back a local id; key-bound puts translate to the owner
    shard's lease id, and a keepalive is only truthy when **every** shard
    still honours its half (one shard losing lease state must trigger the
    owner's re-grant + re-register path, exactly like a hub restart).
    """

    def __init__(
        self,
        spec: str,
        reconnect: bool = True,
        reconnect_max_s: float = 2.0,
        request_grace_s: float = 10.0,
    ):
        self.shard_map = ShardMap.parse(spec) if isinstance(spec, str) else spec
        self.reconnect = reconnect
        self.reconnect_max_s = reconnect_max_s
        self.request_grace_s = request_grace_s
        self.clients: List[HubClient] = []
        self._lease_ids = itertools.count(1)
        # local composite lease id → {shard index: remote lease id}
        self._leases: Dict[int, Dict[int, int]] = {}
        self._closed = False

    @property
    def address(self) -> str:
        return self.shard_map.spec

    async def connect(self) -> "ShardedHubClient":
        for addr in self.shard_map.addresses:
            client = HubClient(
                addr,
                reconnect=self.reconnect,
                reconnect_max_s=self.reconnect_max_s,
                request_grace_s=self.request_grace_s,
            )
            await client.connect()
            self.clients.append(client)
            shard_metrics.note_connect(addr)
        return self

    async def close(self) -> None:
        self._closed = True
        for client in self.clients:
            await client.close()

    # -- routing -------------------------------------------------------------

    def client_for_key(self, key: str) -> HubClient:
        return self.clients[self.shard_map.shard_for_key(key)]

    def client_for_prefix(self, prefix: str) -> HubClient:
        return self.clients[self.shard_map.shard_for_prefix(prefix)]

    def client_for_subject(self, pattern: str) -> HubClient:
        return self.clients[self.shard_map.shard_for_subject(pattern)]

    def shard_health(self) -> List[Dict[str, Any]]:
        """Per-shard connectivity snapshot for the edge ``/health``."""
        return [
            {"shard": c.address, "connected": c.connected}
            for c in self.clients
        ]

    # -- KV -------------------------------------------------------------------

    def _owner_lease(self, client_idx: int, lease_id: Optional[int]) -> Optional[int]:
        if lease_id is None:
            return None
        per_shard = self._leases.get(lease_id)
        if per_shard is None:
            # Not a composite id (e.g. a raw lease from a sibling plane):
            # pass through untranslated — single-shard maps behave exactly
            # like a bare HubClient.
            return lease_id
        remote = per_shard.get(client_idx)
        if remote is None:
            raise KeyError(
                f"composite lease {lease_id} has no grant on shard "
                f"{self.shard_map.addresses[client_idx]}"
            )
        return remote

    async def kv_put(self, key, value, lease_id=None):
        idx = self.shard_map.shard_for_key(key)
        await self.clients[idx].kv_put(
            key, value, self._owner_lease(idx, lease_id)
        )

    async def kv_get(self, key):
        return await self.client_for_key(key).kv_get(key)

    async def kv_get_prefix(self, prefix):
        return await self.client_for_prefix(prefix).kv_get_prefix(prefix)

    async def kv_delete(self, key):
        return await self.client_for_key(key).kv_delete(key)

    async def watch_prefix(self, prefix) -> Watcher:
        return await self.client_for_prefix(prefix).watch_prefix(prefix)

    # -- leases ---------------------------------------------------------------

    async def lease_grant(self, ttl: float = 10.0) -> int:
        per_shard: Dict[int, int] = {}
        for idx, client in enumerate(self.clients):
            per_shard[idx] = await client.lease_grant(ttl)
        local = next(self._lease_ids)
        self._leases[local] = per_shard
        return local

    async def lease_keepalive(self, lease_id: int) -> bool:
        per_shard = self._leases.get(lease_id)
        if per_shard is None:
            return False
        alive = True
        for idx, remote in list(per_shard.items()):
            if not await self.clients[idx].lease_keepalive(remote):
                alive = False
        if not alive:
            # One shard lost its half (restart/failover past the TTL):
            # the composite is broken — revoke the surviving halves so the
            # owner's re-grant path (lease monitor) starts clean instead
            # of leaving orphan leases ticking on healthy shards.
            await self.lease_revoke(lease_id)
        return alive

    async def lease_revoke(self, lease_id: int) -> None:
        per_shard = self._leases.pop(lease_id, None)
        if per_shard is None:
            return
        for idx, remote in per_shard.items():
            try:
                await self.clients[idx].lease_revoke(remote)
            except (ConnectionError, RuntimeError):
                # Unreachable shard: its lease half expires by TTL.
                pass

    # -- pub/sub ---------------------------------------------------------------

    async def publish(self, subject, payload) -> None:
        await self.client_for_subject(subject).publish(subject, payload)

    async def subscribe(self, pattern) -> Subscription:
        return await self.client_for_subject(pattern).subscribe(pattern)

    # -- queues ----------------------------------------------------------------
    # Ack tokens are shard-scoped: wrap them with the owning shard index so
    # ack/nack route back without the caller knowing about shards.

    async def q_push(self, queue, item) -> None:
        await self.client_for_key(queue).q_push(queue, item)

    async def q_pop(self, queue) -> Tuple[Any, str]:
        idx = self.shard_map.shard_for_key(queue)
        item, token = await self.clients[idx].q_pop(queue)
        return item, f"{idx}:{token}"

    def _unwrap_token(self, token: str) -> Tuple[HubClient, str]:
        idx_s, _, raw = token.partition(":")
        try:
            return self.clients[int(idx_s)], raw
        except (ValueError, IndexError):
            raise ValueError(f"not a sharded ack token: {token!r}") from None

    async def q_ack(self, token) -> bool:
        client, raw = self._unwrap_token(token)
        return await client.q_ack(raw)

    async def q_nack(self, token) -> bool:
        client, raw = self._unwrap_token(token)
        return await client.q_nack(raw)

    async def q_len(self, queue) -> int:
        return await self.client_for_key(queue).q_len(queue)
