"""TCP service plane: serve AsyncEngines remotely, call them as AsyncEngines.

Reference semantics: the request plane (NATS request → endpoint subject,
pipeline/network/egress/push.rs:88-158) + response plane (direct TCP callback
with prologue handshake and streamed frames, tcp/{server,client}.rs).  Here
both planes collapse onto MULTIPLEXED direct TCP connections: each client
process keeps ONE connection per worker address, and every request is a
stream id on it — header+data frames up, prologue+items down, CANCEL/KILL
up mid-stream.  (The reference gets multiplexing from NATS subjects +
registered response streams; round 2's connection-per-request design was
pure setup churn at high concurrency.)

Cancellation: CANCEL/KILL frames give remote ``stop_generating``/``kill``
the same semantics as in-process; a client disconnect cancels every stream
it owned (push_handler.rs:100-116 behaviour).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Dict, Optional, Tuple

from ..engine import AsyncEngine, AsyncEngineContext, Context, ResponseStream
from ..faultinject import faults
from ..resilience import Deadline
from .codec import Frame, FrameType, read_frame, write_frame

logger = logging.getLogger(__name__)

_DONE = object()


class RemoteEngineError(RuntimeError):
    """Error raised by the remote engine (propagated through RESP_ERROR).

    ``retryable`` distinguishes transport/worker failures (connection refused,
    connection closed before the stream finished, injected worker faults) from
    application errors the engine raised for THIS request (bad sampling
    params, oversized prompt) — the Client's failover loop only ever retries
    the former; replaying a deterministic request error across every worker
    would just multiply the damage.

    ``kind`` echoes the server prologue's error kind ("request" /
    "internal" / an application tag like "model_not_found") so edges can
    map specific remote failures to specific HTTP statuses without parsing
    message text.
    """

    def __init__(
        self, message: str, retryable: bool = True, kind: Optional[str] = None
    ):
        super().__init__(message)
        self.retryable = retryable
        self.kind = kind


# Built-in liveness/readiness path every ServiceServer answers without
# registration (runtime/health.py probes it over the SAME transport real
# requests ride; no extra port or protocol).
HEALTH_ENDPOINT = "__health__"


class ServiceServer:
    """Hosts AsyncEngines at string paths over TCP (multiplexed streams)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._endpoints: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()
        self._conn_writers: set = set()
        self.crashed = False
        # Optional harness hook fired by the ``worker_crash`` fault point:
        # the owning process finishes the death (revoke lease, close
        # runtime) the way a real SIGKILL would.
        self.on_crash = None
        self._crash_task: Optional[asyncio.Task] = None

    def register(self, path: str, engine: AsyncEngine) -> None:
        self._endpoints[path] = engine

    def unregister(self, path: str) -> None:
        self._endpoints.pop(path, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> "ServiceServer":
        if self._server is None:
            server = await asyncio.start_server(self._handle, self.host, self.port)
            if self._server is not None:
                # Lost a concurrent-start race while awaiting the bind
                # (dynalint DYN101): the first starter owns the address;
                # close the duplicate listener instead of leaking it.
                server.close()
            else:
                self._server = server
                self.port = server.sockets[0].getsockname()[1]
        return self

    def crash(self) -> None:
        """Simulate sudden worker death (the ``worker_crash`` fault point):
        stop accepting, hard-abort every live connection (clients see a
        reset, exactly like a SIGKILL'd process), and fire ``on_crash`` so
        the owner can finish the job (lease revoke etc.)."""
        if self.crashed:
            return
        self.crashed = True
        if self._server is not None:
            self._server.close()
        for writer in list(self._conn_writers):
            try:
                writer.transport.abort()
            except Exception:  # noqa: BLE001 — already-dead transport
                pass
        if self.on_crash is not None:
            res = self.on_crash()
            if asyncio.iscoroutine(res):
                self._crash_task = asyncio.get_running_loop().create_task(res)

    async def close(self) -> None:
        if (
            self._crash_task is not None
            and self._crash_task is not asyncio.current_task()
        ):
            # (An on_crash hook that itself closes the runtime reaches here
            # FROM the crash task — awaiting yourself deadlocks.)
            try:
                await self._crash_task
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — harness hook best-effort
                logger.exception("on_crash hook failed")
            self._crash_task = None
        if self._server is not None:
            self._server.close()
            # Long-lived multiplexed connections never end on their own —
            # cancel the per-connection handlers BEFORE wait_closed() (which
            # waits for them since 3.12).
            for task in list(self._conn_tasks):
                task.cancel()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn_task = asyncio.current_task()
        self._conn_tasks.add(conn_task)
        self._conn_writers.add(writer)
        wlock = asyncio.Lock()
        headers: Dict[int, Dict[str, Any]] = {}  # sid → REQ_HEADER awaiting data
        streams: Dict[int, Tuple[AsyncEngineContext, asyncio.Task]] = {}
        # Every spawned serve_stream keeps a strong ref here until done —
        # `streams` only covers tasks past their first registration line, so
        # a task cancelled (or GC'd) before its first step would otherwise
        # leak out of the finally-block sweep below.
        stream_tasks: set = set()

        async def send(ftype: FrameType, obj: Any = None, sid: int = 0) -> None:
            async with wlock:
                await write_frame(writer, ftype, obj, stream=sid)

        async def serve_stream(sid: int, header: Dict[str, Any], data: Any):
            endpoint_name = header.get("endpoint", "")
            ctx = AsyncEngineContext(header.get("id"))
            # Deadline propagation: the caller sends its REMAINING budget;
            # restart the clock here so queue/transit time already spent is
            # charged to the request (the edge decremented before sending).
            budget = header.get("deadline_s")
            if budget is not None:
                ctx.deadline = Deadline.after(float(budget))
            # Trace propagation (runtime/tracing.py): the caller ships its
            # TraceContext in the request header (omit-when-absent, like
            # deadline_s) so non-PreprocessedRequest payloads — KV exports,
            # control calls — join the request's trace too.
            tr = header.get("trace")
            if tr is not None:
                from ..tracing import parse_trace

                ctx.trace = parse_trace(tr)
            streams[sid] = (ctx, asyncio.current_task())
            try:
                if faults.enabled:
                    if faults.should("worker_crash", self.address):
                        self.crash()  # aborts this transport too
                        return
                    delay = faults.delay_for("delay", endpoint_name)
                    if delay > 0:
                        await asyncio.sleep(delay)
                    if faults.should("error_prologue", endpoint_name):
                        await send(
                            FrameType.RESP_PROLOGUE,
                            {"ok": False, "error": "[fault] injected prologue error",
                             "kind": "internal"},
                            sid,
                        )
                        return
                if endpoint_name == HEALTH_ENDPOINT:
                    # Liveness+readiness without registration: answering at
                    # all proves the transport; the endpoint count is the
                    # readiness signal (runtime/health.probe_address).
                    await send(FrameType.RESP_PROLOGUE, {"ok": True}, sid)
                    await send(
                        FrameType.RESP_ITEM,
                        {"ok": True, "endpoints": len(self._endpoints)},
                        sid,
                    )
                    await send(FrameType.RESP_COMPLETE, None, sid)
                    return
                engine = self._endpoints.get(endpoint_name)
                if engine is None:
                    await send(
                        FrameType.RESP_PROLOGUE,
                        {"ok": False,
                         "error": f"no such endpoint: {header.get('endpoint')}",
                         "kind": "endpoint"},
                        sid,
                    )
                    return
                try:
                    stream = await engine.generate(Context(data, ctx))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — remote boundary
                    # Request-shape errors are the caller's fault — tag them
                    # non-retryable so failover doesn't replay them.  An
                    # exception carrying its own ``error_kind`` (e.g.
                    # ModelNotFoundError → "model_not_found") ships that tag
                    # verbatim so the HTTP edge can map it to a status.
                    kind = getattr(e, "error_kind", None) or (
                        "request"
                        if isinstance(e, (ValueError, TypeError, KeyError))
                        else "internal"
                    )
                    await send(
                        FrameType.RESP_PROLOGUE,
                        {"ok": False, "error": str(e), "kind": kind},
                        sid,
                    )
                    return
                await send(FrameType.RESP_PROLOGUE, {"ok": True}, sid)
                try:
                    async for item in stream:
                        if faults.enabled:
                            # Straggler simulation: stretch THIS worker's
                            # inter-token latency (watchdog outlier bait).
                            stall = faults.delay_for(
                                "slow_stream", self.address
                            )
                            if stall > 0:
                                await asyncio.sleep(stall)
                        await send(FrameType.RESP_ITEM, item, sid)
                        if faults.enabled and faults.should(
                            "drop_mid_stream", endpoint_name
                        ):
                            # Simulate the worker dying mid-stream: hard-abort
                            # the transport (no RESP_ERROR courtesy).
                            ctx.stop_generating()
                            writer.transport.abort()
                            return
                    await send(FrameType.RESP_COMPLETE, None, sid)
                except (ConnectionResetError, BrokenPipeError):
                    ctx.stop_generating()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — stream error to client
                    try:
                        await send(FrameType.RESP_ERROR, {"error": str(e)}, sid)
                    except (ConnectionResetError, BrokenPipeError):
                        pass
            except asyncio.CancelledError:
                ctx.stop_generating()
                raise
            finally:
                streams.pop(sid, None)

        try:
            while True:
                frame = await read_frame(reader)
                sid = frame.stream
                if frame.type == FrameType.REQ_HEADER:
                    headers[sid] = frame.unpack()
                elif frame.type == FrameType.REQ_DATA:
                    header = headers.pop(sid, None)
                    if header is None:
                        continue  # protocol slip; drop
                    t = asyncio.create_task(
                        serve_stream(sid, header, frame.unpack())
                    )
                    stream_tasks.add(t)
                    t.add_done_callback(stream_tasks.discard)
                elif frame.type == FrameType.CANCEL:
                    if sid in streams:
                        streams[sid][0].stop_generating()
                elif frame.type == FrameType.KILL:
                    if sid in streams:
                        streams[sid][0].kill()
                # HEARTBEAT and unknown types: ignore
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # client went away: cancel everything it owned below
        finally:
            for ctx, task in list(streams.values()):
                ctx.stop_generating()
                task.cancel()
            # Catch stragglers not yet registered in `streams` too — after
            # close() the connection must own zero live tasks.
            for task in list(stream_tasks):
                task.cancel()
            writer.close()
            self._conn_writers.discard(writer)
            self._conn_tasks.discard(conn_task)


class MuxConnection:
    """One shared client connection per worker address; streams by id.

    ``get()`` returns the live connection for an address (dialing if
    needed); a broken connection errors all of its in-flight streams and the
    next ``get()`` dials fresh.
    """

    _by_address: Dict[str, "MuxConnection"] = {}
    _dial_locks: Dict[Tuple[int, str], asyncio.Lock] = {}
    # Per-stream receive buffer bound: items are small (one token chunk),
    # so this caps a stalled consumer's memory without blocking the shared
    # read loop (head-of-line).  Overflow terminates only that stream.
    STREAM_QUEUE_MAX = 8192

    def __init__(self, address: str):
        self.address = address
        self._loop = asyncio.get_running_loop()
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock = asyncio.Lock()
        self._queues: Dict[int, asyncio.Queue] = {}
        self._sid = itertools.count(1)
        self._reader_task: Optional[asyncio.Task] = None
        self.closed = False

    @classmethod
    async def get(cls, address: str) -> "MuxConnection":
        # Serialize dialing per (loop, address) so two concurrent first
        # requests can't race into two connections (one would leak).
        lock_key = (id(asyncio.get_running_loop()), address)
        lock = cls._dial_locks.setdefault(lock_key, asyncio.Lock())
        async with lock:
            conn = cls._by_address.get(address)
            # A cached connection is only usable from the loop that created
            # it (its transport and reader task are loop-bound); a different
            # running loop means the old one is gone — dial fresh.
            if (
                conn is not None
                and conn._loop is not asyncio.get_running_loop()
            ):
                conn._close_transport()  # best effort on a dead loop
                conn = None
            if conn is None or conn.closed:
                conn = cls(address)
                await conn._connect()
                cls._by_address[address] = conn
            return conn

    async def _connect(self) -> None:
        if faults.enabled and faults.should("connect_error", self.address):
            self.closed = True
            raise ConnectionRefusedError(
                f"[fault] connect to {self.address} refused"
            )
        host, port = self.address.rsplit(":", 1)
        self._reader, self._writer = await asyncio.open_connection(host, int(port))
        self._reader_task = asyncio.create_task(self._read_loop())

    def _close_transport(self) -> None:
        self.closed = True
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                queue = self._queues.get(frame.stream)
                if queue is None:
                    continue
                if queue.qsize() >= self.STREAM_QUEUE_MAX:
                    # Stalled consumer: kill this stream, not the connection.
                    queue.put_nowait(_DONE)
                    self._queues.pop(frame.stream, None)
                    continue
                queue.put_nowait(frame)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._close_transport()
            for q in self._queues.values():
                q.put_nowait(_DONE)

    async def open_stream(self, header: Dict[str, Any], data: Any) -> Tuple[int, asyncio.Queue]:
        sid = next(self._sid)
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[sid] = queue
        try:
            async with self._wlock:
                await write_frame(self._writer, FrameType.REQ_HEADER, header, stream=sid)
                await write_frame(self._writer, FrameType.REQ_DATA, data, stream=sid)
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            self._close_transport()
            self._queues.pop(sid, None)
            raise RemoteEngineError(f"connection to {self.address} failed: {e}")
        return sid, queue

    async def send(self, ftype: FrameType, sid: int) -> None:
        if self.closed:
            return
        try:
            async with self._wlock:
                await write_frame(self._writer, ftype, None, stream=sid)
        except (ConnectionResetError, BrokenPipeError, OSError):
            self._close_transport()

    def release(self, sid: int) -> None:
        self._queues.pop(sid, None)


class RemoteEngine(AsyncEngine):
    """AsyncEngine proxy for an endpoint served by a remote ServiceServer."""

    def __init__(self, address: str, endpoint: str):
        self.address = address
        self.endpoint = endpoint

    async def generate(self, request: Context) -> ResponseStream:
        conn = await MuxConnection.get(self.address)
        header = {"id": request.id, "endpoint": self.endpoint}
        deadline = getattr(request.ctx, "deadline", None)
        if deadline is not None:
            # Ship the REMAINING budget; the server restarts its own clock.
            header["deadline_s"] = max(deadline.remaining(), 0.0)
        trace = getattr(request.ctx, "trace", None)
        if trace is not None and trace.sampled:
            # Omitted when absent: untraced requests (and pre-tracing
            # consumers) keep the established header shape.
            header["trace"] = trace.to_dict()
        sid, queue = await conn.open_stream(header, request.data)
        try:
            first = await queue.get()
            if first is _DONE:
                raise RemoteEngineError("remote connection closed")
            prologue = first.unpack()
            if not prologue.get("ok"):
                raise RemoteEngineError(
                    prologue.get("error", "remote engine error"),
                    # Application errors (bad request shape, unknown
                    # model/adapter) must not be replayed on other workers;
                    # transport/worker sickness may.
                    retryable=prologue.get("kind") in (None, "internal", "endpoint"),
                    kind=prologue.get("kind"),
                )
        except BaseException:
            conn.release(sid)
            raise

        ctx = request.ctx

        async def forward_cancel():
            try:
                await ctx.stopped()
                await conn.send(
                    FrameType.KILL if ctx.is_killed else FrameType.CANCEL, sid
                )
            except asyncio.CancelledError:
                # aclose() cancels this helper when the stream ends; ending
                # as a cancelled task (nobody awaits the result) is clean.
                raise

        cancel_task = asyncio.create_task(forward_cancel())
        return ResponseStream(
            _RemoteStreamIter(conn, sid, queue, cancel_task), ctx
        )


class _RemoteStreamIter:
    """Response-frame iterator whose aclose() always releases the stream.

    aclose() before completion also tells the worker to stop (CANCEL) —
    with a shared connection there is no socket close to signal abandonment.
    """

    def __init__(
        self,
        conn: MuxConnection,
        sid: int,
        queue: asyncio.Queue,
        cancel_task: asyncio.Task,
    ):
        self._conn = conn
        self._sid = sid
        self._queue = queue
        self._cancel_task = cancel_task
        self._done = False

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._done:
            raise StopAsyncIteration
        try:
            while True:
                frame = await self._queue.get()
                if frame is _DONE:
                    await self.aclose(notify=False)
                    raise RemoteEngineError("remote connection closed mid-stream")
                if frame.type == FrameType.RESP_ITEM:
                    return frame.unpack()
                if frame.type == FrameType.RESP_COMPLETE:
                    await self.aclose(notify=False)
                    raise StopAsyncIteration
                if frame.type == FrameType.RESP_ERROR:
                    err = frame.unpack().get("error", "remote error")
                    await self.aclose(notify=False)
                    # The engine raised for this request — not worker health.
                    raise RemoteEngineError(err, retryable=False)
                # ignore heartbeats/unknown frame types
        except BaseException:
            await self.aclose()
            raise

    async def aclose(self, notify: bool = True) -> None:
        if self._done:
            return
        self._done = True
        self._cancel_task.cancel()
        if notify:
            # Abandoned before completion: stop the remote generation.
            await self._conn.send(FrameType.CANCEL, self._sid)
        self._conn.release(self._sid)
