"""TCP service plane: serve AsyncEngines remotely, call them as AsyncEngines.

Reference semantics: the request plane (NATS request → endpoint subject,
pipeline/network/egress/push.rs:88-158) + response plane (direct TCP callback
with prologue handshake and streamed frames, tcp/{server,client}.rs) — here
collapsed onto ONE direct TCP connection per request: the client dials the
worker, sends header+data (TwoPartMessage), reads a prologue then streamed
items.  CANCEL/KILL frames flow client→worker mid-stream, giving remote
cancellation the same semantics as in-process ``stop_generating``/``kill``
(the reference gets this implicitly by dropping the response stream;
explicit frames are stronger).

A send failure on the worker side stops generation for that request
(push_handler.rs:100-116 behaviour).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Callable, Dict, Optional

from ..engine import AsyncEngine, AsyncEngineContext, Context, ResponseStream
from .codec import FrameType, read_frame, write_frame


class RemoteEngineError(RuntimeError):
    """Error raised by the remote engine (propagated through RESP_ERROR)."""


class ServiceServer:
    """Hosts AsyncEngines at string paths over TCP; one request per connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._endpoints: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._active: set = set()

    def register(self, path: str, engine: AsyncEngine) -> None:
        self._endpoints[path] = engine

    def unregister(self, path: str) -> None:
        self._endpoints.pop(path, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> "ServiceServer":
        if self._server is None:
            self._server = await asyncio.start_server(self._handle, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for task in list(self._active):
            task.cancel()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        task = asyncio.current_task()
        self._active.add(task)
        ctx: Optional[AsyncEngineContext] = None
        control_task: Optional[asyncio.Task] = None
        try:
            header_frame = await read_frame(reader)
            if header_frame.type != FrameType.REQ_HEADER:
                return
            header = header_frame.unpack()
            data_frame = await read_frame(reader)
            if data_frame.type != FrameType.REQ_DATA:
                return

            engine = self._endpoints.get(header.get("endpoint", ""))
            if engine is None:
                await write_frame(
                    writer,
                    FrameType.RESP_PROLOGUE,
                    {"ok": False, "error": f"no such endpoint: {header.get('endpoint')}"},
                )
                return

            ctx = AsyncEngineContext(header.get("id"))
            request = Context(data_frame.unpack(), ctx)

            async def control_loop():
                # reads CANCEL/KILL from the client for the life of the stream
                try:
                    while True:
                        frame = await read_frame(reader)
                        if frame.type == FrameType.CANCEL:
                            ctx.stop_generating()
                        elif frame.type == FrameType.KILL:
                            ctx.kill()
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    # client went away entirely
                    ctx.stop_generating()

            control_task = asyncio.create_task(control_loop())

            try:
                stream = await engine.generate(request)
            except Exception as e:  # noqa: BLE001 — remote boundary
                await write_frame(
                    writer, FrameType.RESP_PROLOGUE, {"ok": False, "error": str(e)}
                )
                return

            await write_frame(writer, FrameType.RESP_PROLOGUE, {"ok": True})
            try:
                async for item in stream:
                    await write_frame(writer, FrameType.RESP_ITEM, item)
                await write_frame(writer, FrameType.RESP_COMPLETE)
            except (ConnectionResetError, BrokenPipeError):
                ctx.stop_generating()
            except Exception as e:  # noqa: BLE001 — stream error to client
                try:
                    await write_frame(writer, FrameType.RESP_ERROR, {"error": str(e)})
                except (ConnectionResetError, BrokenPipeError):
                    pass
        except (asyncio.IncompleteReadError, ConnectionResetError):
            if ctx is not None:
                ctx.stop_generating()
        finally:
            if control_task is not None:
                control_task.cancel()
            writer.close()
            self._active.discard(task)


class RemoteEngine(AsyncEngine):
    """AsyncEngine proxy for an endpoint served by a remote ServiceServer."""

    def __init__(self, address: str, endpoint: str):
        self.address = address
        self.endpoint = endpoint

    async def generate(self, request: Context) -> ResponseStream:
        host, port = self.address.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            await write_frame(
                writer, FrameType.REQ_HEADER, {"id": request.id, "endpoint": self.endpoint}
            )
            await write_frame(writer, FrameType.REQ_DATA, request.data)
            prologue_frame = await read_frame(reader)
            prologue = prologue_frame.unpack()
            if not prologue.get("ok"):
                raise RemoteEngineError(prologue.get("error", "remote engine error"))
        except BaseException:
            writer.close()
            raise

        ctx = request.ctx

        async def forward_cancel():
            try:
                await ctx.stopped()
                await write_frame(
                    writer, FrameType.KILL if ctx.is_killed else FrameType.CANCEL
                )
            except (asyncio.CancelledError, ConnectionResetError, BrokenPipeError):
                pass

        cancel_task = asyncio.create_task(forward_cancel())
        return ResponseStream(_RemoteStreamIter(reader, writer, cancel_task), ctx)


class _RemoteStreamIter:
    """Response-frame iterator whose aclose() always releases the connection.

    A plain inner async generator would skip its ``finally`` when closed
    before the first ``__anext__`` (never-started generators don't run their
    body), leaking the socket and the cancel-forwarding task; this class owns
    cleanup explicitly.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        cancel_task: asyncio.Task,
    ):
        self._reader = reader
        self._writer = writer
        self._cancel_task = cancel_task
        self._done = False

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._done:
            raise StopAsyncIteration
        try:
            while True:
                frame = await read_frame(self._reader)
                if frame.type == FrameType.RESP_ITEM:
                    return frame.unpack()
                if frame.type == FrameType.RESP_COMPLETE:
                    await self.aclose()
                    raise StopAsyncIteration
                if frame.type == FrameType.RESP_ERROR:
                    err = frame.unpack().get("error", "remote error")
                    await self.aclose()
                    raise RemoteEngineError(err)
                # ignore heartbeats/unknown frame types
        except asyncio.IncompleteReadError:
            await self.aclose()
            raise RemoteEngineError("remote connection closed mid-stream")
        except BaseException:
            await self.aclose()
            raise

    async def aclose(self) -> None:
        if self._done:
            return
        self._done = True
        self._cancel_task.cancel()
        self._writer.close()
