"""The hub: self-contained control plane for discovery, events, and queues.

The reference outsources these planes to external infrastructure — etcd
(lease-based discovery KV with prefix watches, lib/runtime/src/transports/
etcd.rs:41-330) and NATS core/JetStream (pub-sub event plane + work queues,
transports/nats.rs).  This build provides one self-contained hub speaking a
newline-delimited-JSON protocol over TCP, so a full distributed deployment
needs zero external services.  Three faces:

- ``HubState``   — the in-memory state machine (KV + leases + subs + queues).
- ``HubServer``  — asyncio TCP server exposing it (the ``docker-compose``
  etcd+NATS replacement; run via ``python -m dynamo_tpu.cli hub``).
- ``HubClient``  — asyncio client; same async interface as ``InprocHub``.
- ``InprocHub``  — direct in-process binding for single-process serving and
  tests (the reference's "static mode", lib/runtime/src/distributed.rs).

Semantics preserved from the reference:
- KV entries may be attached to a **lease**; lease expiry deletes the keys and
  notifies prefix watchers (liveness = lease keep-alive; etcd/lease.rs:19-51).
- ``watch_prefix`` emits the current snapshot as Put events, then live deltas
  (etcd.rs:246-330 ``kv_get_and_watch_prefix``).
- Queues are at-least-once: popped items must be acked; a consumer
  disconnecting with unacked items requeues them (JetStream prefill queue,
  examples/llm/utils/nats_queue.py).
- Subjects support NATS-style wildcards: ``*`` one token, ``>`` tail.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import random
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Set, Tuple

from ..faultinject import faults

logger = logging.getLogger(__name__)


class HubSessionLost(ConnectionError):
    """The hub connection dropped mid-watch.  Server-side watch state is
    gone, so deltas may have been missed: the consumer must re-arm the
    watch (``hub.watch_prefix`` again — it blocks until the hub is back)
    and RESYNC its derived state from a fresh ``kv_get_prefix`` snapshot.
    Every long-lived watcher in the tree follows this recovery shape."""


# Queue sentinel a reconnecting HubClient injects into live watch queues:
# the old server-side watch died with the connection, so the Watcher must
# surface HubSessionLost rather than silently starve.
_WATCH_LOST = object()


# --------------------------------------------------------------------------
# Events / small types
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class WatchEvent:
    """Put/Delete delta on a watched prefix (reference ``WatchEvent``)."""

    type: str  # "put" | "delete"
    key: str
    value: Any = None


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style subject matching: ``*`` = one token, ``>`` = remainder."""
    pt, st = pattern.split("."), subject.split(".")
    for i, p in enumerate(pt):
        if p == ">":
            return len(st) > i  # '>' matches one or more remaining tokens
        if i >= len(st):
            return False
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


# --------------------------------------------------------------------------
# State machine
# --------------------------------------------------------------------------


@dataclass
class _Lease:
    id: int
    ttl: float
    expires_at: float
    keys: Set[str] = field(default_factory=set)


@dataclass
class _QueueItem:
    item: Any
    ack_token: str


class HubState:
    """In-memory KV + lease + pub/sub + queue state with watcher fanout.

    All mutation goes through async methods on the owning event loop, so no
    locks are needed (single-threaded asyncio, the same reasoning as the
    reference's dedicated indexer thread).
    """

    def __init__(self):
        self._kv: Dict[str, Any] = {}
        self._kv_lease: Dict[str, int] = {}
        self._leases: Dict[int, _Lease] = {}
        self._next_lease_id = 1
        self._revision = 0
        # watch id → (prefix, asyncio.Queue of WatchEvent)
        self._watches: Dict[str, Tuple[str, asyncio.Queue]] = {}
        # sub id → (pattern, queue of (subject, payload))
        self._subs: Dict[str, Tuple[str, asyncio.Queue]] = {}
        # queue name → deque of _QueueItem
        self._queues: Dict[str, deque] = {}
        # queue name → waiters (futures)
        self._q_waiters: Dict[str, deque] = {}
        # ack token → (queue name, item) for in-flight redelivery
        self._inflight: Dict[str, Tuple[str, Any]] = {}
        self._expiry_task: Optional[asyncio.Task] = None
        # Replication taps: called (synchronously, on the owning loop) with
        # one op-log entry per durable-state mutation — exactly the deltas
        # a warm standby needs to keep a live copy of ``snapshot()``.
        self._repl_taps: List[Callable[[Dict[str, Any]], None]] = []

    # -- lifecycle ----------------------------------------------------------

    def start_expiry_loop(self) -> None:
        if self._expiry_task is None or self._expiry_task.done():
            self._expiry_task = asyncio.get_running_loop().create_task(
                self._expire_leases_loop()
            )

    async def close(self) -> None:
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            try:
                await self._expiry_task
            except asyncio.CancelledError:
                pass
            self._expiry_task = None

    async def _expire_leases_loop(self) -> None:
        while True:
            await asyncio.sleep(0.2)
            now = time.monotonic()
            expired = [l for l in self._leases.values() if l.expires_at <= now]
            for lease in expired:
                await self.lease_revoke(lease.id)

    # -- replication ---------------------------------------------------------

    def add_replication_tap(self, tap: Callable[[Dict[str, Any]], None]) -> None:
        self._repl_taps.append(tap)

    def remove_replication_tap(self, tap: Callable[[Dict[str, Any]], None]) -> None:
        try:
            self._repl_taps.remove(tap)
        except ValueError:
            pass

    def _replicate(self, entry: Dict[str, Any]) -> None:
        for notify in self._repl_taps:
            notify(entry)

    # -- KV -----------------------------------------------------------------

    async def kv_put(self, key: str, value: Any, lease_id: Optional[int] = None) -> None:
        self._revision += 1
        self._kv[key] = value
        # Rebinding a key to a different lease (or to none) must detach it
        # from the previous lease, or that lease's later expiry would
        # delete a key it no longer owns (the composite-lease re-grant
        # path rebinds every registration onto a fresh lease).
        old_lease = self._kv_lease.get(key)
        if old_lease is not None and old_lease != lease_id:
            if old_lease in self._leases:
                self._leases[old_lease].keys.discard(key)
        if lease_id is not None:
            if lease_id not in self._leases:
                raise KeyError(f"unknown lease {lease_id}")
            self._kv_lease[key] = lease_id
            self._leases[lease_id].keys.add(key)
            if self._repl_taps:
                # Lease-bound keys are NOT durable (snapshot() skips them:
                # live workers re-register); a key that was durable and is
                # now leased leaves the standby's durable view.
                self._replicate({"op": "kv_delete", "key": key})
        else:
            self._kv_lease.pop(key, None)
            if self._repl_taps:
                self._replicate({"op": "kv_put", "key": key, "value": value})
        self._notify(WatchEvent("put", key, value))

    async def kv_get(self, key: str) -> Any:
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix: str) -> Dict[str, Any]:
        return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    async def kv_delete(self, key: str) -> bool:
        if key not in self._kv:
            return False
        self._kv.pop(key)
        lease_id = self._kv_lease.pop(key, None)
        if lease_id is not None and lease_id in self._leases:
            self._leases[lease_id].keys.discard(key)
        if self._repl_taps and lease_id is None:
            self._replicate({"op": "kv_delete", "key": key})
        self._notify(WatchEvent("delete", key))
        return True

    def _notify(self, event: WatchEvent) -> None:
        if faults.enabled and (
            faults.is_armed("watch_stall") or faults.is_armed("hub_outage")
        ):
            # Simulated hub partition/outage: deltas silently stop reaching
            # watchers (their view goes stale until the fault clears).
            return
        for prefix, q in self._watches.values():
            if event.key.startswith(prefix):
                q.put_nowait(event)

    # -- watches ------------------------------------------------------------

    async def watch_create(self, prefix: str) -> Tuple[str, asyncio.Queue]:
        wid = uuid.uuid4().hex
        q: asyncio.Queue = asyncio.Queue()
        # snapshot first (kv_get_and_watch_prefix semantics), then a sync
        # marker so watchers know the snapshot is complete
        for k, v in self._kv.items():
            if k.startswith(prefix):
                q.put_nowait(WatchEvent("put", k, v))
        q.put_nowait(WatchEvent("sync", ""))
        self._watches[wid] = (prefix, q)
        return wid, q

    async def watch_cancel(self, wid: str) -> None:
        self._watches.pop(wid, None)

    # -- leases -------------------------------------------------------------

    async def lease_grant(self, ttl: float) -> int:
        lid = self._next_lease_id
        self._next_lease_id += 1
        self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl)
        if self._repl_taps:
            # The standby tracks the id floor so a promoted shard never
            # re-issues an id a pre-failover client still keeps alive.
            self._replicate({"op": "lease_floor", "floor": self._next_lease_id})
        return lid

    async def lease_keepalive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.expires_at = time.monotonic() + lease.ttl
        return True

    async def lease_revoke(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            # Only delete keys STILL bound to this lease — a key rebound
            # to a fresh lease since must survive the old one's expiry.
            if self._kv_lease.get(key) == lease_id:
                await self.kv_delete(key)

    # -- pub/sub ------------------------------------------------------------

    async def subscribe(self, pattern: str) -> Tuple[str, asyncio.Queue]:
        sid = uuid.uuid4().hex
        q: asyncio.Queue = asyncio.Queue()
        self._subs[sid] = (pattern, q)
        return sid, q

    async def unsubscribe(self, sid: str) -> None:
        self._subs.pop(sid, None)

    async def publish(self, subject: str, payload: Any) -> int:
        if faults.enabled and faults.is_armed("hub_outage"):
            return 0  # event plane down with the hub
        n = 0
        for pattern, q in self._subs.values():
            if subject_matches(pattern, subject):
                q.put_nowait((subject, payload))
                n += 1
        return n

    # -- queues (at-least-once) --------------------------------------------

    async def q_push(self, queue: str, item: Any) -> None:
        waiters = self._q_waiters.setdefault(queue, deque())
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                token = uuid.uuid4().hex
                self._inflight[token] = (queue, item)
                if self._repl_taps:
                    self._replicate({
                        "op": "q_add", "queue": queue, "item": item,
                        "where": "inflight",
                    })
                fut.set_result(_QueueItem(item, token))
                return
        self._queues.setdefault(queue, deque()).append(
            _QueueItem(item, uuid.uuid4().hex)
        )
        if self._repl_taps:
            self._replicate({
                "op": "q_add", "queue": queue, "item": item, "where": "queue",
            })

    async def q_pop(self, queue: str) -> _QueueItem:
        dq = self._queues.setdefault(queue, deque())
        if dq:
            qi = dq.popleft()
            self._inflight[qi.ack_token] = (queue, qi.item)
            if self._repl_taps:
                self._replicate({"op": "q_take", "queue": queue, "item": qi.item})
            return qi
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._q_waiters.setdefault(queue, deque()).append(fut)
        try:
            return await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # q_push handed us an item but our task was cancelled at the
                # await: requeue it so at-least-once holds
                qi = fut.result()
                await self.q_nack(qi.ack_token)
            else:
                fut.cancel()  # q_push skips done/cancelled waiters
            raise

    async def q_ack(self, token: str) -> bool:
        entry = self._inflight.pop(token, None)
        if entry is None:
            return False
        if self._repl_taps:
            queue, item = entry
            self._replicate({"op": "q_settle", "queue": queue, "item": item})
        return True

    async def q_nack(self, token: str) -> bool:
        """Requeue an in-flight item (redelivery; consumer died/declined)."""
        entry = self._inflight.pop(token, None)
        if entry is None:
            return False
        queue, item = entry
        if self._repl_taps:
            self._replicate({"op": "q_settle", "queue": queue, "item": item})
        await self.q_push(queue, item)
        return True

    async def q_len(self, queue: str) -> int:
        return len(self._queues.get(queue, ()))

    # -- persistence --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Durable state: KV entries NOT bound to leases (lease-bound keys
        are live-worker registrations that must re-register on rejoin) plus
        queued + in-flight work items (at-least-once across restart).  The
        lease-id floor also persists: a restarted hub must never re-issue
        an id a pre-restart client still keeps alive (its keepalives would
        silently sustain a stranger's lease)."""
        return {
            "kv": {
                k: v for k, v in self._kv.items() if k not in self._kv_lease
            },
            "queues": {
                name: [qi.item for qi in dq]
                for name, dq in self._queues.items()
                if dq
            },
            "inflight": [
                [queue, item] for queue, item in self._inflight.values()
            ],
            "lease_floor": self._next_lease_id,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        try:
            floor = int(snap.get("lease_floor", 1))
        except (TypeError, ValueError):
            floor = 1
        self._next_lease_id = max(self._next_lease_id, floor)
        for k, v in (snap.get("kv") or {}).items():
            self._kv[k] = v
        for name, items in (snap.get("queues") or {}).items():
            dq = self._queues.setdefault(name, deque())
            for item in items:
                dq.append(_QueueItem(item, uuid.uuid4().hex))
        for queue, item in snap.get("inflight") or ():
            # undelivered at snapshot time from the consumer's perspective
            self._queues.setdefault(queue, deque()).append(
                _QueueItem(item, uuid.uuid4().hex)
            )


# --------------------------------------------------------------------------
# In-process binding
# --------------------------------------------------------------------------


class _QueueIter:
    """Async iterator over a queue with a None close-sentinel and aclose."""

    def __init__(self, queue: asyncio.Queue, cancel: Callable):
        self._queue = queue
        self._cancel = cancel
        self._closed = False

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._closed:
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def aclose(self) -> None:
        if not self._closed:
            self._closed = True
            await self._cancel()


class Watcher(_QueueIter):
    """Async iterator of WatchEvents.

    The snapshot is terminated by a ``sync`` marker event; it is not yielded —
    instead it sets ``synced`` so callers can wait for a consistent initial
    view before routing.
    """

    def __init__(self, queue: asyncio.Queue, cancel: Callable):
        super().__init__(queue, cancel)
        self.synced = asyncio.Event()

    async def __anext__(self) -> WatchEvent:
        if faults.enabled and faults.should("watch_error"):
            raise RuntimeError("[fault] injected watch stream failure")
        while True:
            ev = await super().__anext__()
            if ev is _WATCH_LOST:
                raise HubSessionLost(
                    "hub connection lost; re-arm the watch and resync"
                )
            if ev.type == "sync":
                self.synced.set()
                continue
            return ev


class Subscription(_QueueIter):
    """Async iterator of (subject, payload) with unsubscribe."""


def _payload_nbytes(payload: Any) -> int:
    """Approximate serialized size of a publish payload, for the per-shard
    control-plane volume counters (shard.HubShardMetrics.note_publish) —
    the series that proves bulk bytes left the hub under DYN_BULK_PLANE.
    Best-effort: an unencodable payload counts 0 rather than failing the
    publish."""
    from . import codec

    try:
        return len(codec.encode(payload))
    except Exception:  # noqa: BLE001 — metrics must never break a publish
        return 0


class InprocHub:
    """Direct in-process hub (single-process serving, tests, static mode).

    Leases granted here are auto-kept-alive (the owning process being alive IS
    the liveness signal), matching HubClient's keepalive behaviour, until
    ``lease_revoke``/``close``.
    """

    def __init__(self):
        self.state = HubState()
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}

    async def start(self) -> "InprocHub":
        self.state.start_expiry_loop()
        return self

    async def close(self) -> None:
        for t in self._keepalive_tasks.values():
            t.cancel()
        self._keepalive_tasks.clear()
        await self.state.close()

    # KV
    async def kv_put(self, key, value, lease_id=None):
        await self.state.kv_put(key, value, lease_id)

    async def kv_get(self, key):
        return await self.state.kv_get(key)

    async def kv_get_prefix(self, prefix):
        return await self.state.kv_get_prefix(prefix)

    async def kv_delete(self, key):
        return await self.state.kv_delete(key)

    async def watch_prefix(self, prefix) -> Watcher:
        wid, q = await self.state.watch_create(prefix)

        async def cancel():
            await self.state.watch_cancel(wid)
            q.put_nowait(None)

        return Watcher(q, cancel)

    # leases
    async def lease_grant(self, ttl: float = 10.0) -> int:
        lid = await self.state.lease_grant(ttl)
        self._keepalive_tasks[lid] = asyncio.get_running_loop().create_task(
            self._keepalive_loop(lid, ttl)
        )
        return lid

    async def _keepalive_loop(self, lease_id: int, ttl: float) -> None:
        try:
            while await self.state.lease_keepalive(lease_id):
                await asyncio.sleep(max(ttl / 3.0, 0.05))
        except asyncio.CancelledError:
            pass

    async def lease_keepalive(self, lease_id: int) -> bool:
        return await self.state.lease_keepalive(lease_id)

    async def lease_revoke(self, lease_id: int) -> None:
        task = self._keepalive_tasks.pop(lease_id, None)
        if task:
            task.cancel()
        await self.state.lease_revoke(lease_id)

    # pub/sub
    async def publish(self, subject, payload) -> None:
        from .shard import shard_metrics

        shard_metrics.note_publish("inproc", _payload_nbytes(payload))
        await self.state.publish(subject, payload)

    async def subscribe(self, pattern) -> Subscription:
        sid, q = await self.state.subscribe(pattern)

        async def cancel():
            await self.state.unsubscribe(sid)
            q.put_nowait(None)

        return Subscription(q, cancel)

    # queues
    async def q_push(self, queue, item) -> None:
        await self.state.q_push(queue, item)

    async def q_pop(self, queue) -> Tuple[Any, str]:
        qi = await self.state.q_pop(queue)
        return qi.item, qi.ack_token

    async def q_ack(self, token) -> bool:
        return await self.state.q_ack(token)

    async def q_nack(self, token) -> bool:
        return await self.state.q_nack(token)

    async def q_len(self, queue) -> int:
        return await self.state.q_len(queue)


# --------------------------------------------------------------------------
# TCP server
# --------------------------------------------------------------------------


class HubServer:
    """TCP front for HubState: newline-delimited JSON request/push protocol.

    Client → server: ``{"rid": n, "op": "...", ...}``
    Server → client: ``{"rid": n, "ok": true, ...}`` or pushes
    ``{"push": "watch"|"msg"|null, "id": sub_or_watch_id, ...}``.

    Per-connection bookkeeping mirrors broker session semantics: dropping the
    connection cancels its watches/subscriptions, requeues its unacked queue
    items, and stops keepalives for its leases (which then expire → liveness).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        persist_path: Optional[str] = None,
        persist_interval_s: float = 2.0,
    ):
        self.host = host
        self.port = port
        self.state = HubState()
        self._server: Optional[asyncio.base_events.Server] = None
        # Restart-survival (reference: etcd raft log / NATS JetStream file
        # store): durable KV + queued work snapshot to disk; lease-bound
        # registrations intentionally NOT persisted (workers re-register).
        self.persist_path = persist_path
        self.persist_interval_s = persist_interval_s
        self._persist_task: Optional[asyncio.Task] = None
        # Live per-connection handler tasks.  asyncio's Server.close() does
        # NOT end established connections (and 3.12's wait_closed would wait
        # on them forever), so close() cancels these explicitly — no orphan
        # pump/handler tasks may survive a closed hub.
        self._conn_tasks: set = set()

    async def start(self) -> "HubServer":
        if self.persist_path and os.path.exists(self.persist_path):
            with open(self.persist_path) as f:
                self.state.restore(json.load(f))
        self.state.start_expiry_loop()
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.persist_path:
            self._persist_task = asyncio.get_running_loop().create_task(
                self._persist_loop()
            )
        return self

    def _persist_now(self) -> None:
        if not self.persist_path:
            return
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state.snapshot(), f)
        os.replace(tmp, self.persist_path)  # atomic swap

    async def _persist_loop(self) -> None:
        while True:
            await asyncio.sleep(self.persist_interval_s)
            try:
                self._persist_now()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("hub snapshot failed")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def close(self) -> None:
        if self._persist_task is not None:
            self._persist_task.cancel()
            try:
                await self._persist_task
            except asyncio.CancelledError:
                pass
            self._persist_task = None
        try:
            self._persist_now()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("final hub snapshot failed")
        if self._server is not None:
            self._server.close()
            for task in list(self._conn_tasks):
                task.cancel()
            await self._server.wait_closed()
            self._server = None
        await self.state.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn_task = asyncio.current_task()
        self._conn_tasks.add(conn_task)
        session_watches: Dict[str, asyncio.Task] = {}
        session_subs: Dict[str, asyncio.Task] = {}
        session_unacked: Set[str] = set()
        session_pop_tasks: Set[asyncio.Task] = set()
        session_repl_taps: List[Callable[[Dict[str, Any]], None]] = []
        session_repl_tasks: Set[asyncio.Task] = set()
        write_lock = asyncio.Lock()

        async def send(obj: Any) -> None:
            async with write_lock:
                writer.write(json.dumps(obj).encode() + b"\n")
                await writer.drain()

        async def pump_watch(wid: str, q: asyncio.Queue):
            while True:
                ev = await q.get()
                await send(
                    {"push": "watch", "id": wid, "type": ev.type, "key": ev.key, "value": ev.value}
                )

        async def pump_sub(sid: str, q: asyncio.Queue):
            while True:
                subject, payload = await q.get()
                await send({"push": "msg", "id": sid, "subject": subject, "payload": payload})

        async def do_pop(rid: int, queue: str):
            qi = await self.state.q_pop(queue)
            session_unacked.add(qi.ack_token)
            await send({"rid": rid, "ok": True, "item": qi.item, "token": qi.ack_token})

        async def pump_oplog(q: asyncio.Queue):
            while True:
                entry = await q.get()
                await send({"push": "oplog", "entry": entry})

        try:
            while True:
                if faults.enabled and faults.is_armed("hub_outage"):
                    # Simulated hub outage: drop the connection without a
                    # goodbye (clients observe exactly what a dead hub
                    # looks like and enter their reconnect loops; the
                    # accept path below drops fresh dials the same way).
                    break
                line = await reader.readline()
                if not line:
                    break
                if faults.enabled and faults.is_armed("hub_outage"):
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    await send({"rid": None, "ok": False, "error": "bad json"})
                    continue
                rid, op = msg.get("rid"), msg.get("op")
                try:
                    st = self.state
                    if op == "kv_put":
                        await st.kv_put(msg["key"], msg.get("value"), msg.get("lease"))
                        await send({"rid": rid, "ok": True})
                    elif op == "kv_get":
                        await send({"rid": rid, "ok": True, "value": await st.kv_get(msg["key"])})
                    elif op == "kv_get_prefix":
                        await send(
                            {"rid": rid, "ok": True, "kvs": await st.kv_get_prefix(msg["prefix"])}
                        )
                    elif op == "kv_delete":
                        await send({"rid": rid, "ok": True, "deleted": await st.kv_delete(msg["key"])})
                    elif op == "watch":
                        wid, q = await st.watch_create(msg["prefix"])
                        # respond before pumping: the client must map wid → queue
                        # before the first push (snapshot) hits the socket
                        await send({"rid": rid, "ok": True, "id": wid})
                        wt = asyncio.create_task(pump_watch(wid, q))
                        session_watches[wid] = wt
                        # A crashed pump must not linger as a live-looking
                        # entry (close() would "cancel" a dead task and
                        # leak the watch registration).
                        wt.add_done_callback(
                            lambda t, wid=wid: session_watches.pop(wid, None)
                            if session_watches.get(wid) is t
                            else None
                        )
                    elif op == "watch_cancel":
                        wid = msg["id"]
                        task = session_watches.pop(wid, None)
                        if task:
                            task.cancel()
                        await st.watch_cancel(wid)
                        await send({"rid": rid, "ok": True})
                    elif op == "lease_grant":
                        lid = await st.lease_grant(msg.get("ttl", 10.0))
                        await send({"rid": rid, "ok": True, "lease": lid})
                    elif op == "lease_keepalive":
                        ok = await st.lease_keepalive(msg["lease"])
                        await send({"rid": rid, "ok": ok})
                    elif op == "lease_revoke":
                        await st.lease_revoke(msg["lease"])
                        await send({"rid": rid, "ok": True})
                    elif op == "publish":
                        n = await st.publish(msg["subject"], msg.get("payload"))
                        await send({"rid": rid, "ok": True, "delivered": n})
                    elif op == "subscribe":
                        sid, q = await st.subscribe(msg["pattern"])
                        await send({"rid": rid, "ok": True, "id": sid})
                        st_task = asyncio.create_task(pump_sub(sid, q))
                        session_subs[sid] = st_task
                        st_task.add_done_callback(
                            lambda t, sid=sid: session_subs.pop(sid, None)
                            if session_subs.get(sid) is t
                            else None
                        )
                    elif op == "unsubscribe":
                        sid = msg["id"]
                        task = session_subs.pop(sid, None)
                        if task:
                            task.cancel()
                        await st.unsubscribe(sid)
                        await send({"rid": rid, "ok": True})
                    elif op == "q_push":
                        await st.q_push(msg["queue"], msg.get("item"))
                        await send({"rid": rid, "ok": True})
                    elif op == "q_pop":
                        t = asyncio.create_task(do_pop(rid, msg["queue"]))
                        session_pop_tasks.add(t)
                        t.add_done_callback(session_pop_tasks.discard)
                    elif op == "q_ack":
                        session_unacked.discard(msg["token"])
                        await send({"rid": rid, "ok": await st.q_ack(msg["token"])})
                    elif op == "q_nack":
                        session_unacked.discard(msg["token"])
                        await send({"rid": rid, "ok": await st.q_nack(msg["token"])})
                    elif op == "q_len":
                        await send({"rid": rid, "ok": True, "len": await st.q_len(msg["queue"])})
                    elif op == "replica_attach":
                        # Warm-standby replication: hand over a consistent
                        # snapshot, then stream every durable mutation as
                        # an op-log push.  Snapshot + tap registration are
                        # one synchronous step on the loop, so no delta
                        # can fall between them.
                        oq: asyncio.Queue = asyncio.Queue()
                        tap = oq.put_nowait
                        snap = self.state.snapshot()
                        self.state.add_replication_tap(tap)
                        session_repl_taps.append(tap)
                        await send({"rid": rid, "ok": True, "snapshot": snap})
                        ot = asyncio.create_task(pump_oplog(oq))
                        session_repl_tasks.add(ot)
                        ot.add_done_callback(session_repl_tasks.discard)
                    elif op == "ping":
                        await send({"rid": rid, "ok": True})
                    else:
                        await send({"rid": rid, "ok": False, "error": f"unknown op {op}"})
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — protocol surface
                    await send({"rid": rid, "ok": False, "error": str(e)})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for tap in session_repl_taps:
                self.state.remove_replication_tap(tap)
            for task in list(session_watches.values()) + list(session_subs.values()):
                task.cancel()
            for task in list(session_repl_tasks):
                task.cancel()
            for task in session_pop_tasks:
                task.cancel()
            for wid in session_watches:
                await self.state.watch_cancel(wid)
            for sid in session_subs:
                await self.state.unsubscribe(sid)
            for token in list(session_unacked):
                await self.state.q_nack(token)
            writer.close()
            self._conn_tasks.discard(conn_task)


# --------------------------------------------------------------------------
# Warm standby (shard replication)
# --------------------------------------------------------------------------


class HubStandby:
    """Warm standby for one hub shard.

    Attaches to the primary's replication stream (``replica_attach``:
    snapshot handover, then one op-log push per durable mutation) and
    maintains a live copy of the primary's ``snapshot()`` — durable KV,
    queued + in-flight work, and the lease-id floor.  On primary death,
    ``promote()`` starts a fresh ``HubServer`` (by default on the dead
    primary's address) restored from that copy: clients observe exactly a
    hub restart — reconnect, re-arm watches with resync, leases re-grant —
    and the preserved floor guarantees the promoted shard never re-issues
    a lease id a pre-failover client still keeps alive.
    """

    def __init__(self, primary_address: str):
        self.primary_address = primary_address
        self._kv: Dict[str, Any] = {}
        self._queues: Dict[str, List[Any]] = {}
        self._inflight: List[List[Any]] = []  # [queue, item] pairs
        self._lease_floor = 1
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._task: Optional[asyncio.Task] = None
        # Set when the replication stream dies (primary gone) or on close.
        self.primary_lost = asyncio.Event()
        self.ops_applied = 0

    async def start(self) -> "HubStandby":
        host, port = self.primary_address.rsplit(":", 1)
        self._reader, self._writer = await asyncio.open_connection(
            host, int(port)
        )
        self._writer.write(
            json.dumps({"rid": 1, "op": "replica_attach"}).encode() + b"\n"
        )
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise ConnectionError(
                f"hub {self.primary_address} closed during replica_attach"
            )
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(f"replica_attach refused: {resp!r}")
        self._load_snapshot(resp.get("snapshot") or {})
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    def _load_snapshot(self, snap: Dict[str, Any]) -> None:
        self._kv = dict(snap.get("kv") or {})
        self._queues = {
            name: list(items)
            for name, items in (snap.get("queues") or {}).items()
        }
        self._inflight = [list(e) for e in (snap.get("inflight") or ())]
        try:
            self._lease_floor = int(snap.get("lease_floor", 1))
        except (TypeError, ValueError):
            self._lease_floor = 1

    async def _run(self) -> None:
        assert self._reader is not None
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                msg = json.loads(line)
                if msg.get("push") == "oplog":
                    self._apply(msg.get("entry") or {})
        except asyncio.CancelledError:
            raise
        except (ConnectionResetError, OSError, json.JSONDecodeError):
            pass
        finally:
            self.primary_lost.set()

    def _apply(self, entry: Dict[str, Any]) -> None:
        op = entry.get("op")
        if op == "kv_put":
            self._kv[entry["key"]] = entry.get("value")
        elif op == "kv_delete":
            self._kv.pop(entry["key"], None)
        elif op == "lease_floor":
            try:
                self._lease_floor = max(self._lease_floor, int(entry["floor"]))
            except (KeyError, TypeError, ValueError):
                pass
        elif op == "q_add":
            if entry.get("where") == "inflight":
                self._inflight.append([entry["queue"], entry.get("item")])
            else:
                self._queues.setdefault(entry["queue"], []).append(
                    entry.get("item")
                )
        elif op == "q_take":
            items = self._queues.get(entry["queue"])
            item = entry.get("item")
            if items and item in items:
                items.remove(item)
            self._inflight.append([entry["queue"], item])
        elif op == "q_settle":
            pair = [entry["queue"], entry.get("item")]
            if pair in self._inflight:
                self._inflight.remove(pair)
        self.ops_applied += 1

    def snapshot(self) -> Dict[str, Any]:
        """The shadow state in ``HubState.snapshot()`` schema."""
        return {
            "kv": dict(self._kv),
            "queues": {
                name: list(items)
                for name, items in self._queues.items()
                if items
            },
            "inflight": [list(e) for e in self._inflight],
            "lease_floor": self._lease_floor,
        }

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    async def promote(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        persist_path: Optional[str] = None,
        persist_interval_s: float = 2.0,
    ) -> "HubServer":
        """Take over the shard: start a HubServer restored from the shadow
        state — on the dead primary's address unless told otherwise."""
        await self.close()
        p_host, p_port = self.primary_address.rsplit(":", 1)
        server = HubServer(
            host=host or p_host,
            port=int(port if port is not None else p_port),
            persist_path=persist_path,
            persist_interval_s=persist_interval_s,
        )
        server.state.restore(self.snapshot())
        await server.start()
        return server


# --------------------------------------------------------------------------
# TCP client
# --------------------------------------------------------------------------


class _SubSession:
    """A live client-side subscription: survives reconnects (the server-side
    sid is rebound; the local queue and its consumer never change)."""

    __slots__ = ("sid", "pattern", "queue")

    def __init__(self, sid: str, pattern: str, queue: asyncio.Queue):
        self.sid = sid
        self.pattern = pattern
        self.queue = queue


class _ParkedEntry:
    """One request parked on a down hub connection, with the bookkeeping
    the park-buffer cap needs to shed oldest-idempotent-first."""

    __slots__ = ("op", "size", "idempotent", "fut")

    def __init__(self, op: str, size: int, idempotent: bool,
                 fut: asyncio.Future):
        self.op = op
        self.size = size
        self.idempotent = idempotent
        self.fut = fut


class HubClient:
    """Asyncio client for HubServer; same interface as InprocHub.

    Leases granted through this client are kept alive automatically by a
    background task (ttl/3 cadence) until ``lease_revoke``/``close`` — the
    reference's etcd lease keep-alive loop (transports/etcd/lease.rs:51).

    Session resume (hub restart survival): a lost connection enters a
    full-jitter backoff reconnect loop instead of bricking the client.
    While down, ``_request`` parks callers for up to ``request_grace_s``
    (a hub crash pauses the fleet rather than killing it); on reconnect:

    - **subscriptions** re-arm transparently — the event plane is lossy by
      contract, so the same local queue is re-bound to a fresh server-side
      subscription and consumers never notice;
    - **watches** CANNOT resume transparently (deltas were missed and the
      snapshot-then-delta contract would be silently broken), so each live
      watcher raises ``HubSessionLost`` — every consumer in the tree
      already owns a re-arm+resync recovery path for exactly this;
    - **unacked queue items** are counted as requeued (the server's
      disconnect/restart handling re-enqueues them; at-least-once holds)
      and the ack tokens dropped.
    """

    RECONNECT_BACKOFF_INITIAL = 0.05
    # Park-buffer caps: a long outage must pause the fleet, not grow client
    # memory without bound.  When either cap is hit, the OLDEST IDEMPOTENT
    # parked request is shed with a ConnectionError (idempotent callers
    # already own retry paths; queue verbs are shed only as a last resort)
    # and counted on /metrics (hub_shard_parked_shed_total).
    PARK_MAX_REQUESTS = 512
    PARK_MAX_BYTES = 4 << 20

    def __init__(
        self,
        address: str,
        reconnect: bool = True,
        reconnect_max_s: float = 2.0,
        request_grace_s: float = 10.0,
    ):
        self.address = address
        self.reconnect = reconnect
        self.reconnect_max_s = reconnect_max_s
        self.request_grace_s = request_grace_s
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_queues: Dict[str, asyncio.Queue] = {}
        self._sub_queues: Dict[str, asyncio.Queue] = {}
        # sid → live subscription session (pattern + queue); reconnect
        # re-arms these server-side and rebinds the NEW sid to the session.
        self._sub_sessions: Dict[str, _SubSession] = {}
        # unacked q_pop tokens held by this client (requeued on conn loss)
        self._unacked: Set[str] = set()
        # pushes that arrive before the requesting coroutine registers its
        # queue (read_loop may outrun watch_prefix/subscribe resumption)
        self._early_pushes: Dict[str, List[Any]] = {}
        # ids whose watch/subscription was closed: drop late pushes instead of
        # buffering them forever
        self._closed_push_ids: set = set()
        self._reader_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}
        self._write_lock = asyncio.Lock()
        self._connected = asyncio.Event()
        self._connected_at = 0.0
        self._closed = False
        # Bounded park buffer: park id → entry (insertion-ordered).
        self._parked: Dict[int, _ParkedEntry] = {}
        self._park_ids = itertools.count(1)
        self._park_bytes = 0

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    async def connect(self) -> "HubClient":
        host, port = self.address.rsplit(":", 1)
        self._reader, self._writer = await asyncio.open_connection(host, int(port))
        self._reader_task = asyncio.create_task(self._read_loop())
        self._connected.set()
        self._connected_at = time.monotonic()
        return self

    async def close(self) -> None:
        self._closed = True
        # Wake requests parked on the reconnect: they re-check _closed and
        # fail fast instead of sleeping out the grace budget.
        self._connected.set()
        for t in self._keepalive_tasks.values():
            t.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._writer:
            self._writer.close()
        for q in self._watch_queues.values():
            q.put_nowait(None)
        for q in self._sub_queues.values():
            q.put_nowait(None)

    async def _read_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                msg = json.loads(line)
                push = msg.get("push")
                if push == "watch":
                    item = WatchEvent(msg["type"], msg["key"], msg.get("value"))
                    q = self._watch_queues.get(msg["id"])
                    if q:
                        q.put_nowait(item)
                    elif msg["id"] not in self._closed_push_ids:
                        self._early_pushes.setdefault(msg["id"], []).append(item)
                elif push == "msg":
                    item = (msg["subject"], msg.get("payload"))
                    q = self._sub_queues.get(msg["id"])
                    if q:
                        q.put_nowait(item)
                    elif msg["id"] not in self._closed_push_ids:
                        self._early_pushes.setdefault(msg["id"], []).append(item)
                else:
                    fut = self._pending.pop(msg.get("rid"), None)
                    if fut and not fut.done():
                        fut.set_result(msg)
        except asyncio.CancelledError:
            pass
        except (ConnectionResetError, OSError, json.JSONDecodeError):
            pass
        finally:
            self._connected.clear()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("hub connection lost"))
            self._pending.clear()
            if not self._closed:
                self._on_connection_lost()

    def _on_connection_lost(self) -> None:
        """Connection died under us: account requeues, error live watches,
        and (when enabled) start the backoff reconnect loop."""
        from ..resilience import metrics

        if self._unacked:
            # The server requeues a disconnected session's unacked items
            # (and a restarted hub restores in-flight items from its
            # snapshot) — from this client's view they are requeued work.
            metrics.hub_requeued_items_total += len(self._unacked)
            self._unacked.clear()
        # Live watches are broken by contract (missed deltas): surface
        # HubSessionLost to their consumers, who re-arm + resync.
        for wid, q in list(self._watch_queues.items()):
            self._closed_push_ids.add(wid)
            q.put_nowait(_WATCH_LOST)
        self._watch_queues.clear()
        # Drop the dead server-side sub ids from push routing; the sessions
        # themselves survive and are re-bound after reconnect.
        for sid in list(self._sub_queues):
            self._sub_queues.pop(sid, None)
        if self.reconnect:
            # A connection that died young means the hub is accepting and
            # immediately dropping (mid-restart, outage fault): start the
            # backoff ladder higher so the retry loop doesn't spin.
            uptime = time.monotonic() - self._connected_at
            initial = (
                self.RECONNECT_BACKOFF_INITIAL
                if uptime >= 1.0
                else min(0.5, self.reconnect_max_s)
            )
            self._reconnect_task = asyncio.get_running_loop().create_task(
                self._reconnect_loop(initial)
            )

    async def _reconnect_loop(self, backoff: float) -> None:
        from ..resilience import metrics

        try:
            while not self._closed:
                # Full jitter BEFORE each dial: a fleet of clients orphaned
                # by one hub crash must not re-dial in lockstep.
                await asyncio.sleep(random.uniform(0.0, backoff))
                if self._closed:
                    return
                try:
                    host, port = self.address.rsplit(":", 1)
                    self._reader, self._writer = await asyncio.open_connection(
                        host, int(port)
                    )
                    break
                except OSError:
                    backoff = min(max(backoff, 0.05) * 2, self.reconnect_max_s)
            if self._closed:
                if self._writer is not None:
                    self._writer.close()
                return
            self._reader_task = asyncio.create_task(self._read_loop())
            self._connected.set()
            self._connected_at = time.monotonic()
            metrics.hub_reconnects_total += 1
            from .shard import shard_metrics
            shard_metrics.note_reconnect(self.address)
            logger.info("hub connection to %s re-established", self.address)
            # Re-arm subscriptions onto their existing local queues: the
            # pub/sub plane is lossy by contract, so consumers keep their
            # iterators and never observe the gap.
            for old_sid, sess in list(self._sub_sessions.items()):
                self._sub_sessions.pop(old_sid, None)
                try:
                    resp = await self._request("subscribe", pattern=sess.pattern)
                except (ConnectionError, RuntimeError):
                    # Hub flapped again mid-resume: the fresh read_loop's
                    # death restarts this whole loop; re-register the
                    # session so the next pass retries it.
                    self._sub_sessions[old_sid] = sess
                    continue
                new_sid = resp["id"]
                sess.sid = new_sid
                for item in self._early_pushes.pop(new_sid, []):
                    sess.queue.put_nowait(item)
                self._sub_queues[new_sid] = sess.queue
                self._sub_sessions[new_sid] = sess
                metrics.hub_sessions_resumed_total += 1
        except asyncio.CancelledError:
            raise

    # Ops safe to replay across a reconnect: a lost response cannot make a
    # replay observable (KV puts/gets/deletes are last-write-wins; a
    # half-registered watch/sub dies with its connection; an orphaned
    # lease_grant expires unkept; publish dupes are within the lossy-plane
    # contract).  Queue verbs are EXCLUDED — q_push would duplicate work
    # items beyond the at-least-once redelivery contract, and pop/ack
    # tokens are connection-scoped.
    _IDEMPOTENT_OPS = frozenset({
        "kv_put", "kv_get", "kv_get_prefix", "kv_delete", "lease_keepalive",
        "lease_grant", "lease_revoke", "q_len", "ping", "watch",
        "watch_cancel", "subscribe", "unsubscribe", "publish",
    })

    def _shed_parked(self, incoming_size: int) -> None:
        """Enforce the park-buffer caps before parking another request:
        shed the oldest idempotent parked entry (then oldest of any kind)
        until the incoming one fits."""
        from .shard import shard_metrics

        while self._parked and (
            len(self._parked) + 1 > self.PARK_MAX_REQUESTS
            or self._park_bytes + incoming_size > self.PARK_MAX_BYTES
        ):
            victim_id = None
            for pid, entry in self._parked.items():
                if entry.idempotent:
                    victim_id = pid
                    break
            if victim_id is None:
                victim_id = next(iter(self._parked))
            entry = self._parked.pop(victim_id)
            self._park_bytes -= entry.size
            if not entry.fut.done():
                entry.fut.set_exception(ConnectionError(
                    f"parked {entry.op} shed: hub {self.address} park "
                    f"buffer over cap ({self.PARK_MAX_REQUESTS} requests / "
                    f"{self.PARK_MAX_BYTES} bytes)"
                ))
            shard_metrics.note_shed(self.address)

    async def _park(self, op: str, size: int, budget: float) -> None:
        """Park one request until the reconnect loop restores the
        connection.  Raises ConnectionError if the park-buffer cap sheds
        this entry, TimeoutError when the budget runs out first."""
        from .shard import shard_metrics

        self._shed_parked(size)
        pid = next(self._park_ids)
        entry = _ParkedEntry(
            op=op,
            size=size,
            idempotent=op in self._IDEMPOTENT_OPS,
            fut=asyncio.get_running_loop().create_future(),
        )
        self._parked[pid] = entry
        self._park_bytes += size
        shard_metrics.note_parked(self.address)
        wait_task = asyncio.ensure_future(self._connected.wait())
        try:
            done, _ = await asyncio.wait(
                {wait_task, entry.fut},
                timeout=budget,
                return_when=asyncio.FIRST_COMPLETED,
            )
            if entry.fut in done:
                entry.fut.result()  # raises the shed ConnectionError
            if not done:
                raise asyncio.TimeoutError
        finally:
            wait_task.cancel()
            if not entry.fut.done():
                entry.fut.cancel()
            if self._parked.pop(pid, None) is not None:
                self._park_bytes -= entry.size

    async def _request(self, op: str, **kw) -> Dict[str, Any]:
        from .shard import shard_metrics

        retryable = self.reconnect and op in self._IDEMPOTENT_OPS
        deadline = time.monotonic() + self.request_grace_s
        last_exc: Optional[BaseException] = None
        first = True
        park_size = -1  # serialized lazily, only if this request parks
        replaying = False
        while first or (retryable and time.monotonic() < deadline):
            first = False
            if self._closed:
                raise ConnectionError("hub client closed")
            if self._writer is None:
                raise ConnectionError("not connected")
            if not self._connected.is_set():
                if not self.reconnect:
                    # No reconnect loop will ever set the event again —
                    # parking would just sleep out the grace for nothing.
                    raise ConnectionError("hub connection lost")
                # Hub down, reconnect in progress: park the caller so a hub
                # restart pauses traffic instead of failing it — within the
                # bounded park buffer.
                budget = deadline - time.monotonic()
                if budget <= 0:
                    break
                if park_size < 0:
                    park_size = len(json.dumps({"op": op, **kw}, default=str))
                try:
                    await self._park(op, park_size, budget)
                except asyncio.TimeoutError:
                    break
            if replaying:
                shard_metrics.note_replayed(self.address)
                replaying = False
            rid = next(self._rids)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[rid] = fut
            payload = {"rid": rid, "op": op, **kw}
            try:
                async with self._write_lock:
                    self._writer.write(json.dumps(payload).encode() + b"\n")
                    await self._writer.drain()
                msg = await fut
            except (ConnectionError, ConnectionResetError, BrokenPipeError,
                    OSError) as e:
                # Connection died under this request: idempotent ops keep
                # replaying until the grace budget runs out (a flapping hub
                # accepts and drops several times mid-restart); the rest
                # surface immediately.
                self._pending.pop(rid, None)
                last_exc = e
                if retryable:
                    replaying = True
                    await asyncio.sleep(random.uniform(0.02, 0.1))
                continue
            if not msg.get("ok") and op not in (
                "lease_keepalive", "q_ack", "q_nack"
            ):
                raise RuntimeError(msg.get("error", f"{op} failed"))
            return msg
        if isinstance(last_exc, ConnectionError):
            raise last_exc
        if last_exc is not None:
            raise ConnectionError(f"hub request failed: {last_exc}") from last_exc
        raise ConnectionError(
            f"hub {self.address} unreachable "
            f"(reconnect pending > {self.request_grace_s:g}s)"
        )

    # KV
    async def kv_put(self, key, value, lease_id=None):
        await self._request("kv_put", key=key, value=value, lease=lease_id)

    async def kv_get(self, key):
        return (await self._request("kv_get", key=key)).get("value")

    async def kv_get_prefix(self, prefix):
        return (await self._request("kv_get_prefix", prefix=prefix)).get("kvs", {})

    async def kv_delete(self, key):
        return (await self._request("kv_delete", key=key)).get("deleted", False)

    async def watch_prefix(self, prefix) -> Watcher:
        resp = await self._request("watch", prefix=prefix)
        wid = resp["id"]
        q: asyncio.Queue = asyncio.Queue()
        for item in self._early_pushes.pop(wid, []):
            q.put_nowait(item)
        self._watch_queues[wid] = q

        async def cancel():
            self._watch_queues.pop(wid, None)
            self._early_pushes.pop(wid, None)
            self._closed_push_ids.add(wid)
            if not self._closed:
                try:
                    await self._request("watch_cancel", id=wid)
                except (ConnectionError, RuntimeError):
                    pass
            q.put_nowait(None)

        return Watcher(q, cancel)

    # leases
    async def lease_grant(self, ttl: float = 10.0) -> int:
        lid = (await self._request("lease_grant", ttl=ttl))["lease"]
        self._keepalive_tasks[lid] = asyncio.create_task(self._keepalive_loop(lid, ttl))
        return lid

    async def _keepalive_loop(self, lease_id: int, ttl: float) -> None:
        try:
            while True:
                await asyncio.sleep(max(ttl / 3.0, 0.05))
                try:
                    ok = (
                        await self._request("lease_keepalive", lease=lease_id)
                    ).get("ok")
                except ConnectionError:
                    # Hub down/reconnecting: keep trying — a SHORT outage
                    # (connection blip, not a restart) leaves the lease
                    # alive server-side, and abandoning it here would
                    # deregister a perfectly healthy worker.
                    continue
                if not ok:
                    return  # lease truly gone; the owner re-grants
        except asyncio.CancelledError:
            pass

    async def lease_keepalive(self, lease_id: int) -> bool:
        return (await self._request("lease_keepalive", lease=lease_id)).get("ok", False)

    async def lease_revoke(self, lease_id: int) -> None:
        task = self._keepalive_tasks.pop(lease_id, None)
        if task:
            task.cancel()
        await self._request("lease_revoke", lease=lease_id)

    # pub/sub
    async def publish(self, subject, payload) -> None:
        from .shard import shard_metrics

        shard_metrics.note_publish(self.address, _payload_nbytes(payload))
        await self._request("publish", subject=subject, payload=payload)

    async def subscribe(self, pattern) -> Subscription:
        resp = await self._request("subscribe", pattern=pattern)
        sess = _SubSession(resp["id"], pattern, asyncio.Queue())
        for item in self._early_pushes.pop(sess.sid, []):
            sess.queue.put_nowait(item)
        self._sub_queues[sess.sid] = sess.queue
        self._sub_sessions[sess.sid] = sess

        async def cancel():
            # The session's sid moves on reconnect: always read it live.
            sid = sess.sid
            self._sub_queues.pop(sid, None)
            self._sub_sessions.pop(sid, None)
            self._early_pushes.pop(sid, None)
            self._closed_push_ids.add(sid)
            if not self._closed:
                try:
                    await self._request("unsubscribe", id=sid)
                except (ConnectionError, RuntimeError):
                    pass
            sess.queue.put_nowait(None)

        return Subscription(sess.queue, cancel)

    # queues
    async def q_push(self, queue, item) -> None:
        await self._request("q_push", queue=queue, item=item)

    async def q_pop(self, queue) -> Tuple[Any, str]:
        resp = await self._request("q_pop", queue=queue)
        self._unacked.add(resp["token"])
        return resp["item"], resp["token"]

    async def q_ack(self, token) -> bool:
        self._unacked.discard(token)
        return (await self._request("q_ack", token=token)).get("ok", False)

    async def q_nack(self, token) -> bool:
        self._unacked.discard(token)
        return (await self._request("q_nack", token=token)).get("ok", False)

    async def q_len(self, queue) -> int:
        return (await self._request("q_len", queue=queue))["len"]
