"""Layered runtime configuration: defaults < config file < environment.

Reference semantics: lib/runtime/src/config.rs:58-115 — a figment of
``RuntimeConfig::default()``, then an optional TOML/JSON file named by
``DYN_RUNTIME_CONFIG``, then ``DYN_*`` environment variables, later layers
winning per key.  Same precedence here with YAML/JSON files.

Env mapping: ``DYN_<FIELD>`` (case-insensitive) sets a top-level field;
double underscores nest (``DYN_HTTP__PORT=8080`` → ``http.port``).  Values
parse as JSON when possible ("8080" → int, "true" → bool), else string.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Mapping, Optional

ENV_PREFIX = "DYN_"
CONFIG_PATH_ENV = "DYN_RUNTIME_CONFIG"


def _parse_env_value(raw: str) -> Any:
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return raw


def _deep_merge(base: Dict[str, Any], over: Mapping[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, Mapping) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _load_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(text) or {}
    return json.loads(text or "{}")


def env_overrides(
    environ: Optional[Mapping[str, str]] = None, prefix: str = ENV_PREFIX
) -> Dict[str, Any]:
    """``DYN_A__B=v`` → {"a": {"b": v}} (reserved names excluded)."""
    environ = os.environ if environ is None else environ
    reserved = {CONFIG_PATH_ENV, "DYN_LOG", "DYN_LOG_FORMAT", "DYN_LOG_FILE"}
    out: Dict[str, Any] = {}
    for key, raw in environ.items():
        if not key.startswith(prefix) or key in reserved:
            continue
        path = key[len(prefix):].lower().split("__")
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = _parse_env_value(raw)
    return out


@dataclass
class RuntimeConfig:
    """The runtime's own knobs (reference RuntimeConfig: worker threads →
    here event-loop/debug toggles, grace periods, endpoint health)."""

    namespace: str = "dynamo"
    hub: Optional[str] = None  # host:port of the discovery hub
    # graceful shutdown (reference: graceful_shutdown_timeout)
    shutdown_timeout_s: float = 30.0
    kill_timeout_s: float = 5.0
    # service plane
    host: str = "0.0.0.0"
    http_port: int = 8000
    metrics_port: int = 9091
    # engine defaults (overridable per worker)
    engine: Dict[str, Any] = field(default_factory=dict)
    # request-resilience knobs (runtime/resilience.py): retry_max_attempts,
    # retry_base_delay_s, retry_max_delay_s, breaker_failure_threshold,
    # breaker_reset_s, http_max_inflight, http_admission_queue,
    # http_admission_timeout_s, request_deadline_s.  Nested env works:
    # ``DYN_RESILIENCE__RETRY_MAX_ATTEMPTS=5``.
    resilience: Dict[str, Any] = field(default_factory=dict)
    # SLA planner section (planner/policy.py): SLO targets (ttft_p95_ms,
    # itl_p95_ms, kv_headroom) + policy bounds (min/max_prefill,
    # min/max_decode, band_up/band_down, confirm/cooldown ticks).  Nested
    # env works: ``DYN_PLANNER__TTFT_P95_MS=1500``.
    planner: Dict[str, Any] = field(default_factory=dict)
    # Draft-free speculative decoding defaults (engine/config.py
    # SpecDecodeConfig keys: enable, ngram_min, ngram_max, k, k_min,
    # ewma_alpha, accept_floor, cooldown_tokens).  The CLI engine builder
    # merges this section under any explicit --spec-* flags; nested env
    # works: ``DYN_SPEC_DECODE__ENABLE=true``, ``DYN_SPEC_DECODE__K=8``.
    spec_decode: Dict[str, Any] = field(default_factory=dict)
    # Batched multi-LoRA defaults (engine/config.py LoraConfig keys:
    # enable, max_adapters, rank, promote_timeout_s) plus an optional
    # ``adapters`` map {name: path-or-repo-or-"random[:seed]"} loaded at
    # engine start.  CLI --lora* flags win per key; nested env works:
    # ``DYN_LORA__ENABLE=true``, ``DYN_LORA__MAX_ADAPTERS=8``.
    lora: Dict[str, Any] = field(default_factory=dict)
    # QoS/overload-control section (llm/qos.py QosConfig keys at the edge:
    # rate, burst, tenants, brownout{queue_high,kv_high,ttft_p95_ms,
    # band_up,band_down,confirm_up,confirm_down,cooldown,max_tokens_cap},
    # tick_s; engine/config.py QosSchedConfig keys for the scheduler:
    # tenant_weights, default_weight, batch_every).  Nested env works:
    # ``DYN_QOS__RATE=20``, ``DYN_QOS__BROWNOUT__QUEUE_HIGH=32``.
    qos: Dict[str, Any] = field(default_factory=dict)
    # Distributed request tracing (runtime/tracing.py TracingConfig keys:
    # enabled, sample, ring, export_interval_s, ttl_s, tail_keep,
    # tail_slo_ttft_ms).  Nested env works: ``DYN_TRACING__SAMPLE=0.1``,
    # ``DYN_TRACING__TAIL_SLO_TTFT_MS=1500``.
    tracing: Dict[str, Any] = field(default_factory=dict)
    extra: Dict[str, Any] = field(default_factory=dict)  # unrecognized keys

    @classmethod
    def from_layers(
        cls,
        file_path: Optional[str] = None,
        environ: Optional[Mapping[str, str]] = None,
    ) -> "RuntimeConfig":
        """defaults < file (arg or $DYN_RUNTIME_CONFIG) < DYN_* env."""
        environ = os.environ if environ is None else environ
        merged: Dict[str, Any] = {}
        path = file_path or environ.get(CONFIG_PATH_ENV)
        if path:
            merged = _deep_merge(merged, _load_file(path))
        merged = _deep_merge(merged, env_overrides(environ))
        known = {f for f in cls.__dataclass_fields__ if f != "extra"}
        kwargs = {k: v for k, v in merged.items() if k in known}
        extra = {k: v for k, v in merged.items() if k not in known}
        cfg = cls(**kwargs)
        cfg.extra = extra
        return cfg
